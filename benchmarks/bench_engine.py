"""Vectorized Go engine throughput (board steps/s).

The rebuild's analogue of the reference's Cython-engine motivation
(SURVEY.md §2a): random-legal-move games stepped in lockstep under one
jit — the raw rules-kernel speed with no NN in the loop. Compare with
Pgx's O(10⁴–10⁶) steps/s/device (SURVEY.md §6).
"""

from __future__ import annotations

import functools
import sys

sys.path.insert(0, ".")
from benchmarks._harness import report, std_parser, timed  # noqa: E402


def main() -> None:
    import jax
    import jax.numpy as jnp

    from rocalphago_tpu.engine.jaxgo import (
        GoConfig,
        legal_mask,
        new_states,
        step,
    )

    ap = std_parser(__doc__)
    ap.add_argument("--moves", type=int, default=128)
    args = ap.parse_args()
    batch = args.batch or (1024 if jax.devices()[0].platform == "tpu"
                           else 64)
    cfg = GoConfig(size=args.board)
    vstep = jax.vmap(functools.partial(step, cfg))
    vlegal = jax.vmap(functools.partial(legal_mask, cfg))

    @jax.jit
    def run(rng):
        states = new_states(cfg, batch)

        def ply(carry, _):
            states, rng = carry
            rng, sub = jax.random.split(rng)
            legal = vlegal(states)[:, :-1]
            logits = jnp.where(legal, 0.0, -1e30)
            action = jnp.where(
                legal.any(-1),
                jax.random.categorical(sub, logits, axis=-1),
                cfg.num_points).astype(jnp.int32)
            return (vstep(states, action), rng), None

        (states, _), _ = jax.lax.scan(ply, (states, rng),
                                      length=args.moves)
        return states.step_count

    key = [jax.random.key(0)]

    def once():
        key[0], sub = jax.random.split(key[0])
        return jax.device_get(run(sub))

    dt = timed(once, reps=args.reps, profile_dir=args.profile)
    report("engine_steps", batch * args.moves / dt, "steps/s",
           batch=batch, board=args.board)


if __name__ == "__main__":
    main()
