"""Vectorized Go engine throughput (board steps/s).

The rebuild's analogue of the reference's Cython-engine motivation
(SURVEY.md §2a): random-legal-move games stepped in lockstep under one
jit — the raw rules-kernel speed with no NN in the loop. Compare with
Pgx's O(10⁴–10⁶) steps/s/device (SURVEY.md §6).
"""

from __future__ import annotations

import sys

sys.path.insert(0, ".")
from benchmarks._harness import (  # noqa: E402
    random_game_states,
    report,
    std_parser,
    timed,
)


def main() -> None:
    import jax

    from rocalphago_tpu.engine.jaxgo import GoConfig

    ap = std_parser(__doc__)
    ap.add_argument("--moves", type=int, default=128)
    args = ap.parse_args()
    batch = args.batch or (1024 if jax.devices()[0].platform == "tpu"
                           else 64)
    cfg = GoConfig(size=args.board)
    key = [jax.random.key(0)]

    def once():
        key[0], sub = jax.random.split(key[0])
        states = random_game_states(cfg, batch, args.moves, sub)
        return jax.device_get(states.step_count)

    from rocalphago_tpu.engine.jaxgo import _dense_engine

    dt = timed(once, reps=args.reps, profile_dir=args.profile)
    report("engine_steps", batch * args.moves / dt, "steps/s",
           batch=batch, board=args.board,
           formulation="dense" if _dense_engine() else "scatter")


if __name__ == "__main__":
    main()
