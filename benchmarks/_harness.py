"""Shared micro-benchmark harness.

Parity: the reference's ``benchmarks/`` cProfile scripts (SURVEY.md §2
"Benchmarks", §5 "Tracing / profiling"). Here each script times a
jitted program with compile excluded and prints one JSON line, the
same shape as the repo-root ``bench.py``; pass ``--profile DIR`` to
any script to additionally capture a ``jax.profiler`` trace viewable
in TensorBoard/Perfetto.
"""

from __future__ import annotations

import argparse
import json
import os
import time

import jax

def enable_compile_cache() -> None:
    """Persistent XLA compile cache via the SHARED runtime helper
    (``runtime.compilecache`` — the same knob every CLI entry point
    now runs; ``ROCALPHAGO_COMPILE_CACHE`` overrides/disables): cost-
    analysis AOT compiles and the jit dispatch path then share one
    compile per program instead of paying the 20-40s TPU compile
    twice. Called from :func:`std_parser` (i.e. benchmark entry
    points only) — NOT at import time, because the test suite imports
    this module for :func:`harvest_chase_lanes` and must keep its own
    cache configuration (the helper's first-config-wins rule also
    protects that case)."""
    from rocalphago_tpu.runtime.compilecache import (
        enable_compile_cache as _enable,
    )

    _enable()


# bf16 peak FLOP/s per chip by TPU generation (public spec sheets);
# used for MFU = achieved flops/s ÷ peak. The attached tunnel is v5e.
_TPU_BF16_PEAK = {"v5e": 197e12, "v5litepod": 197e12,
                  "v4": 275e12, "v5p": 459e12, "v6e": 918e12}


def bf16_peak_flops() -> float | None:
    """Peak bf16 FLOP/s of the attached chip, or None off-TPU (an MFU
    against a host CPU "peak" would be meaningless).

    Generation detection: ``$PALLAS_AXON_TPU_GEN`` if set, else the
    device_kind string with spaces/dashes stripped so JAX's spellings
    ("TPU v5 lite", "TPU v5p", "TPU v6 lite") match the generation
    keys. Order matters: the more specific "v5p"/"v5lite" patterns are
    tested before bare "v5"."""
    if jax.devices()[0].platform != "tpu":
        return None
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    compact = (gen or jax.devices()[0].device_kind.lower()) \
        .replace(" ", "").replace("-", "")
    for keys, peak in (
            (("v5e", "v5lite"), _TPU_BF16_PEAK["v5e"]),
            (("v6e", "v6lite"), _TPU_BF16_PEAK["v6e"]),
            (("v5p", "v5"), _TPU_BF16_PEAK["v5p"]),
            (("v4",), _TPU_BF16_PEAK["v4"])):
        if any(k in compact for k in keys):
            return peak
    return _TPU_BF16_PEAK["v5e"]   # attached tunnel default


def program_flops(jitted_fn, *args, **kwargs) -> float | None:
    """FLOPs XLA's cost analysis attributes to one call of the jitted
    program (``lower().compile().cost_analysis()["flops"]``) — the
    numerator of every MFU line in BENCH_RESULTS.md. None when the
    backend doesn't report it.

    SPMD note: for a program sharded over n devices this is the
    PER-DEVICE module's flops. ``mfu(flops / dt)`` is therefore the
    per-chip utilization as-is, but per-item normalizations must use
    the per-device item count (global batch ÷ n devices)."""
    try:
        analysis = jitted_fn.lower(*args, **kwargs).compile() \
            .cost_analysis()
        if isinstance(analysis, (list, tuple)):   # older jax returns
            analysis = analysis[0]                # one dict per device
        flops = float(analysis.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:  # noqa: BLE001 — cost analysis is best-effort
        return None


def mfu(flops_per_sec: float | None) -> float | None:
    """Model FLOPs utilization vs the chip's bf16 peak (None off-TPU
    or when flops are unknown)."""
    peak = bf16_peak_flops()
    if peak is None or not flops_per_sec:
        return None
    return flops_per_sec / peak


def std_parser(description: str) -> argparse.ArgumentParser:
    enable_compile_cache()
    ap = argparse.ArgumentParser(description=description)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--board", type=int, default=19)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="write a jax.profiler trace to DIR")
    return ap


def timed(fn, reps: int = 3, profile_dir: str | None = None) -> float:
    """Seconds per call of ``fn`` (first call = warmup/compile,
    excluded). ``fn`` must force completion itself (return
    ``jax.device_get`` of something)."""
    fn()
    if profile_dir:
        jax.profiler.start_trace(profile_dir)
    t0 = time.time()
    for _ in range(reps):
        fn()
    dt = (time.time() - t0) / reps
    if profile_dir:
        jax.profiler.stop_trace()
    return dt


def report(metric: str, value: float, unit: str,
           baseline: float | None = None, **extra) -> None:
    """Print the one-line JSON result AND append it (with platform +
    timestamp) to the machine-readable log ``benchmarks/results.jsonl``
    (override with ``$ROCALPHAGO_BENCH_LOG``; empty disables) so perf
    history is greppable instead of living only in BENCH_RESULTS.md
    prose (VERDICT r2 weak #3)."""
    line = {"metric": metric, "value": round(value, 2), "unit": unit}
    if baseline is not None:
        line["vs_baseline"] = round(value / max(baseline, 1e-12), 3)
    line.update(extra)
    print(json.dumps(line))

    log = os.environ.get(
        "ROCALPHAGO_BENCH_LOG",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "benchmarks", "results.jsonl"))
    if not log:
        return
    try:
        rec = dict(line, platform=jax.devices()[0].platform,
                   date=time.strftime("%Y-%m-%dT%H:%M:%S"))
        with open(log, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except Exception:  # noqa: BLE001 — logging must never fail a bench
        pass


def harvest_chase_lanes(size: int, lanes: int | None, seed: int,
                        moves_lo: int = 8, moves_hi: int = 120,
                        positions: int | None = None):
    """Valid ladder-chase entries from random games: every 2-liberty
    group is a chase entry (chaser to move). Returns
    ``(boards [L,N] int8, labels [L,N] int32, prey_roots [L] int32)``
    numpy arrays. Shared by ``benchmarks/bench_chase.py`` and
    ``tests/test_ops.py`` so both always exercise the exact entry
    contract the ladder planes hand to the chase (board + carried
    min-root labeling + prey root). Stop either at ``lanes`` total
    lanes or after ``positions`` random positions."""
    import jax.numpy as jnp
    import numpy as np

    from rocalphago_tpu.engine import pygo
    from rocalphago_tpu.engine.jaxgo import (
        GoConfig,
        compute_labels,
        lib_counts_from_labels,
    )

    if lanes is None and positions is None:
        raise ValueError("pass lanes and/or positions — with neither "
                         "bound the harvest would loop forever")
    cfg = GoConfig(size=size)
    rng = np.random.default_rng(seed)
    boards, labels, preys = [], [], []
    pos = 0
    while (lanes is None or len(preys) < lanes) and (
            positions is None or pos < positions):
        pos += 1
        st = pygo.GameState(size=size, komi=7.5)
        for _ in range(int(rng.integers(moves_lo, moves_hi))):
            legal = st.get_legal_moves(include_eyes=False)
            if not legal or st.is_end_of_game:
                break
            st.do_move(legal[rng.integers(len(legal))])
        flat = np.asarray(st.board, np.int8).reshape(-1)
        lab = np.asarray(compute_labels(cfg, jnp.asarray(flat)))
        libs = np.asarray(lib_counts_from_labels(
            cfg, jnp.asarray(flat), jnp.asarray(lab)))
        for root in np.unique(lab[flat != 0]):
            if libs[root] == 2 and (lanes is None or len(preys) < lanes):
                boards.append(flat)
                labels.append(lab)
                preys.append(int(root))
        if positions is None and lanes is not None and pos > lanes * 20:
            break   # safety: pathological seed with no 2-lib groups
    if not boards:
        raise ValueError(
            f"no chase entries found in {pos} random position(s) — "
            "increase positions/moves or change the seed")
    return (np.stack(boards), np.stack(labels),
            np.asarray(preys, np.int32))


def random_game_states(cfg, batch: int, moves: int, rng_key):
    """Batched mid-game positions: ``moves`` uniform random legal
    plies under one jit (shared by the engine/encoder benchmarks)."""
    import functools

    import jax
    import jax.numpy as jnp

    from rocalphago_tpu.engine.jaxgo import (
        legal_mask,
        new_states,
        step,
        vgroup_data,
    )

    vstep = jax.vmap(functools.partial(step, cfg))
    vlegal = jax.vmap(functools.partial(legal_mask, cfg))
    vgd = vgroup_data(cfg, with_zxor=cfg.enforce_superko)

    @jax.jit
    def run(rng):
        states = new_states(cfg, batch)

        def ply(carry, _):
            states, rng = carry
            rng, sub = jax.random.split(rng)
            # share one group analysis between legality and step — the
            # same structure as the real self-play loop
            gd = vgd(states)
            legal = vlegal(states, gd)[:, :-1]
            logits = jnp.where(legal, 0.0, -1e30)
            action = jnp.where(
                legal.any(-1),
                jax.random.categorical(sub, logits, axis=-1),
                cfg.num_points).astype(jnp.int32)
            return (vstep(states, action, gd), rng), None

        (states, _), _ = jax.lax.scan(ply, (states, rng), length=moves)
        return states

    return run(rng_key)
