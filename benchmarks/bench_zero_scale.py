"""Actor/learner scaling for the zero loop (docs/SCALE.md).

Measures, per actor count, on one mesh: games-ingested/min into the
replay buffer, learner steps/s, and the learner-idle fraction — vs
the synchronous loop's baseline, whose self-play phase fraction IS
its learner idleness (the update waits out every self-play phase).
The actor/learner split exists to push that idle fraction down: the
sweep runs the decoupled configuration (free-running actors,
prioritized-recency sampling), where the learner's cadence is no
longer gated on fresh games — it waits only for the initial fill.
Device sections share a ``DispatchGang`` (``training/actor.py``):
on one mesh, concurrent play/learn programs with collectives must
not interleave.

CPU: run with a virtual 8-device mesh (the default here — the
``--no-force-host-devices`` flag disables the XLA override for real
accelerators, where the platform's own devices form the mesh).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, ".")

# the virtual-device override must land before jax imports (no
# conftest here); harmless but pointless on TPU, hence the flag
if ("--no-force-host-devices" not in sys.argv
        and "xla_force_host_platform_device_count"
        not in os.environ.get("XLA_FLAGS", "")):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()

from benchmarks._harness import report, std_parser  # noqa: E402


def main() -> None:
    import time

    import jax
    import optax

    from rocalphago_tpu.data.replay import ReplayBuffer
    from rocalphago_tpu.engine.jaxgo import GoConfig
    from rocalphago_tpu.io.checkpoint import pack_rng, unpack_rng
    from rocalphago_tpu.models import CNNPolicy, CNNValue
    from rocalphago_tpu.parallel import mesh as meshlib
    from rocalphago_tpu.training.actor import (
        DispatchGang,
        ParamsPublisher,
        SelfplayActor,
    )
    from rocalphago_tpu.training.learner import ZeroLearner
    from rocalphago_tpu.training.zero import (
        init_zero_state,
        make_zero_iteration,
    )

    ap = std_parser(__doc__)
    ap.add_argument("--actors", default="1,2,4",
                    help="comma-separated actor counts to sweep")
    ap.add_argument("--steps", type=int, default=8,
                    help="learner steps measured per actor count")
    ap.add_argument("--move-limit", type=int, default=16)
    ap.add_argument("--sims", type=int, default=4)
    ap.add_argument("--sim-chunk", type=int, default=2)
    ap.add_argument("--replay-chunk", type=int, default=8)
    ap.add_argument("--no-force-host-devices", action="store_true",
                    help="keep the platform's real devices (TPU)")
    ap.add_argument("--kill-actor-at", type=int, default=None,
                    help="recovery A/B: run the sweep under the fleet "
                    "supervisor and inject a kill into actor 0 at "
                    "this learner step; the report row gains kill_at "
                    "+ mttr_s (death detection to first post-restart "
                    "game — docs/RESILIENCE.md 'Fleet supervision')")
    ap.add_argument("--cap-p", type=float, default=0.0,
                    help="playout-cap randomization: probability a "
                    "ply gets the full --sims budget (0 = off; the "
                    "'econ row' runs this at 0.25 — see "
                    "docs/PERFORMANCE.md 'Self-play economics')")
    ap.add_argument("--cap-cheap", type=int, default=None,
                    help="cheap budget for capped plies "
                    "(default sims/4)")
    ap.add_argument("--wire", action="store_true",
                    help="wire rig: actors run as PROCESSES shipping "
                    "games to an in-process replay service "
                    "(docs/REPLAYNET.md) and the learner samples the "
                    "service's buffer — the wire-tax A/B against the "
                    "in-process sweep (rows: zero_wire_ingest_"
                    "games_per_min + learner_idle_frac)")
    ap.add_argument("--wire-measure-s", type=float, default=20.0,
                    help="wire: minimum timed-window length — the "
                    "learner keeps stepping past --steps until this "
                    "much wall clock has elapsed, so the ingest rate "
                    "is measured over a meaningful window")
    ap.add_argument("--wire-warmup-s", type=float, default=600.0,
                    help="wire: wait budget for every actor process "
                    "to compile and ship its first game before the "
                    "timed window opens (matches the in-process "
                    "sweep, whose actors start compile-hot)")
    ap.set_defaults(board=5, batch=8)
    args = ap.parse_args()
    econ = {}
    if args.cap_p:
        econ = {"cap_p": args.cap_p,
                "cap_cheap": args.cap_cheap or max(1, args.sims // 4)}

    feats = ("board", "ones")
    vfeats = feats + ("color",)
    pol = CNNPolicy(feats, board=args.board, layers=1,
                    filters_per_layer=4)
    val = CNNValue(vfeats, board=args.board, layers=1,
                   filters_per_layer=4)
    cfg = GoConfig(size=args.board)
    tx_p, tx_v = optax.sgd(0.01), optax.sgd(0.01)
    n_dev = len(jax.devices())
    while args.batch % n_dev:
        n_dev -= 1
    mesh = meshlib.make_mesh(n_dev)
    mesh_shape = (f"{mesh.shape[meshlib.DATA_AXIS]}"
                  f"x{mesh.shape[meshlib.MODEL_AXIS]}")
    iteration = make_zero_iteration(
        cfg, feats, vfeats, pol.module.apply, val.module.apply,
        tx_p, tx_v, batch=args.batch, move_limit=args.move_limit,
        n_sim=args.sims, max_nodes=16, sim_chunk=args.sim_chunk,
        replay_chunk=args.replay_chunk, mesh=mesh, **econ)
    state0 = meshlib.replicate(mesh, init_zero_state(
        pol.params, val.params, tx_p, tx_v, seed=0))

    # ---------------- synchronous baseline: selfplay-phase fraction
    def sync_iter(state):
        _, game_key = jax.random.split(unpack_rng(state.rng))
        t0 = time.monotonic()
        games = jax.device_get(iteration.play(
            state.policy_params, state.value_params, game_key))
        t1 = time.monotonic()
        state, m = iteration.learn(state, games)
        float(jax.device_get(m["policy_loss"]))    # sync
        return state, t1 - t0, time.monotonic() - t1

    state, _, _ = sync_iter(state0)                # compile
    t_play = t_learn = 0.0
    reps = max(args.reps, 2)
    t0 = time.monotonic()
    for _ in range(reps):
        state, dp, dl = sync_iter(state)
        t_play += dp
        t_learn += dl
    sync_dt = time.monotonic() - t0
    selfplay_frac = t_play / max(t_play + t_learn, 1e-9)
    report("zero_sync_games_per_min",
           reps * args.batch * 60.0 / sync_dt, "games/min",
           batch=args.batch, board=args.board, actors=0,
           mesh_shape=mesh_shape,
           selfplay_frac=round(selfplay_frac, 4), **econ)

    # ---------------- wire sweep: actor processes over replaynet
    if args.wire:
        import shutil
        import subprocess
        import tempfile

        from rocalphago_tpu.replaynet.server import ReplayService

        for n_actors in [int(x) for x in str(args.actors).split(",")]:
            buf = ReplayBuffer(capacity=max(2 * n_actors, 4))
            # evict mode: the sampling learner never pops, so the
            # buffer is a sliding window (same semantics as the
            # in-process free-run sweep)
            svc = ReplayService(buf, evict=True).start()
            tmp = tempfile.mkdtemp(prefix="zero_wire_")
            procs = [subprocess.Popen(
                [sys.executable, "-m",
                 "rocalphago_tpu.replaynet.actor",
                 "--connect", f"127.0.0.1:{svc.port}",
                 "--spool-dir", os.path.join(tmp, f"a{i}"),
                 "--actor-id", str(i), "--mode", "selfplay",
                 "--games", "1000000", "--seed", "0",
                 "--board", str(args.board),
                 "--batch", str(args.batch),
                 "--move-limit", str(args.move_limit),
                 "--sims", str(args.sims),
                 "--sim-chunk", str(args.sim_chunk)])
                for i in range(n_actors)]
            try:
                # warmup: every actor pays its play compile cold (the
                # in-process sweep's actors start hot off the sync
                # baseline) — open the timed window once each has
                # shipped at least one game
                t_warm = time.monotonic()
                while (buf.ingested_games < n_actors * args.batch
                       and time.monotonic() - t_warm
                       < args.wire_warmup_s):
                    if any(p.poll() is not None for p in procs):
                        raise RuntimeError(
                            "wire actor process died during warmup")
                    time.sleep(0.5)
                base_ingested = buf.ingested_games
                learner = ZeroLearner(iteration.learn, buf,
                                      sample=True)
                state = state0
                t0 = time.monotonic()
                steps_done = 0
                while (steps_done < args.steps
                       or time.monotonic() - t0
                       < args.wire_measure_s):
                    out = learner.step(state, timeout=300.0)
                    if out is None:
                        raise RuntimeError(
                            "wire learner starved at step "
                            f"{steps_done}")
                    state, m, _ = out
                    steps_done += 1
                dt = time.monotonic() - t0
                ingested = buf.ingested_games - base_ingested
            finally:
                for p in procs:
                    p.terminate()
                for p in procs:
                    try:
                        p.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        p.kill()
                        p.wait()
                svc.drain("bench")
                buf.close()
                shutil.rmtree(tmp, ignore_errors=True)
            idle = round(learner.idle_frac, 4)
            report("zero_wire_ingest_games_per_min",
                   ingested * 60.0 / dt, "games/min",
                   batch=args.batch, board=args.board,
                   actors=n_actors, mesh_shape=mesh_shape,
                   learner_idle_frac=idle,
                   sync_selfplay_frac=round(selfplay_frac, 4),
                   **econ)
            report("zero_wire_learner_steps_per_s",
                   steps_done / dt, "steps/s", batch=args.batch,
                   board=args.board, actors=n_actors,
                   mesh_shape=mesh_shape, learner_idle_frac=idle,
                   **econ)
        return

    # ---------------- actor/learner sweep
    for n_actors in [int(x) for x in str(args.actors).split(",")]:
        buf = ReplayBuffer(capacity=max(2 * n_actors, 4))
        pub = ParamsPublisher()
        gang = DispatchGang()

        def make_actor(i, attempt=0, beat=None):
            key = jax.random.fold_in(unpack_rng(state0.rng), i + 1)
            if attempt:
                key = jax.random.fold_in(key, attempt)
            return SelfplayActor(
                iteration.play, pub, buf, pack_rng(key),
                name=f"a{i}", lockstep=False, pace=False,
                poll_s=0.1, gang=gang, on_progress=beat)

        sup = None
        handles = actors = []
        if args.kill_actor_at is not None:
            # the recovery A/B rides the supervised rig: the injected
            # kill, the restart and the MTTR stamp are the production
            # machinery, not bench scaffolding
            from rocalphago_tpu.runtime.supervisor import (
                RestartPolicy,
                Supervisor,
            )

            sup = Supervisor(policy=RestartPolicy(base_delay=0.05,
                                                  max_delay=0.5),
                             poll_s=0.05)
            handles = [
                sup.add((lambda i: lambda attempt, beat:
                         make_actor(i, attempt, beat))(i),
                        name=f"a{i}")
                for i in range(n_actors)]
        else:
            actors = [make_actor(i) for i in range(n_actors)]
        learner = ZeroLearner(iteration.learn, buf, sample=True,
                              gang=gang)
        pub.publish(state0.policy_params, state0.value_params,
                    version=0)
        if sup is not None:
            sup.start()
        else:
            for ac in actors:
                ac.start()
        state = state0
        t0 = time.monotonic()
        for step in range(args.steps):
            if sup is not None and step == args.kill_actor_at:
                handles[0].worker.inject_fault()
            out = learner.step(state, timeout=300.0)
            if out is None:
                err = next((ac.error for ac in actors if ac.error),
                           None)
                raise RuntimeError(
                    f"learner starved at step {step} "
                    f"(actor error: {err})")
            state, m, _ = out
            pub.publish(state.policy_params, state.value_params,
                        version=step + 1)
        dt = time.monotonic() - t0
        ingested = buf.ingested_games
        buf.close()
        if sup is not None:
            sup.stop()
        else:
            for ac in actors:
                ac.stop()
        idle = round(learner.idle_frac, 4)
        recovery = {}
        if sup is not None:
            mttr = handles[0].last_mttr_s
            recovery = {"kill_at": args.kill_actor_at,
                        "mttr_s": (round(mttr, 3)
                                   if mttr is not None else None),
                        "restarts": sum(h.restarts
                                        for h in sup.handles())}
        report("zero_ingest_games_per_min",
               ingested * 60.0 / dt, "games/min",
               batch=args.batch, board=args.board, actors=n_actors,
               mesh_shape=mesh_shape, learner_idle_frac=idle,
               sync_selfplay_frac=round(selfplay_frac, 4),
               **recovery, **econ)
        report("zero_learner_steps_per_s", args.steps / dt,
               "steps/s", batch=args.batch, board=args.board,
               actors=n_actors, mesh_shape=mesh_shape,
               learner_idle_frac=idle, **econ)


if __name__ == "__main__":
    main()
