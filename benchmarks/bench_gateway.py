"""Gateway wire tax: moves/sec over the socket vs in-process.

The acceptance bench for ``rocalphago_tpu/gateway`` (docs/GATEWAY.md):
N concurrent game sessions served two ways over ONE warmed
:class:`~rocalphago_tpu.serve.sessions.ServePool` —

* **direct** — the pre-gateway baseline: each session is driven
  in-process (thread per session, ladder-wrapped ``get_move`` on a
  local ``GameState``), exactly what ``bench_serve.py``'s threaded
  arm measures;
* **gateway** — the same traffic through the full network stack:
  :class:`~rocalphago_tpu.gateway.server.GatewayServer` on localhost,
  :func:`~rocalphago_tpu.gateway.client.run_load` driving one NDJSON
  connection per session (frame encode/decode, socket hops, the
  per-request fault barrier and SLO arming all included).

Per (conns, mode) config one record goes to ``results.jsonl``:
aggregate ``moves/s`` (value) plus p50/p99 per-genmove latency; a
``gateway_wire_tax`` record carries the gateway/direct rate ratio —
the acceptance gate is ratio ≥ 0.8 at 16 connections (wire tax at
most 20%).

Usage::

    python benchmarks/bench_gateway.py [--conns 1,4,16] [--board 9]
        [--layers 6] [--filters 96] [--sims 8] [--moves 4] [--reps 3]
"""

from __future__ import annotations

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from benchmarks._harness import report, std_parser  # noqa: E402


def _percentile(sorted_vals, q):
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _run_threads(n, fn):
    """Run ``fn(i)`` in n threads behind one start barrier; returns
    (wall seconds, list of per-call exceptions)."""
    ready = threading.Barrier(n + 1)
    errors: list = []

    def work(i):
        try:
            ready.wait()
            fn(i)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    ready.wait()
    t0 = time.monotonic()
    for t in threads:
        t.join()
    return time.monotonic() - t0, errors


def main():
    ap = std_parser("gateway wire tax: socket vs in-process serving "
                    "(direct/gateway A/B)")
    ap.add_argument("--conns", default="1,4,16",
                    help="comma list of concurrent-connection counts "
                         "(= sessions on the direct side)")
    ap.add_argument("--layers", type=int, default=6)
    ap.add_argument("--filters", type=int, default=96)
    ap.add_argument("--sims", type=int, default=8,
                    help="simulations per move")
    ap.add_argument("--moves", type=int, default=4,
                    help="genmoves per connection per rep")
    ap.add_argument("--slo-ms", type=float, default=None,
                    help="per-genmove SLO the gateway arms (default "
                         "off: pure throughput A/B)")
    ap.add_argument("--replicas", type=int, default=0,
                    help="when > 0, add a third arm: the same "
                         "traffic through a RolloutRouter federating "
                         "this many gateway replicas (every replica "
                         "pool shares ONE compiled searcher); "
                         "reports mode=router rows plus the "
                         "router/gateway rate ratio (the router tax)")
    ap.set_defaults(board=9)   # serving default, like bench_serve
    a = ap.parse_args()

    from rocalphago_tpu.engine import pygo
    from rocalphago_tpu.gateway.client import run_load
    from rocalphago_tpu.gateway.server import GatewayServer
    from rocalphago_tpu.models import CNNPolicy, CNNValue
    from rocalphago_tpu.serve.evaluator import default_batch_sizes
    from rocalphago_tpu.serve.sessions import ServePool

    conn_counts = [int(s) for s in a.conns.split(",") if s]
    pol = CNNPolicy(("board", "ones"), board=a.board,
                    layers=a.layers, filters_per_layer=a.filters)
    val = CNNValue(("board", "ones", "color"), board=a.board,
                   layers=a.layers, filters_per_layer=a.filters)

    common = dict(board=a.board, layers=a.layers, filters=a.filters,
                  sims=a.sims, moves=a.moves)

    for n_conns in conn_counts:
        sizes = default_batch_sizes(cap=n_conns)
        pool = ServePool(val, pol, n_sim=a.sims,
                         max_sessions=n_conns,
                         queue_rows=4 * max(sizes),
                         batch_sizes=sizes)
        pool.warm()

        # ---- direct: in-process threaded sessions, the baseline the
        # wire tax is measured against (ladder-wrapped like the
        # gateway's sessions, so the A/B isolates ONLY the wire)
        best = None
        for _ in range(a.reps):
            sessions = [pool.open_session() for _ in range(n_conns)]
            games = [pygo.GameState(size=a.board, komi=7.5)
                     for _ in range(n_conns)]
            lats: list = []
            lat_lock = threading.Lock()

            def play(i):
                game = games[i]
                for _ in range(a.moves):
                    t0 = time.monotonic()
                    mv = sessions[i].get_move(game)
                    dt = time.monotonic() - t0
                    with lat_lock:
                        lats.append(dt)
                    game.do_move(mv)

            wall, errors = _run_threads(n_conns, play)
            for s in sessions:
                s.close()
            if errors:
                raise errors[0]
            rate = n_conns * a.moves / wall
            if best is None or rate > best[0]:
                best = (rate, sorted(lats))
        direct_rate, lats = best
        report("gateway_moves_per_s", direct_rate, "moves/s",
               conns=n_conns, mode="direct",
               p50_s=round(_percentile(lats, 0.50), 4),
               p99_s=round(_percentile(lats, 0.99), 4), **common)

        # ---- gateway: identical traffic through the localhost
        # socket server (one NDJSON connection per session)
        server = GatewayServer(pool, max_conns=n_conns,
                               slo_ms=a.slo_ms).start()

        def settled():
            # a closed client releases its slot at the handler's NEXT
            # read; back-to-back reps must not race that or rep N+1
            # sheds against rep N's still-draining connections
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if server.stats()["conns"]["live"] == 0:
                    return
                time.sleep(0.01)
            raise RuntimeError("gateway connections did not settle")

        best = None
        for _ in range(a.reps):
            settled()
            out = run_load("127.0.0.1", server.port,
                           conns=n_conns, moves=a.moves)
            if out["sheds"] or out["disconnects"] or out["errors"]:
                raise RuntimeError(
                    f"gateway load not clean at {n_conns} conns: "
                    f"{out['sheds']} sheds, "
                    f"{out['disconnects']} disconnects, "
                    f"{out['errors']} errors")
            rate = out["moves"] / out["elapsed_s"]
            if best is None or rate > best[0]:
                best = (rate, sorted(out["latencies_s"]))
        server.drain(reason="bench")
        gateway_rate, lats = best
        report("gateway_moves_per_s", gateway_rate, "moves/s",
               conns=n_conns, mode="gateway",
               p50_s=round(_percentile(lats, 0.50), 4),
               p99_s=round(_percentile(lats, 0.99), 4), **common)

        # the acceptance number: gateway throughput as a fraction of
        # direct (≥ 0.8 at 16 conns = wire tax within 20%)
        report("gateway_wire_tax", gateway_rate / direct_rate, "x",
               conns=n_conns, **common)

        # ---- router: the same traffic once more, now through a
        # federation front door (docs/ROLLOUT.md) — the extra hop's
        # cost relative to one bare gateway is the router tax
        if a.replicas > 0:
            from rocalphago_tpu.rollout.router import (
                Replica,
                RolloutRouter,
            )

            extra_pools = [
                ServePool(val, pol, n_sim=a.sims,
                          max_sessions=n_conns,
                          queue_rows=4 * max(sizes),
                          batch_sizes=sizes,
                          searcher=pool.search)
                for _ in range(a.replicas - 1)]
            servers = [GatewayServer(p, max_conns=n_conns,
                                     slo_ms=a.slo_ms).start()
                       for p in [pool] + extra_pools]
            reps = [Replica("127.0.0.1", s.port, gateway=s,
                            name=f"r{i}")
                    for i, s in enumerate(servers)]
            router = RolloutRouter(reps,
                                   max_conns=n_conns).start()

            def router_settled():
                deadline = time.monotonic() + 30.0
                while time.monotonic() < deadline:
                    if (router.stats()["conns"]["live"] == 0
                            and all(s.stats()["conns"]["live"] == 0
                                    for s in servers)):
                        return
                    time.sleep(0.01)
                raise RuntimeError(
                    "router connections did not settle")

            best = None
            for _ in range(a.reps):
                router_settled()
                out = run_load("127.0.0.1", router.port,
                               conns=n_conns, moves=a.moves)
                if out["sheds"] or out["disconnects"] or \
                        out["errors"]:
                    raise RuntimeError(
                        f"router load not clean at {n_conns} "
                        f"conns: {out['sheds']} sheds, "
                        f"{out['disconnects']} disconnects, "
                        f"{out['errors']} errors")
                rate = out["moves"] / out["elapsed_s"]
                if best is None or rate > best[0]:
                    best = (rate, sorted(out["latencies_s"]))
            router.drain(reason="bench")
            router.close()
            for s in servers:
                s.drain(reason="bench")
                s.close()
            for p in extra_pools:
                p.close()
            router_rate, lats = best
            report("gateway_moves_per_s", router_rate, "moves/s",
                   conns=n_conns, mode="router",
                   replicas=a.replicas,
                   p50_s=round(_percentile(lats, 0.50), 4),
                   p99_s=round(_percentile(lats, 0.99), 4),
                   **common)
            report("gateway_router_tax",
                   router_rate / gateway_rate, "x",
                   conns=n_conns, replicas=a.replicas, **common)
        pool.close()


if __name__ == "__main__":
    main()
