"""Rollout-net forward latency + on-device rollout throughput.

The AlphaGo paper's rollout policy is valued for its ~2 µs/move
forward (SURVEY.md §6); the TPU analogue of that number is (a) the
batched forward latency of ``CNNRollout`` and (b) the end-to-end
steps/s of :func:`search.selfplay.make_device_rollout`, which is what
MCTS actually pays per wave with ``device_rollout=True``.
"""

from __future__ import annotations

import sys

sys.path.insert(0, ".")
from benchmarks._harness import report, std_parser, timed  # noqa: E402


def main() -> None:
    import jax

    from rocalphago_tpu.engine.jaxgo import GoConfig, new_states
    from rocalphago_tpu.models import CNNRollout
    from rocalphago_tpu.search.selfplay import make_device_rollout

    ap = std_parser(__doc__)
    ap.add_argument("--rollout-limit", type=int, default=100)
    args = ap.parse_args()
    batch = args.batch or 64

    net = CNNRollout(board=args.board)
    planes = jax.numpy.zeros(
        (batch, args.board, args.board, net.preprocess.output_dim),
        jax.numpy.float32)

    per_call = timed(lambda: jax.device_get(net.forward(planes)),
                     reps=max(args.reps * 10, 10),
                     profile_dir=args.profile)
    report("rollout_forward", per_call * 1e6 / batch, "us/position",
           batch=batch, board=args.board)

    cfg = GoConfig(size=args.board)
    run = make_device_rollout(cfg, net.feature_list, net.module.apply,
                              rollout_limit=args.rollout_limit,
                              with_steps=True)
    states = new_states(cfg, batch)
    # the loop exits when every game ends — record the plies actually
    # executed (deterministic across reps) instead of assuming the
    # full rollout_limit ran
    box = []

    def once():
        out = jax.device_get(run(net.params, states, jax.random.key(1)))
        box.append(out[1])
        return out

    per_rollout = timed(once, reps=args.reps, profile_dir=args.profile)
    executed = box[-1]
    report("device_rollout_steps", batch * int(executed) / per_rollout,
           "board-steps/s", batch=batch, board=args.board,
           rollout_limit=args.rollout_limit, executed_plies=int(executed))


if __name__ == "__main__":
    main()
