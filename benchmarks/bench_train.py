"""SL training-step throughput (positions/s) on synthetic data.

The device-side half of the reference's training hot path (SURVEY.md
§3.1): full 12×128 policy on 48 planes, jitted data-parallel train
step with on-device dihedral augmentation, synthetic batches (no input
pipeline — measure the step itself).
"""

from __future__ import annotations

import sys

sys.path.insert(0, ".")
from benchmarks._harness import (  # noqa: E402
    mfu,
    program_flops,
    report,
    std_parser,
    timed,
)


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from rocalphago_tpu.io.checkpoint import pack_rng
    from rocalphago_tpu.models import CNNPolicy
    from rocalphago_tpu.parallel import mesh as meshlib
    from rocalphago_tpu.training.sl import SLState, make_train_step

    ap = std_parser(__doc__)
    ap.add_argument("--batch-sweep", default=None, metavar="B1,B2,...",
                    help="measure a comma-separated list of batch "
                    "sizes (one result line each) instead of one")
    args = ap.parse_args()
    default_b = 256 if jax.devices()[0].platform == "tpu" else 16
    batches = ([int(b) for b in args.batch_sweep.split(",")]
               if args.batch_sweep else [args.batch or default_b])
    net = CNNPolicy(board=args.board, layers=12, filters_per_layer=128)
    mesh = meshlib.make_mesh()
    tx = optax.sgd(0.003)

    rep = meshlib.replicated(mesh)
    state = meshlib.replicate(mesh, SLState(
        params=net.params, opt_state=tx.init(net.params),
        step=jnp.int32(0), rng=pack_rng(jax.random.key(0))))
    state_sh = jax.tree.map(lambda _: rep, state)
    train_step = jax.jit(
        make_train_step(net.module.apply, tx, args.board,
                        symmetries=True),
        in_shardings=(state_sh, meshlib.data_sharding(mesh, 4),
                      meshlib.data_sharding(mesh, 1)),
        out_shardings=(state_sh, rep))

    rng = np.random.default_rng(0)
    for batch in batches:
        planes = rng.random((batch, args.board, args.board,
                             net.preprocess.output_dim), np.float32)
        actions = rng.integers(0, args.board ** 2, batch,
                               dtype=np.int32)
        planes, actions = meshlib.shard_batch(mesh, (planes, actions))

        # XLA's own cost analysis of the compiled step: fwd + bwd +
        # update FLOPs, the MFU numerator (VERDICT r2 missing #3).
        # program_flops is the PER-DEVICE module's count — normalize
        # per-position by the per-device share of the global batch
        n_dev = mesh.shape[meshlib.DATA_AXIS]
        flops = program_flops(train_step, state, planes, actions)

        holder = [state]

        def once():
            holder[0], m = train_step(holder[0], planes, actions)
            return jax.device_get(m["loss"])

        dt = timed(once, reps=args.reps, profile_dir=args.profile)
        extra = {}
        if flops:
            extra["flops_per_position"] = round(
                flops / max(batch // n_dev, 1))
            u = mfu(flops / dt)   # per-chip: per-device flops ÷ peak
            if u is not None:
                extra["mfu"] = round(u, 4)
        report("sl_train_step", batch / dt, "positions/s",
               batch=batch, board=args.board, devices=n_dev, **extra)


if __name__ == "__main__":
    main()
