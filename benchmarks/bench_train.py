"""SL training-step throughput (positions/s) on synthetic data.

The device-side half of the reference's training hot path (SURVEY.md
§3.1): full 12×128 policy on 48 planes, jitted data-parallel train
step with on-device dihedral augmentation, synthetic batches (no input
pipeline — measure the step itself).
"""

from __future__ import annotations

import sys

sys.path.insert(0, ".")
from benchmarks._harness import report, std_parser, timed  # noqa: E402


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from rocalphago_tpu.io.checkpoint import pack_rng
    from rocalphago_tpu.models import CNNPolicy
    from rocalphago_tpu.parallel import mesh as meshlib
    from rocalphago_tpu.training.sl import SLState, make_train_step

    args = std_parser(__doc__).parse_args()
    batch = args.batch or (256 if jax.devices()[0].platform == "tpu"
                           else 16)
    net = CNNPolicy(board=args.board, layers=12, filters_per_layer=128)
    mesh = meshlib.make_mesh()
    tx = optax.sgd(0.003)

    rep = meshlib.replicated(mesh)
    state = meshlib.replicate(mesh, SLState(
        params=net.params, opt_state=tx.init(net.params),
        step=jnp.int32(0), rng=pack_rng(jax.random.key(0))))
    state_sh = jax.tree.map(lambda _: rep, state)
    train_step = jax.jit(
        make_train_step(net.module.apply, tx, args.board,
                        symmetries=True),
        in_shardings=(state_sh, meshlib.data_sharding(mesh, 4),
                      meshlib.data_sharding(mesh, 1)),
        out_shardings=(state_sh, rep))

    rng = np.random.default_rng(0)
    planes = rng.random((batch, args.board, args.board,
                         net.preprocess.output_dim), np.float32)
    actions = rng.integers(0, args.board ** 2, batch, dtype=np.int32)
    planes, actions = meshlib.shard_batch(mesh, (planes, actions))

    holder = [state]

    def once():
        holder[0], m = train_step(holder[0], planes, actions)
        return jax.device_get(m["loss"])

    dt = timed(once, reps=args.reps, profile_dir=args.profile)
    report("sl_train_step", batch / dt, "positions/s",
           batch=batch, board=args.board,
           devices=mesh.shape[meshlib.DATA_AXIS])


if __name__ == "__main__":
    main()
