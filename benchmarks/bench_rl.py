"""Full REINFORCE iteration throughput (games/min).

Parity: the reference's ``reinforcement_policy_trainer_benchmark.py``
— its RL game loop was the slowest path in the repo (SURVEY.md §2
"Benchmarks", §3.2). Measures the whole jitted iteration: self-play
game scan + replay gradient + SGD update.
"""

from __future__ import annotations

import sys

sys.path.insert(0, ".")
from benchmarks._harness import report, std_parser, timed  # noqa: E402


def main() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    from rocalphago_tpu.io.checkpoint import pack_rng
    from rocalphago_tpu.models import CNNPolicy
    from rocalphago_tpu.parallel import mesh as meshlib
    from rocalphago_tpu.training.rl import RLState, make_rl_iteration

    ap = std_parser(__doc__)
    ap.add_argument("--moves", type=int, default=None)
    ap.add_argument("--chunk", type=int, default=None,
                    help="plies per compiled segment (0 = monolithic "
                         "program; default 10 on TPU — the monolithic "
                         "iteration is the one program that crashed "
                         "the tunnel's ~40s watchdog in round 2)")
    args = ap.parse_args()
    on_tpu = jax.devices()[0].platform == "tpu"
    batch = args.batch or (64 if on_tpu else 8)
    moves = args.moves or (400 if on_tpu else 40)
    chunk = args.chunk if args.chunk is not None else (
        10 if on_tpu else 0)

    net = CNNPolicy(board=args.board, layers=12, filters_per_layer=128)
    mesh = meshlib.make_mesh()
    tx = optax.sgd(0.001)
    if chunk:
        from rocalphago_tpu.training.rl import make_rl_iteration_chunked

        iteration = make_rl_iteration_chunked(
            net.cfg, net.feature_list, net.module.apply, tx, batch,
            moves, temperature=0.67, chunk=chunk, mesh=mesh)
    else:
        iteration = jax.jit(make_rl_iteration(
            net.cfg, net.feature_list, net.module.apply, tx, batch,
            moves, temperature=0.67, mesh=mesh))
    state = meshlib.replicate(mesh, RLState(
        params=net.params, opt_state=tx.init(net.params),
        iteration=jnp.int32(0), rng=pack_rng(jax.random.key(0))))
    opp = meshlib.replicate(mesh, net.params)
    holder = [state]

    def once():
        holder[0], m = iteration(holder[0], opp)
        return jax.device_get(m["win_rate"])

    dt = timed(once, reps=args.reps, profile_dir=args.profile)
    report("rl_iteration", batch / dt * 60.0, "games/min",
           batch=batch, moves=moves, board=args.board, chunk=chunk,
           devices=mesh.shape[meshlib.DATA_AXIS])


if __name__ == "__main__":
    main()
