"""Flood-fill labeling: XLA while_loop vs the Pallas VMEM kernel.

The labeling is the engine's hottest primitive (one per ply per game
in self-play, one per ladder rung). This compares the default XLA
formulation (`jaxgo.compute_labels`, convergence loop + pointer
jumping) against `ops.pallas_labels` (whole fixpoint in VMEM, static
sweep bound) on whatever backend is attached; on non-TPU hosts the
kernel runs in interpret mode, whose absolute time is meaningless —
only the TPU comparison decides whether the engine should switch.
"""

from __future__ import annotations

import sys

sys.path.insert(0, ".")
from benchmarks._harness import report, std_parser, timed  # noqa: E402


def main() -> None:
    import jax
    import numpy as np

    from rocalphago_tpu.engine.jaxgo import GoConfig, compute_labels
    from rocalphago_tpu.ops import pallas_labels

    args = std_parser(__doc__).parse_args()
    batch = args.batch or 256
    cfg = GoConfig(size=args.board)
    n = cfg.num_points

    rng = np.random.default_rng(0)
    boards = rng.choice(np.asarray([0, 1, -1], np.int8), (batch, n),
                        p=[0.4, 0.3, 0.3])
    boards = jax.device_put(boards)

    xla = jax.jit(jax.vmap(lambda b: compute_labels(cfg, b)))
    dt = timed(lambda: jax.device_get(xla(boards)), reps=args.reps,
               profile_dir=args.profile)
    report("labels_xla", batch / dt, "boards/s", batch=batch,
           board=args.board)

    on_tpu = jax.devices()[0].platform == "tpu"
    dt = timed(lambda: jax.device_get(
        pallas_labels(boards, args.board, interpret=not on_tpu)),
        reps=args.reps, profile_dir=args.profile)
    report("labels_pallas", batch / dt, "boards/s", batch=batch,
           board=args.board, interpret=not on_tpu)


if __name__ == "__main__":
    main()
