"""MCTS playout throughput (sims/s) with real nets.

The reference's per-playout batch-1 NN eval was its search bottleneck
(SURVEY.md §3.3); this measures the batched-leaf rebuild end to end:
host tree + one jitted policy/value forward per wave.
"""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")
from benchmarks._harness import report, std_parser  # noqa: E402


def main() -> None:
    from rocalphago_tpu.engine import pygo
    from rocalphago_tpu.models import CNNPolicy, CNNValue
    from rocalphago_tpu.search.mcts import MCTSPlayer
    from rocalphago_tpu.search.players import reset_player

    ap = std_parser(__doc__)
    ap.add_argument("--playouts", type=int, default=64)
    ap.add_argument("--leaf-batch", type=int, default=16)
    ap.add_argument("--lmbda", type=float, default=0.0,
                    help="0 = value-net only (no rollouts)")
    ap.add_argument("--device-rollout", action="store_true",
                    help="rollouts as one on-device scan per wave "
                         "(device_rollout_fn) instead of host rules")
    ap.add_argument("--rollout-limit", type=int, default=500)
    args = ap.parse_args()

    policy = CNNPolicy(board=args.board, layers=12,
                       filters_per_layer=128)
    value = CNNValue(board=args.board, layers=12, filters_per_layer=128)
    rollout = None
    if args.device_rollout:
        from rocalphago_tpu.models import CNNRollout
        rollout = CNNRollout(board=args.board)
    player = MCTSPlayer(value, policy, rollout=rollout, lmbda=args.lmbda,
                        n_playout=args.playouts,
                        rollout_limit=args.rollout_limit,
                        leaf_batch=args.leaf_batch, seed=0,
                        device_rollout=args.device_rollout)
    state = pygo.GameState(size=args.board)
    player.get_move(state.copy())      # warmup/compile

    t0 = time.time()
    for _ in range(args.reps):
        reset_player(player)
        player.get_move(state.copy())
    dt = (time.time() - t0) / args.reps
    report("mcts_playouts", args.playouts / dt, "sims/s",
           playouts=args.playouts, leaf_batch=args.leaf_batch,
           board=args.board, lmbda=args.lmbda,
           device_rollout=args.device_rollout)


if __name__ == "__main__":
    main()
