"""Ladder-chase throughput: XLA lockstep vmap vs the Pallas per-lane
kernel (``ops/chase.py``).

The chase loop is the 48-plane encoder's dominant cost; the XLA
formulation pays max-over-batch trips in lockstep while the kernel
gives each lane its own loop. Lanes are harvested from random games
(every 2-liberty group is a valid chase entry, chaser to move).
"""

from __future__ import annotations

import functools
import sys

sys.path.insert(0, ".")
from benchmarks._harness import (  # noqa: E402
    harvest_chase_lanes,
    report,
    std_parser,
    timed,
)


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rocalphago_tpu.engine.jaxgo import GoConfig
    from rocalphago_tpu.features.ladders import _chase
    from rocalphago_tpu.ops.chase import pallas_chase

    ap = std_parser(__doc__)
    ap.add_argument("--depth", type=int, default=40)
    args = ap.parse_args()
    size = args.board
    n = size * size
    lanes = args.batch or 128
    cfg = GoConfig(size=size)

    boards, labels, preys = harvest_chase_lanes(size, lanes, seed=0,
                                                moves_lo=20)
    boards = jnp.asarray(boards)
    labels_a = jnp.asarray(labels)
    lanes = len(preys)
    prey_oh = jnp.asarray(np.arange(n)[None, :] == preys[:, None])
    preys = jnp.asarray(preys)

    on_tpu = jax.devices()[0].platform == "tpu"

    xla = jax.jit(jax.vmap(functools.partial(
        _chase, cfg, depth=args.depth, enabled=True)))
    dt = timed(lambda: jax.device_get(xla(boards, labels_a, preys)),
               reps=args.reps, profile_dir=args.profile)
    report("chase_xla", round(lanes / dt, 1), "lanes/s",
           batch=lanes, board=size, depth=args.depth)

    try:
        pal = lambda: jax.device_get(pallas_chase(  # noqa: E731
            boards, labels_a, prey_oh, size, args.depth,
            interpret=not on_tpu))
        dt = timed(pal, reps=args.reps)
        report("chase_pallas", round(lanes / dt, 1), "lanes/s",
               batch=lanes, board=size, depth=args.depth,
               interpret=not on_tpu)
    except Exception as e:  # noqa: BLE001 — keep the XLA number
        print(f"chase_pallas failed: {type(e).__name__}: {e}",
              file=sys.stderr)


if __name__ == "__main__":
    main()
