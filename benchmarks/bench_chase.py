"""Ladder-chase throughput: XLA lockstep vmap vs the Pallas per-lane
kernel (``ops/chase.py``).

The chase loop is the 48-plane encoder's dominant cost; the XLA
formulation pays max-over-batch trips in lockstep while the kernel
gives each lane its own loop. Lanes are harvested from random games
(every 2-liberty group is a valid chase entry, chaser to move).
"""

from __future__ import annotations

import functools
import sys

sys.path.insert(0, ".")
from benchmarks._harness import report, std_parser, timed  # noqa: E402


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rocalphago_tpu.engine import pygo
    from rocalphago_tpu.engine.jaxgo import GoConfig, compute_labels, \
        lib_counts_from_labels
    from rocalphago_tpu.features.ladders import _chase
    from rocalphago_tpu.ops.chase import pallas_chase

    ap = std_parser(__doc__)
    ap.add_argument("--depth", type=int, default=40)
    args = ap.parse_args()
    size = args.board
    n = size * size
    lanes = args.batch or 128
    cfg = GoConfig(size=size)

    rng = np.random.default_rng(0)
    boards, labels, preys = [], [], []
    while len(preys) < lanes:
        st = pygo.GameState(size=size, komi=7.5)
        for _ in range(int(rng.integers(20, 120))):
            legal = st.get_legal_moves(include_eyes=False)
            if not legal or st.is_end_of_game:
                break
            st.do_move(legal[rng.integers(len(legal))])
        flat = np.asarray(st.board, np.int8).reshape(-1)
        lab = np.asarray(compute_labels(cfg, jnp.asarray(flat)))
        libs = np.asarray(lib_counts_from_labels(
            cfg, jnp.asarray(flat), jnp.asarray(lab)))
        for root in np.unique(lab[flat != 0]):
            if libs[root] == 2 and len(preys) < lanes:
                boards.append(flat)
                labels.append(lab)
                preys.append(int(root))
    boards = jnp.asarray(np.stack(boards))
    labels_a = jnp.asarray(np.stack(labels))
    preys = np.asarray(preys, np.int32)
    prey_oh = jnp.asarray(np.arange(n)[None, :] == preys[:, None])
    preys = jnp.asarray(preys)

    on_tpu = jax.devices()[0].platform == "tpu"

    xla = jax.jit(jax.vmap(functools.partial(
        _chase, cfg, depth=args.depth, enabled=True)))
    dt = timed(lambda: jax.device_get(xla(boards, labels_a, preys)),
               reps=args.reps, profile_dir=args.profile)
    report("chase_xla", round(lanes / dt, 1), "lanes/s",
           batch=lanes, board=size, depth=args.depth)

    try:
        pal = lambda: jax.device_get(pallas_chase(  # noqa: E731
            boards, labels_a, prey_oh, size, args.depth,
            interpret=not on_tpu))
        dt = timed(pal, reps=args.reps)
        report("chase_pallas", round(lanes / dt, 1), "lanes/s",
               batch=lanes, board=size, depth=args.depth,
               interpret=not on_tpu)
    except Exception as e:  # noqa: BLE001 — keep the XLA number
        print(f"chase_pallas failed: {type(e).__name__}: {e}",
              file=sys.stderr)


if __name__ == "__main__":
    main()
