"""Multi-size serving: one FCN checkpoint on every board.

Per ``--sizes`` entry, ``--sessions`` concurrent games drive the
:class:`~rocalphago_tpu.multisize.MultiSizePool`'s member pool for
that size through the fleet driver — one record per board size:
aggregate ``moves/s``, p50/p99 per-genmove latency, evaluator batch
occupancy. This is the headline table docs/MULTISIZE.md cites: the
SAME param pytree serving 9×9, 13×13 and 19×19 side by side.

The A/B (``--ab``): the multi-size pool shares ONE checkpoint across
the ladder, so its incremental cost per extra size is compiled
programs only; the counterfactual — one standalone :class:`~
rocalphago_tpu.serve.sessions.ServePool` per size over per-size nets
— pays a separate param pytree per size. Both arms report the
``jax_compiles_total`` delta (obs compile tracking) and resident
param bytes, so the table shows what sharing actually buys: params
×1 vs ×N, compiles identical (the per-size programs are the
irreducible cost either way — static shapes carry H×W).

Usage::

    python benchmarks/bench_multisize.py [--sizes 9,13,19]
        [--sessions 8] [--sims 8] [--moves 2] [--reps 2] [--ab]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from benchmarks._harness import report, std_parser  # noqa: E402


def _percentile(sorted_vals, q):
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _param_bytes(*nets) -> int:
    import jax

    return sum(leaf.size * leaf.dtype.itemsize
               for net in nets
               for leaf in jax.tree.leaves(net.params))


def _compiles_total() -> int:
    from rocalphago_tpu.obs import registry

    return sum(v for k, v in registry.snapshot()["counters"].items()
               if k.startswith("jax_compiles_total"))


def _drive(pool, size, sessions, moves, reps):
    """moves/s + latency percentiles for ``sessions`` concurrent
    games at ``size`` through one member pool's fleet driver."""
    from rocalphago_tpu.engine import pygo

    handles = [pool.open_session(size=size, resilient=False)
               for _ in range(sessions)]
    driver = pool.driver(handles)
    driver.warm()
    best = None
    for _ in range(reps):
        lats: list = []
        games = [pygo.GameState(size=size) for _ in range(sessions)]
        t_rep = time.monotonic()
        for _ in range(moves):
            t0 = time.monotonic()
            mvs = driver.genmove_all(games)
            lats.extend([time.monotonic() - t0] * sessions)
            for game, mv in zip(games, mvs):
                game.do_move(mv)
        wall = time.monotonic() - t_rep
        rate = sessions * moves / wall
        if best is None or rate > best[0]:
            best = (rate, sorted(lats))
    occupancy = pool.pool_for(size).evaluator.stats()[
        "batch_occupancy"]
    for h in handles:
        h.close()
    rate, lats = best
    return rate, lats, occupancy


def main():
    ap = std_parser("multi-size serving: one FCN checkpoint per-size "
                    "throughput + shared-vs-separate pool A/B")
    ap.add_argument("--sizes", default="9,13,19",
                    help="comma list of board sizes the ladder serves")
    ap.add_argument("--sessions", type=int, default=8)
    ap.add_argument("--layers", type=int, default=6)
    ap.add_argument("--filters", type=int, default=96)
    ap.add_argument("--sims", type=int, default=8)
    ap.add_argument("--moves", type=int, default=2,
                    help="genmoves per session per rep")
    ap.add_argument("--ab", action="store_true",
                    help="also measure the one-standalone-pool-per-"
                         "size counterfactual (params ×N)")
    a = ap.parse_args()

    from rocalphago_tpu.models import CNNPolicy, CNNValue
    from rocalphago_tpu.multisize import MultiSizePool
    from rocalphago_tpu.serve.evaluator import default_batch_sizes
    from rocalphago_tpu.serve.sessions import ServePool

    sizes = tuple(int(s) for s in a.sizes.split(",") if s.strip())
    batch_sizes = default_batch_sizes(cap=a.sessions)
    pool_kw = dict(n_sim=a.sims, max_sessions=a.sessions,
                   queue_rows=4 * max(batch_sizes),
                   batch_sizes=batch_sizes)
    common = dict(sessions=a.sessions, layers=a.layers,
                  filters=a.filters, sims=a.sims, moves=a.moves)

    # ---- one MultiSizePool, one checkpoint, every size -----------
    pol = CNNPolicy(("board", "ones"), board=sizes[0],
                    layers=a.layers, filters_per_layer=a.filters)
    val = CNNValue(("board", "ones", "color"), board=sizes[0],
                   layers=a.layers, filters_per_layer=a.filters)
    c0 = _compiles_total()
    mp = MultiSizePool(val, pol, sizes=sizes, **pool_kw)
    for size in sizes:
        rate, lats, occupancy = _drive(mp, size, a.sessions,
                                       a.moves, a.reps)
        report("multisize_moves_per_s", rate, "moves/s",
               board=size, mode="one_pool",
               p50_s=round(_percentile(lats, 0.50), 4),
               p99_s=round(_percentile(lats, 0.99), 4),
               occupancy=occupancy, **common)
    report("multisize_param_mb", _param_bytes(pol, val) / 1e6, "MB",
           mode="one_pool", boards=a.sizes,
           compiles=_compiles_total() - c0, **common)
    mp.close()

    # ---- A/B: a standalone pool (and checkpoint) per size --------
    if not a.ab:
        return
    c0 = _compiles_total()
    nets, pools = [], []
    for size in sizes:
        p = CNNPolicy(("board", "ones"), board=size,
                      layers=a.layers, filters_per_layer=a.filters)
        v = CNNValue(("board", "ones", "color"), board=size,
                     layers=a.layers, filters_per_layer=a.filters)
        nets.extend((p, v))
        pools.append(ServePool(v, p, label_board=True, **pool_kw))
    for size, pool in zip(sizes, pools):
        handles = [pool.open_session(resilient=False)
                   for _ in range(a.sessions)]
        driver = pool.driver(handles)
        driver.warm()
        from rocalphago_tpu.engine import pygo

        best = None
        for _ in range(a.reps):
            games = [pygo.GameState(size=size)
                     for _ in range(a.sessions)]
            t0 = time.monotonic()
            for _ in range(a.moves):
                mvs = driver.genmove_all(games)
                for game, mv in zip(games, mvs):
                    game.do_move(mv)
            rate = a.sessions * a.moves / (time.monotonic() - t0)
            best = rate if best is None else max(best, rate)
        report("multisize_moves_per_s", best, "moves/s",
               board=size, mode="pool_per_size", **common)
        for h in handles:
            h.close()
    report("multisize_param_mb", _param_bytes(*nets) / 1e6, "MB",
           mode="pool_per_size", boards=a.sizes,
           compiles=_compiles_total() - c0, **common)
    for pool in pools:
        pool.close()


if __name__ == "__main__":
    main()
