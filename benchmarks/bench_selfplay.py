"""Self-play ply-program throughput + MFU at configurable batch.

The headline driver bench (``bench.py``) measures full games; this
script isolates the per-ply self-play program (encode → policy forward
→ sample → rules step, one compiled segment of the chunked runner) so
batch scaling and MFU are measurable without playing whole games
(VERDICT r2 missing #3/#4: "MFU for ... the self-play step at batch
{64, 256, 1024}"). Mid-game seeds keep the measurement honest — the
vmap'd fixpoint loops stall on the slowest board, and opening boards
hide exactly that cost.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from benchmarks._harness import (  # noqa: E402
    mfu,
    program_flops,
    report,
    std_parser,
    timed,
)


def run_cap_ab(args) -> None:
    """Playout-cap randomization A/B (docs/PERFORMANCE.md "Self-play
    economics"): full MCTS self-play games/min at each ``--cap-p``
    value — the probability a ply draws the FULL ``--sims`` budget;
    the rest run the cheap cap (sims/4). ``cap_p=1.0`` is the
    all-full baseline every speedup is read against. Small nets on
    purpose: the cap's win is search volume, which doesn't depend on
    net width, and a fat net would just move the bottleneck."""
    import jax

    from rocalphago_tpu.engine.jaxgo import GoConfig
    from rocalphago_tpu.models import CNNPolicy, CNNValue
    from rocalphago_tpu.obs import registry as obs_registry
    from rocalphago_tpu.search.device_mcts import make_mcts_selfplay

    on_tpu = jax.devices()[0].platform == "tpu"
    batch = args.batch or (64 if on_tpu else 8)
    board = args.board
    if board == 19 and not on_tpu:
        board = 9            # full-game 19×19 MCTS on CPU is minutes/rep
    cfg = GoConfig(size=board)
    feats = ("board", "ones")
    pol = CNNPolicy(feats, board=board, layers=2, filters_per_layer=8)
    val = CNNValue(feats + ("color",), board=board, layers=2,
                   filters_per_layer=8)
    cheap = max(1, args.sims // 4)
    for p in [float(x) for x in str(args.cap_p).split(",")]:
        run = make_mcts_selfplay(
            cfg, pol.feature_list, val.feature_list, pol.module.apply,
            val.module.apply, batch, args.move_limit, args.sims,
            sim_chunk=min(8, args.sims), cap_p=p, cap_cheap=cheap)

        def once():
            final, _, _ = run(pol.params, val.params, jax.random.key(3))
            return jax.device_get(final.board)

        dt = timed(once, reps=args.reps, profile_dir=args.profile)
        frac = obs_registry.REGISTRY.snapshot()["gauges"].get(
            "selfplay_fullsearch_frac")
        extra = {}
        if frac is not None:
            extra["fullsearch_frac"] = round(float(frac), 4)
        report("selfplay_cap_games_per_min", batch * 60.0 / dt,
               "games/min", batch=batch, board=board, cap_p=p,
               cap_cheap=cheap, n_sim=args.sims,
               move_limit=args.move_limit, **extra)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from rocalphago_tpu.engine.jaxgo import GoConfig
    from rocalphago_tpu.models import CNNPolicy
    from rocalphago_tpu.search.selfplay import make_selfplay_chunked

    ap = std_parser(__doc__)
    ap.add_argument("--batch-sweep", default=None, metavar="B1,B2,...",
                    help="measure a comma-separated list of batch "
                    "sizes (one result line each)")
    ap.add_argument("--seed-plies", type=int, default=80,
                    help="mid-game depth of the seed states")
    ap.add_argument("--plies", type=int, default=None,
                    help="plies per timed segment (the timed segment "
                    "is ONE device program — keep plies × per-ply "
                    "cost under the ~40s TPU watchdog; default 5 on "
                    "TPU, 10 elsewhere)")
    ap.add_argument("--cap-ab", action="store_true",
                    help="run the playout-cap A/B instead of the ply-"
                    "program bench: MCTS self-play games/min at each "
                    "--cap-p value (docs/PERFORMANCE.md)")
    ap.add_argument("--cap-p", default="1.0,0.25", metavar="P1,P2,...",
                    help="full-search probabilities to sweep in the "
                    "cap A/B (1.0 = every move full, the baseline)")
    ap.add_argument("--sims", type=int, default=32,
                    help="full search budget per move (cap A/B)")
    ap.add_argument("--move-limit", type=int, default=24,
                    help="plies per game (cap A/B)")
    args = ap.parse_args()
    if args.cap_ab:
        run_cap_ab(args)
        return
    on_tpu = jax.devices()[0].platform == "tpu"
    if args.plies is None:
        args.plies = 5 if on_tpu else 10
    batches = ([int(b) for b in args.batch_sweep.split(",")]
               if args.batch_sweep else [args.batch or
                                         (64 if on_tpu else 8)])
    cfg = GoConfig(size=args.board)
    net = CNNPolicy(board=args.board, layers=12, filters_per_layer=128)

    # one seed run at the largest batch; smaller candidates slice it
    # (slicing, not tiling, keeps the slowest-board tail realistic).
    # Seed chunk 5: per-ply cost at the largest batch is exactly what
    # this benchmark exists to measure, i.e. unknown — 5-ply segments
    # keep even a several-s/ply surprise under the ~40s TPU worker
    # watchdog (same policy as bench.py's seeding)
    seed_batch = max(batches)
    seed = make_selfplay_chunked(
        cfg, net.feature_list, net.module.apply, net.module.apply,
        seed_batch, args.seed_plies, chunk=5, score_on_device=False)
    mid = seed(net.params, net.params, jax.random.key(0)).final
    jax.device_get(mid.board)

    for batch in batches:
        states = jax.tree.map(lambda x: x[:batch], mid)
        run = make_selfplay_chunked(
            cfg, net.feature_list, net.module.apply, net.module.apply,
            batch, args.plies, chunk=args.plies,
            score_on_device=False)
        from rocalphago_tpu.features.incremental import init_caches
        from rocalphago_tpu.search.selfplay import incremental_default

        # the segment's carry layout follows the encode-incr knob:
        # a cold cache slab when the delta path is traced in, None
        # for the from-scratch encoder
        caches0 = (init_caches(cfg, batch) if incremental_default()
                   else None)
        flops = program_flops(
            run.segment, net.params, net.params, states, caches0,
            jax.random.key(0), jnp.int32(0), length=args.plies)

        def once():
            res = run(net.params, net.params, jax.random.key(1),
                      initial_states=states)
            return jax.device_get(res.final.board)

        dt = timed(once, reps=args.reps, profile_dir=args.profile)
        plies_per_s = batch * args.plies / dt
        extra = {}
        if flops:
            extra["flops_per_board_ply"] = round(
                flops / (batch * args.plies))
            u = mfu(flops / dt)
            if u is not None:
                extra["mfu"] = round(u, 4)
        report("selfplay_ply_program", plies_per_s, "board-plies/s",
               batch=batch, board=args.board,
               seed_plies=args.seed_plies, **extra)

    # pipelined-vs-sync A/B over a MULTI-segment run (the single-
    # segment program above has no chunk boundary to pipeline): four
    # `--plies`-ply segments with the done-poll on, once at depth 0
    # (per-segment host sync — the old behavior) and once at depth 1
    # (one segment in flight while the host reads the LAGGED
    # done-scalar; runtime.pipeline). Same compiled segment program
    # both ways; host_gap_frac = fraction of wall time with nothing
    # in flight.
    import time as _time

    from rocalphago_tpu.runtime.pipeline import ChunkPipeline

    ab_batch = max(batches)
    ab_states = jax.tree.map(lambda x: x[:ab_batch], mid)
    ab_run = make_selfplay_chunked(
        cfg, net.feature_list, net.module.apply, net.module.apply,
        ab_batch, args.plies * 4, chunk=args.plies,
        score_on_device=False)
    for depth in (0, 1):
        pipe = ChunkPipeline(depth=depth, runner="bench_selfplay")

        def once_ab():
            res = ab_run(net.params, net.params, jax.random.key(2),
                         initial_states=ab_states,
                         stop_when_done=True, pipeline=pipe)
            return jax.device_get(res.final.board)

        once_ab()                        # warmup/compile rep
        pipe.reset_stats()
        t0 = _time.time()
        for _ in range(args.reps):
            once_ab()
        dt = (_time.time() - t0) / args.reps
        report("selfplay_pipeline", ab_batch * args.plies * 4 / dt,
               "board-plies/s", batch=ab_batch, board=args.board,
               seed_plies=args.seed_plies, pipeline_depth=depth,
               host_gap_frac=round(pipe.host_gap_frac, 4))


if __name__ == "__main__":
    main()
