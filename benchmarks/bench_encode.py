"""Encode-path A/B harness: gating × phase-1 depth × chase formulation.

The 48-plane encode is the self-play ceiling and the two ladder planes
are ~93% of it (BENCH_RESULTS.md "Bottleneck analysis") — yet until
this harness every encode knob was a platform heuristic. This measures
each configuration of the three axes that matter and records one
results.jsonl row per config, so the defaults in
``features/ladders.py`` are set from numbers (the
``jaxgo._dense_engine`` discipline):

* **gating** — ``shared`` (the pooled, gated capture+escape chase of
  ``ladders.ladder_planes``) vs ``split`` (the legacy per-plane
  chases; ``$ROCALPHAGO_LADDER_GATE``);
* **phase1** — the two-phase chase schedule's lockstep depth
  (``$ROCALPHAGO_LADDER_PHASE1``; a value ≥ ladder depth recovers the
  old single-phase FIXED-RUNG read — the baseline the gated/early-exit
  path is judged against);
* **impl** — ``xla`` (batch-lockstep while_loop) vs ``pallas`` (the
  per-lane TPU kernel ``ops/chase.py``; ``interpret`` runs it in the
  Pallas interpreter — correctness-only, not perf-comparable).

Every row carries ``us_per_pos`` (per-position microseconds — the
unit ``scripts/bench_report.py``'s encode column renders) plus the
axis fields, and one ``encode_noladder`` row measures the same batch
without the ladder planes so the ladder share of encode is a recorded
number, not folklore. The env knobs are read at TRACE time, so each
config traces a fresh program — the A/B never reuses a stale cached
trace. TPU rows: the ``encode_*`` steps in
``scripts/tpu_window_hunter2.sh`` run this harness per config in the
next healthy window.

TRAJECTORY rows (``--trajectory``, PR 6): self-play and MCTS visit
SUCCESSIVE positions, so the batched mid-game measurement above is
the wrong model for the sequential hot paths — this mode replays a
real random-game tail position by position and A/Bs the incremental
encoder (``features/incremental.py``, ``encode_incr`` rows, cache
carried ply to ply) against the from-scratch encode (
``encode_scratch``), µs/pos each; ``encode_incr`` additionally
records the speedup as ``vs_baseline`` (incr rate ÷ scratch rate).
``--traj-batch`` adds the batched-lockstep pair
(``encode_incr_batched`` / ``encode_scratch_batched``) — the numbers
behind ``selfplay.incremental_default``'s measured default. TPU rows:
``encode_incr*`` hunter steps.
"""

from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, ".")
from benchmarks._harness import (  # noqa: E402
    random_game_states,
    report,
    std_parser,
    timed,
)


def _game_tail(cfg, skip: int, plies: int, rng_key):
    """One REAL game's successive positions (uniform random legal
    policy, the same move model as ``random_game_states``): a host
    list of ``plies`` single GoStates, positions ``skip+1 .. skip+plies``
    of the game — the sequential stream the incremental encoder is
    built for."""
    import jax
    import jax.numpy as jnp

    from rocalphago_tpu.engine.jaxgo import (
        group_data,
        legal_mask,
        new_state,
        step,
    )

    @jax.jit
    def run(rng):
        def ply(carry, _):
            state, rng = carry
            rng, sub = jax.random.split(rng)
            gd = group_data(cfg, state.board,
                            with_zxor=cfg.enforce_superko,
                            labels=state.labels)
            legal = legal_mask(cfg, state, gd)[:-1]
            logits = jnp.where(legal, 0.0, -1e30)
            action = jnp.where(
                legal.any(), jax.random.categorical(sub, logits),
                cfg.num_points).astype(jnp.int32)
            new = step(cfg, state, action, gd)
            return (new, rng), new

        _, states = jax.lax.scan(ply, (new_state(cfg), rng),
                                 length=skip + plies)
        return jax.tree.map(lambda x: x[skip:], states)

    stacked = jax.block_until_ready(run(rng_key))
    return [jax.tree.map(lambda x, i=i: x[i], stacked)
            for i in range(plies)]


def _trajectory_ab(cfg, args) -> None:
    """Sequential (and optionally batched-lockstep) trajectory A/B —
    see the module docstring's TRAJECTORY paragraph."""
    import functools

    import jax

    from rocalphago_tpu.features import incremental as incr
    from rocalphago_tpu.features.planes import encode
    from benchmarks._harness import random_game_states

    slot_kw = ({"ladder_chase_slots": args.slots}
               if args.slots is not None else {})
    plies = args.traj_plies
    states_seq = _game_tail(cfg, args.traj_skip, plies,
                            jax.random.key(0))

    enc = jax.jit(functools.partial(
        encode, cfg, ladder_depth=args.depth, **slot_kw))
    step_fn = jax.jit(lambda s, c: incr.encode_step(
        cfg, s, c, ladder_depth=args.depth, **slot_kw))
    cache0 = incr.init_cache(cfg)

    def run_scratch():
        out = None
        for st in states_seq:
            out = enc(st)
        return jax.device_get(out)

    last = {}

    def run_incr():
        # cache cold at the tail start each rep (honest: the warmup
        # ply is in the average, amortized over the tail)
        cache, out = cache0, None
        for st in states_seq:
            out, cache = step_fn(st, cache)
        last["stats"] = jax.device_get(cache.stats)
        return jax.device_get(out)

    dt_s = timed(run_scratch, reps=args.reps)
    rate_s = plies / dt_s
    report("encode_scratch", rate_s, "positions/s",
           board=args.board, plies=plies,
           us_per_pos=round(1e6 * dt_s / plies, 1))
    dt_i = timed(run_incr, reps=args.reps)
    report("encode_incr", plies / dt_i, "positions/s",
           baseline=rate_s, board=args.board, plies=plies,
           us_per_pos=round(1e6 * dt_i / plies, 1))
    # the invalidation cascade behind the incr number (one rep's
    # device-side stat vector): how many footprint hits the coarse
    # region keys let through, how many survived the cell test as
    # real invalidations, and how many chases a flipped dormant
    # verdict forced — the tentpole's tightening, as a recorded row
    s = {f: int(v) for f, v in zip(incr.STAT_FIELDS, last["stats"])}
    report("encode_incr_cascade",
           s["entries_invalidated"] / plies, "invalidations/ply",
           board=args.board, plies=plies,
           foot_hits=s["foot_hits"],
           verdict_flips=s["verdict_flips"],
           entries_revived=s["entries_revived"],
           chases_run=s["chases_run"],
           verdicts_reused=s["verdicts_reused"],
           lanes_refreshed=s["lanes_refreshed"])

    if not args.traj_batch:
        return
    from rocalphago_tpu.features.planes import batched_encoder
    from rocalphago_tpu.features import DEFAULT_FEATURES

    b = args.traj_batch
    mid = jax.block_until_ready(random_game_states(
        cfg, b, args.traj_skip, jax.random.key(1)))
    benc = jax.jit(batched_encoder(cfg, DEFAULT_FEATURES, **slot_kw))
    bdenc = jax.jit(incr.batched_delta_encoder(
        cfg, DEFAULT_FEATURES, **slot_kw))
    caches0 = incr.init_caches(cfg, b)
    actions = _random_action_stepper(cfg, b)

    def run_batch(encoder, with_cache):
        def go():
            states, caches, out = mid, caches0, None
            rng = jax.random.key(2)
            for _ in range(plies):
                if with_cache:
                    out, caches = encoder(states, caches)
                else:
                    out = encoder(states)
                states, rng = actions(states, rng)
            return jax.device_get(out)

        return go

    dt_bs = timed(run_batch(benc, False), reps=args.reps)
    rate_bs = b * plies / dt_bs
    report("encode_scratch_batched", rate_bs, "positions/s",
           batch=b, board=args.board, plies=plies,
           us_per_pos=round(1e6 * dt_bs / (b * plies), 1))
    dt_bi = timed(run_batch(bdenc, True), reps=args.reps)
    report("encode_incr_batched", b * plies / dt_bi, "positions/s",
           baseline=rate_bs, batch=b, board=args.board, plies=plies,
           us_per_pos=round(1e6 * dt_bi / (b * plies), 1))


def _random_action_stepper(cfg, batch: int):
    """Jitted ``(states, rng) -> (states', rng')`` — one uniform
    random-legal lockstep ply (the batched trajectory's move model)."""
    import functools

    import jax
    import jax.numpy as jnp

    from rocalphago_tpu.engine.jaxgo import (
        legal_mask,
        step,
        vgroup_data,
    )

    vstep = jax.vmap(functools.partial(step, cfg))
    vlegal = jax.vmap(functools.partial(legal_mask, cfg))
    vgd = vgroup_data(cfg, with_zxor=cfg.enforce_superko)

    @jax.jit
    def go(states, rng):
        rng, sub = jax.random.split(rng)
        gd = vgd(states)
        legal = vlegal(states, gd)[:, :-1]
        logits = jnp.where(legal, 0.0, -1e30)
        action = jnp.where(
            legal.any(-1), jax.random.categorical(sub, logits, axis=-1),
            cfg.num_points).astype(jnp.int32)
        return vstep(states, action, gd), rng

    return go


def main() -> None:
    import jax

    from rocalphago_tpu.engine.jaxgo import GoConfig
    from rocalphago_tpu.features import DEFAULT_FEATURES
    from rocalphago_tpu.features.planes import encode

    ap = std_parser(__doc__)
    ap.add_argument("--gating", default="shared",
                    help="comma list: shared,split")
    ap.add_argument("--phase1", default="4",
                    help="comma list of phase-1 depths (>= --depth "
                         "recovers the single-phase fixed-rung read)")
    ap.add_argument("--impl", default="xla",
                    help="comma list: xla,pallas,interpret")
    ap.add_argument("--depth", type=int, default=40)
    ap.add_argument("--slots", type=int, default=None,
                    help="ladder_chase_slots override (default: the "
                         "encoder's measured default)")
    ap.add_argument("--skip-noladder", action="store_true")
    ap.add_argument("--trajectory", action="store_true",
                    help="sequential-trajectory A/B: encode_incr vs "
                         "encode_scratch over a real game tail "
                         "(µs/pos), instead of the batched axes sweep")
    ap.add_argument("--traj-plies", type=int, default=80,
                    help="tail length (positions encoded per rep)")
    ap.add_argument("--traj-skip", type=int, default=40,
                    help="opening plies skipped before the tail")
    ap.add_argument("--traj-batch", type=int, default=0,
                    help="also run the batched-lockstep trajectory "
                         "pair at this game batch (0 = skip)")
    args = ap.parse_args()
    batch = args.batch or (256 if jax.devices()[0].platform == "tpu"
                           else 16)
    cfg = GoConfig(size=args.board)

    if args.trajectory:
        _trajectory_ab(cfg, args)
        return

    # mid-game positions: 120 random-legal plies — dense boards with
    # real multi-ladder structure, the encode's stressed case
    states = jax.block_until_ready(
        random_game_states(cfg, batch, 120, jax.random.key(0)))

    slot_kw = ({"ladder_chase_slots": args.slots}
               if args.slots is not None else {})

    def build(features):
        # a fresh partial per config → a fresh trace, so the env
        # knobs (read at trace time) really take effect per row
        return jax.jit(jax.vmap(functools.partial(
            encode, cfg, features=features,
            ladder_depth=args.depth, **slot_kw)))

    def measure(features):
        enc = build(features)
        return timed(lambda: jax.device_get(enc(states)),
                     reps=args.reps, profile_dir=None)

    if not args.skip_noladder:
        no_ladder = tuple(f for f in DEFAULT_FEATURES
                          if not f.startswith("ladder"))
        dt = measure(no_ladder)
        report("encode_noladder", batch / dt, "positions/s",
               batch=batch, board=args.board,
               us_per_pos=round(1e6 * dt / batch, 1))
        # the same floor reached the way an operator reaches it: the
        # ROCALPHAGO_LADDER_PLANES=off feature-spec path (the
        # ladder-free self-play configuration). Must land within 1.5×
        # of the raw no-ladder row above — the knob path adds no
        # hidden tax, it just drops the planes from the spec.
        from rocalphago_tpu.features.pyfeatures import active_features

        prev = os.environ.get("ROCALPHAGO_LADDER_PLANES")
        os.environ["ROCALPHAGO_LADDER_PLANES"] = "off"
        try:
            lf = active_features(DEFAULT_FEATURES)
            dt = measure(lf)
            report("encode_noladder_net", batch / dt, "positions/s",
                   batch=batch, board=args.board,
                   ladder_planes="off", planes=len(lf),
                   us_per_pos=round(1e6 * dt / batch, 1))
        finally:
            if prev is None:
                os.environ.pop("ROCALPHAGO_LADDER_PLANES", None)
            else:
                os.environ["ROCALPHAGO_LADDER_PLANES"] = prev

    impl_env = {"xla": "", "pallas": "1", "interpret": "interpret"}
    for impl in args.impl.split(","):
        if impl not in impl_env:
            print(f"bench_encode: unknown impl {impl!r}",
                  file=sys.stderr)
            continue
        for gating in args.gating.split(","):
            for phase1 in (int(p) for p in args.phase1.split(",")):
                os.environ["ROCALPHAGO_PALLAS_CHASE"] = impl_env[impl]
                os.environ["ROCALPHAGO_LADDER_GATE"] = gating
                os.environ["ROCALPHAGO_LADDER_PHASE1"] = str(phase1)
                t0 = time.time()
                try:
                    dt = measure(DEFAULT_FEATURES)
                except Exception as e:  # noqa: BLE001 — keep the sweep
                    print(f"bench_encode: {impl}/{gating}/p{phase1} "
                          f"failed after {time.time() - t0:.0f}s: "
                          f"{type(e).__name__}: {e}", file=sys.stderr)
                    continue
                report("encode_ab", batch / dt, "positions/s",
                       batch=batch, board=args.board,
                       gating=gating, phase1=phase1, chase_impl=impl,
                       us_per_pos=round(1e6 * dt / batch, 1),
                       **({"slots": args.slots}
                          if args.slots is not None else {}))


if __name__ == "__main__":
    main()
