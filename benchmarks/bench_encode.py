"""Encode-path A/B harness: gating × phase-1 depth × chase formulation.

The 48-plane encode is the self-play ceiling and the two ladder planes
are ~93% of it (BENCH_RESULTS.md "Bottleneck analysis") — yet until
this harness every encode knob was a platform heuristic. This measures
each configuration of the three axes that matter and records one
results.jsonl row per config, so the defaults in
``features/ladders.py`` are set from numbers (the
``jaxgo._dense_engine`` discipline):

* **gating** — ``shared`` (the pooled, gated capture+escape chase of
  ``ladders.ladder_planes``) vs ``split`` (the legacy per-plane
  chases; ``$ROCALPHAGO_LADDER_GATE``);
* **phase1** — the two-phase chase schedule's lockstep depth
  (``$ROCALPHAGO_LADDER_PHASE1``; a value ≥ ladder depth recovers the
  old single-phase FIXED-RUNG read — the baseline the gated/early-exit
  path is judged against);
* **impl** — ``xla`` (batch-lockstep while_loop) vs ``pallas`` (the
  per-lane TPU kernel ``ops/chase.py``; ``interpret`` runs it in the
  Pallas interpreter — correctness-only, not perf-comparable).

Every row carries ``us_per_pos`` (per-position microseconds — the
unit ``scripts/bench_report.py``'s encode column renders) plus the
axis fields, and one ``encode_noladder`` row measures the same batch
without the ladder planes so the ladder share of encode is a recorded
number, not folklore. The env knobs are read at TRACE time, so each
config traces a fresh program — the A/B never reuses a stale cached
trace. TPU rows: the ``encode_*`` steps in
``scripts/tpu_window_hunter2.sh`` run this harness per config in the
next healthy window.
"""

from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, ".")
from benchmarks._harness import (  # noqa: E402
    random_game_states,
    report,
    std_parser,
    timed,
)


def main() -> None:
    import jax

    from rocalphago_tpu.engine.jaxgo import GoConfig
    from rocalphago_tpu.features import DEFAULT_FEATURES
    from rocalphago_tpu.features.planes import encode

    ap = std_parser(__doc__)
    ap.add_argument("--gating", default="shared",
                    help="comma list: shared,split")
    ap.add_argument("--phase1", default="4",
                    help="comma list of phase-1 depths (>= --depth "
                         "recovers the single-phase fixed-rung read)")
    ap.add_argument("--impl", default="xla",
                    help="comma list: xla,pallas,interpret")
    ap.add_argument("--depth", type=int, default=40)
    ap.add_argument("--slots", type=int, default=None,
                    help="ladder_chase_slots override (default: the "
                         "encoder's measured default)")
    ap.add_argument("--skip-noladder", action="store_true")
    args = ap.parse_args()
    batch = args.batch or (256 if jax.devices()[0].platform == "tpu"
                           else 16)
    cfg = GoConfig(size=args.board)

    # mid-game positions: 120 random-legal plies — dense boards with
    # real multi-ladder structure, the encode's stressed case
    states = jax.block_until_ready(
        random_game_states(cfg, batch, 120, jax.random.key(0)))

    slot_kw = ({"ladder_chase_slots": args.slots}
               if args.slots is not None else {})

    def build(features):
        # a fresh partial per config → a fresh trace, so the env
        # knobs (read at trace time) really take effect per row
        return jax.jit(jax.vmap(functools.partial(
            encode, cfg, features=features,
            ladder_depth=args.depth, **slot_kw)))

    def measure(features):
        enc = build(features)
        return timed(lambda: jax.device_get(enc(states)),
                     reps=args.reps, profile_dir=None)

    if not args.skip_noladder:
        no_ladder = tuple(f for f in DEFAULT_FEATURES
                          if not f.startswith("ladder"))
        dt = measure(no_ladder)
        report("encode_noladder", batch / dt, "positions/s",
               batch=batch, board=args.board,
               us_per_pos=round(1e6 * dt / batch, 1))

    impl_env = {"xla": "", "pallas": "1", "interpret": "interpret"}
    for impl in args.impl.split(","):
        if impl not in impl_env:
            print(f"bench_encode: unknown impl {impl!r}",
                  file=sys.stderr)
            continue
        for gating in args.gating.split(","):
            for phase1 in (int(p) for p in args.phase1.split(",")):
                os.environ["ROCALPHAGO_PALLAS_CHASE"] = impl_env[impl]
                os.environ["ROCALPHAGO_LADDER_GATE"] = gating
                os.environ["ROCALPHAGO_LADDER_PHASE1"] = str(phase1)
                t0 = time.time()
                try:
                    dt = measure(DEFAULT_FEATURES)
                except Exception as e:  # noqa: BLE001 — keep the sweep
                    print(f"bench_encode: {impl}/{gating}/p{phase1} "
                          f"failed after {time.time() - t0:.0f}s: "
                          f"{type(e).__name__}: {e}", file=sys.stderr)
                    continue
                report("encode_ab", batch / dt, "positions/s",
                       batch=batch, board=args.board,
                       gating=gating, phase1=phase1, chase_impl=impl,
                       us_per_pos=round(1e6 * dt / batch, 1),
                       **({"slots": args.slots}
                          if args.slots is not None else {}))


if __name__ == "__main__":
    main()
