"""Fully on-device MCTS throughput (sims/s across the game batch).

The host-tree search (``bench_mcts.py``) pays a host↔device round
trip per leaf wave; ``search.device_mcts`` runs the entire search —
tree, select, expand, evaluate, backup — as one jitted program, with
every simulation evaluating the whole game batch in lockstep. This
measures batched search throughput: total simulations (batch × n_sim)
per second, the number that matters for self-play generation where
many games search simultaneously.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from benchmarks._harness import report, std_parser  # noqa: E402


def main() -> None:
    import jax

    from rocalphago_tpu.engine.jaxgo import GoConfig, new_states
    from rocalphago_tpu.models import CNNPolicy, CNNValue
    from rocalphago_tpu.search.device_mcts import make_device_mcts

    ap = std_parser(__doc__)
    ap.add_argument("--sims", type=int, default=64)
    ap.add_argument("--max-nodes", type=int, default=None,
                    help="tree slab capacity (default: 2x sims)")
    ap.add_argument("--gumbel", action="store_true",
                    help="Gumbel sequential-halving root search "
                         "(make_gumbel_mcts) instead of PUCT")
    args = ap.parse_args()
    on_tpu = jax.devices()[0].platform == "tpu"
    batch = args.batch or (16 if on_tpu else 4)
    make = make_device_mcts
    plan_sims = args.sims
    if args.gumbel:
        from rocalphago_tpu.search.device_mcts import (
            gumbel_plan_sims,
            make_gumbel_mcts,
        )

        make = make_gumbel_mcts
        # the halving plan can exceed the requested sims at small
        # budgets — size the slab (and report) from the real count,
        # or the bench would measure a capacity-saturated search
        plan_sims = gumbel_plan_sims(args.sims, 16,
                                     args.board ** 2 + 1)
    max_nodes = args.max_nodes or 2 * plan_sims

    policy = CNNPolicy(board=args.board, layers=12,
                       filters_per_layer=128)
    value = CNNValue(board=args.board, layers=12, filters_per_layer=128)
    search = make(
        GoConfig(size=args.board), policy.feature_list,
        value.feature_list, policy.module.apply, value.module.apply,
        n_sim=args.sims, max_nodes=max_nodes)
    roots = new_states(GoConfig(size=args.board), batch)

    # chunked driving: one compiled program per chunk of simulations,
    # tree device-resident between calls — the ~40s worker watchdog
    # must never see the whole search as one program. Off-TPU the
    # chunk still splits the search so the pipelined-vs-sync A/B
    # below measures real chunk boundaries.
    chunk = 8 if on_tpu else max(1, args.sims // 4)
    rng = [jax.random.key(0)]

    def once(pipe):
        if args.gumbel:
            rng[0], sub = jax.random.split(rng[0])
            visits, _, _, _ = search.run_chunked(
                policy.params, value.params, roots, sub, chunk,
                pipeline=pipe)
        else:
            visits, _ = search.run_chunked(policy.params,
                                           value.params, roots, chunk,
                                           pipeline=pipe)
        return jax.device_get(visits)

    # pipelined-vs-sync A/B: depth 0 = the old per-chunk host sync,
    # depth 1 = one chunk in flight while the host decides
    # (runtime.pipeline). Same compiled programs either way — the A/B
    # pays no extra compiles; host_gap_frac is the fraction of wall
    # time the device had nothing in flight.
    import time as _time

    from rocalphago_tpu.runtime.pipeline import ChunkPipeline

    for depth in (0, 1):
        pipe = ChunkPipeline(depth=depth, runner="bench_device_mcts")
        once(pipe)                       # warmup/compile rep
        pipe.drain()                     # clear the async tail
        pipe.reset_stats()
        if args.profile and depth == 1:
            jax.profiler.start_trace(args.profile)
        t0 = _time.time()
        for _ in range(args.reps):
            once(pipe)
        pipe.drain()
        dt = (_time.time() - t0) / args.reps
        if args.profile and depth == 1:
            jax.profiler.stop_trace()
        report("device_mcts_sims", batch * plan_sims / dt, "sims/s",
               batch=batch, sims=plan_sims, max_nodes=max_nodes,
               board=args.board, gumbel=args.gumbel,
               pipeline_depth=depth,
               host_gap_frac=round(pipe.host_gap_frac, 4))


if __name__ == "__main__":
    main()
