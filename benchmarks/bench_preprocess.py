"""48-plane feature-encoder throughput (positions/s).

The reference's ``preprocess_benchmark.py`` profiled its hottest
function — per-state Python featurization (SURVEY.md §2 "Benchmarks",
§3.2). The rebuild's encoder is a vmapped jitted program over batched
device states; this measures end-to-end positions/s on mid-game boards.
"""

from __future__ import annotations

import functools
import sys

sys.path.insert(0, ".")
from benchmarks._harness import report, std_parser, timed  # noqa: E402


def main() -> None:
    import jax
    import jax.numpy as jnp

    from rocalphago_tpu.engine.jaxgo import GoConfig, new_states, step
    from rocalphago_tpu.features import DEFAULT_FEATURES
    from rocalphago_tpu.features.planes import encode

    args = std_parser(__doc__).parse_args()
    batch = args.batch or (256 if jax.devices()[0].platform == "tpu"
                           else 32)
    cfg = GoConfig(size=args.board)

    # build mid-game positions: 120 random-legal plies
    vstep = jax.vmap(functools.partial(step, cfg))

    @jax.jit
    def fill(rng):
        states = new_states(cfg, batch)

        def ply(carry, _):
            states, rng = carry
            rng, sub = jax.random.split(rng)
            from rocalphago_tpu.engine.jaxgo import legal_mask
            legal = jax.vmap(
                functools.partial(legal_mask, cfg))(states)[:, :-1]
            logits = jnp.where(legal, 0.0, -1e30)
            action = jax.random.categorical(sub, logits, axis=-1)
            action = jnp.where(legal.any(-1), action,
                               cfg.num_points).astype(jnp.int32)
            return (vstep(states, action), rng), None

        (states, _), _ = jax.lax.scan(ply, (states, rng),
                                      length=120)
        return states

    states = jax.block_until_ready(fill(jax.random.key(0)))
    enc = jax.jit(jax.vmap(
        functools.partial(encode, cfg, features=DEFAULT_FEATURES)))

    dt = timed(lambda: jax.device_get(enc(states)), reps=args.reps,
               profile_dir=args.profile)
    report("preprocess_48planes", batch / dt, "positions/s",
           batch=batch, board=args.board)


if __name__ == "__main__":
    main()
