"""48-plane feature-encoder throughput (positions/s).

The reference's ``preprocess_benchmark.py`` profiled its hottest
function — per-state Python featurization (SURVEY.md §2 "Benchmarks",
§3.2). The rebuild's encoder is a vmapped jitted program over batched
device states; this measures end-to-end positions/s on mid-game boards.
"""

from __future__ import annotations

import functools
import sys

sys.path.insert(0, ".")
from benchmarks._harness import (  # noqa: E402
    random_game_states,
    report,
    std_parser,
    timed,
)


def main() -> None:
    import jax

    from rocalphago_tpu.engine.jaxgo import GoConfig
    from rocalphago_tpu.features import DEFAULT_FEATURES
    from rocalphago_tpu.features.planes import encode

    args = std_parser(__doc__).parse_args()
    batch = args.batch or (256 if jax.devices()[0].platform == "tpu"
                           else 32)
    cfg = GoConfig(size=args.board)

    # mid-game positions: 120 random-legal plies
    states = jax.block_until_ready(
        random_game_states(cfg, batch, 120, jax.random.key(0)))
    enc = jax.jit(jax.vmap(
        functools.partial(encode, cfg, features=DEFAULT_FEATURES)))

    dt = timed(lambda: jax.device_get(enc(states)), reps=args.reps,
               profile_dir=args.profile)
    report("preprocess_48planes", batch / dt, "positions/s",
           batch=batch, board=args.board)


if __name__ == "__main__":
    main()
