"""Serving throughput: aggregate moves/sec vs concurrent sessions.

The headline for ``rocalphago_tpu/serve`` (docs/SERVING.md): N
concurrent game sessions, each an on-device PUCT search, served two
ways —

* **batched** — sessions share ONE :class:`~rocalphago_tpu.serve.
  evaluator.BatchingEvaluator`: every simulation's leaf eval is
  coalesced with the other sessions' leaves into one device batch
  (``prepare_sim`` → shared eval → ``apply_sim``);
* **unbatched** (the A/B) — the per-session path: each session runs
  the fused single-game search (``init`` + ``run_sims``), its NN
  evals at batch 1 inside its own compiled program.

Both sides share one compiled searcher (no per-mode compile skew);
measurement starts after an explicit warmup of every program either
side runs. Per (sessions, mode) config one record goes to
``results.jsonl``: aggregate ``moves/s`` (value), p50/p99 per-genmove
latency, and — batched — the evaluator's real batch occupancy.

Defaults are CPU-shaped (the A/B's decision surface: the eval must
dominate the split path's per-row overhead, so the default net is
eval-heavy): board 9, 6×96 convs, 8 sims/move. On one CPU core the
batched curve rises with session count while unbatched stays flat at
its single-session rate — the cross-game economics the serving
subsystem exists for — and saturates once the core runs out of
FLOPs (~64 sessions here; 256 measured flat within noise, which is
why the default sweep stops at 64 — the accelerator continuation is
the ``serve_small``/``serve_fleet`` hunter steps).

``--cache-ab`` replaces the batched/unbatched sweep with the
transposition-cache A/B (docs/SERVING.md "Evaluation cache"): the
same fleet drive run twice — ``eval_cache=False`` vs an attached
:class:`~rocalphago_tpu.serve.evalcache.EvalCache` — over an
opening-replay workload shaped like real fleet traffic: K
deterministic opening lines shared round-robin by the sessions
(in-batch dedup inside one rep) and replayed identically across reps
(cross-rep cache hits). Both arms share one compiled searcher, both
records carry the measured hit rate, the arms' move lists are
asserted identical (cache hits are bit-identical by construction)
and ``jax_compiles_total`` is asserted flat across both measured
phases.

Usage::

    python benchmarks/bench_serve.py [--sessions 1,8,64]
        [--board 9] [--layers 6] [--filters 96] [--sims 8]
        [--moves 2] [--max-wait-us 50000] [--reps 3]
    python benchmarks/bench_serve.py --cache-ab --sessions 16
        [--opening-lines 4] [--opening-moves 6]
"""

from __future__ import annotations

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from benchmarks._harness import report, std_parser  # noqa: E402


def _percentile(sorted_vals, q):
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _run_threads(n, fn):
    """Run ``fn(i)`` in n threads behind one start barrier; returns
    (wall seconds, list of per-call exceptions)."""
    ready = threading.Barrier(n + 1)
    errors: list = []

    def work(i):
        try:
            ready.wait()
            fn(i)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    ready.wait()
    t0 = time.monotonic()
    for t in threads:
        t.join()
    return time.monotonic() - t0, errors


def main():
    ap = std_parser("serving throughput vs concurrent sessions "
                    "(batched evaluator A/B)")
    ap.add_argument("--sessions", default="1,8,64",
                    help="comma list of concurrent-session counts. "
                         "The CPU default stops at 64: on one host "
                         "core the batched path saturates there "
                         "(measured flat ±2%% to 256 — the 256-row "
                         "record and the TPU continuation live in "
                         "the serve_fleet hunter step)")
    ap.add_argument("--layers", type=int, default=6)
    ap.add_argument("--filters", type=int, default=96)
    ap.add_argument("--sims", type=int, default=8,
                    help="simulations per move")
    ap.add_argument("--moves", type=int, default=2,
                    help="genmoves per session per rep")
    ap.add_argument("--max-wait-us", type=float, default=50000.0,
                    help="partial-batch flush age — keep it above "
                         "one convoy period (it only bites when "
                         "sessions stop submitting)")
    ap.add_argument("--max-nodes", type=int, default=None,
                    help="search slab size (default sims+1: the "
                         "exact per-move serving need)")
    ap.add_argument("--skip-unbatched", action="store_true")
    ap.add_argument("--skip-threaded", action="store_true",
                    help="skip the thread-per-session latency-mode "
                         "arm (the batched driver and unbatched A/B "
                         "still run)")
    ap.add_argument("--cache-ab", action="store_true",
                    help="run the transposition-cache A/B (opening-"
                         "replay fleet workload, cache off vs on) "
                         "instead of the batched/unbatched sweep")
    ap.add_argument("--opening-lines", type=int, default=4,
                    help="[cache-ab] distinct deterministic opening "
                         "lines shared round-robin by the sessions")
    ap.add_argument("--opening-moves", type=int, default=6,
                    help="[cache-ab] plies per opening line")
    ap.set_defaults(board=9)   # serving default (std_parser's 19 is
    #                            the training benches' default)
    a = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from rocalphago_tpu.engine import jaxgo, pygo
    from rocalphago_tpu.models import CNNPolicy, CNNValue
    from rocalphago_tpu.search.device_mcts import make_device_mcts
    from rocalphago_tpu.serve.evaluator import default_batch_sizes
    from rocalphago_tpu.serve.sessions import ServePool

    session_counts = [int(s) for s in a.sessions.split(",") if s]
    pol = CNNPolicy(("board", "ones"), board=a.board,
                    layers=a.layers, filters_per_layer=a.filters)
    val = CNNValue(("board", "ones", "color"), board=a.board,
                   layers=a.layers, filters_per_layer=a.filters)
    cfg = pol.cfg
    # ONE compiled searcher for every pool and the unbatched side.
    # Serving slab sizing: a reuse-free per-move search allocates at
    # most root + n_sim nodes, so sims+1 (not the reuse-friendly
    # 2×n_sim default) — at 256 sessions the slab is the cache
    # footprint, and halving it is measurable.
    max_nodes = a.max_nodes or (a.sims + 1)
    searcher = make_device_mcts(cfg, pol.feature_list,
                                val.feature_list, pol.module.apply,
                                val.module.apply, n_sim=a.sims,
                                max_nodes=max_nodes)

    def fresh_game():
        return pygo.GameState(size=a.board, komi=7.5)

    # ---------------- transposition-cache A/B (module docstring) ----
    if a.cache_ab:
        import random

        from rocalphago_tpu.obs.registry import REGISTRY
        from rocalphago_tpu.serve.evalcache import EvalCache

        # K deterministic opening lines: each a fixed pseudo-random
        # legal sequence — sessions share them round-robin (in-batch
        # dedup) and every rep replays them (cross-rep cache hits),
        # the shape of real fleet traffic (shared openings/joseki)
        lines = []
        for k in range(a.opening_lines):
            rng = random.Random(1000 + k)
            st = fresh_game()
            line: list = []
            for _ in range(a.opening_moves):
                legal = st.get_legal_moves(include_eyes=False)
                if not legal:
                    break
                mv = legal[rng.randrange(len(legal))]
                line.append(mv)
                st.do_move(mv)
            lines.append(line)

        def games_for(n_sessions):
            games = []
            for i in range(n_sessions):
                g = fresh_game()
                for mv in lines[i % len(lines)]:
                    g.do_move(mv)
                games.append(g)
            return games

        def compiles():
            return {k: v
                    for k, v in REGISTRY.snapshot()["counters"].items()
                    if k.startswith("jax_compiles_total")}

        for n_sessions in session_counts:
            sizes = default_batch_sizes(cap=n_sessions)
            results = {}
            for arm in ("off", "on"):
                # False force-disables even under the env switch —
                # both arms share the one compiled searcher
                cache = EvalCache() if arm == "on" else False
                pool = ServePool(val, pol, n_sim=a.sims,
                                 max_sessions=n_sessions,
                                 queue_rows=4 * max(sizes),
                                 batch_sizes=sizes,
                                 max_wait_us=a.max_wait_us,
                                 searcher=searcher, eval_cache=cache)
                pool.warm()
                sessions = [pool.open_session(resilient=False)
                            for _ in range(n_sessions)]
                driver = pool.driver(sessions)
                driver.warm()
                snap0 = compiles()
                played: list = []
                t0 = time.monotonic()
                for _ in range(a.reps):
                    games = games_for(n_sessions)
                    for _ in range(a.moves):
                        mvs = driver.genmove_all(games)
                        played.append(list(mvs))
                        for game, mv in zip(games, mvs):
                            game.do_move(mv)
                wall = time.monotonic() - t0
                if compiles() != snap0:
                    raise AssertionError(
                        "jax_compiles_total moved during the measured "
                        f"cache-ab phase (arm={arm}) — warmup gap")
                ev = pool.evaluator.stats()
                if arm == "on":
                    # hit bit-identity probe: a warm cached evaluate
                    # against the direct device eval of the same row
                    import numpy as _np
                    root = jax.tree.map(lambda x: x[None],
                                        jaxgo.from_pygo(cfg, games[0]))
                    d_p, d_v = jax.device_get(
                        pool.evaluator.eval_direct(root))
                    c_p, c_v = pool.evaluator.evaluate(root, rows=1)
                    c_p, c_v = pool.evaluator.evaluate(root, rows=1)
                    if not (_np.array_equal(_np.asarray(c_p),
                                            _np.asarray(d_p))
                            and _np.array_equal(_np.asarray(c_v),
                                                _np.asarray(d_v))):
                        raise AssertionError(
                            "cached eval not bit-identical to direct")
                for s in sessions:
                    s.close()
                pool.close()
                rate = n_sessions * a.moves * a.reps / wall
                results[arm] = (rate, played, ev)
                report("serve_moves_per_s", rate, "moves/s",
                       sessions=n_sessions, mode="batched", cache=arm,
                       hit_rate=ev["cache"]["hit_rate"],
                       dedup_saved=ev["dedup_saved"],
                       occupancy=ev["batch_occupancy"],
                       batch_sizes=",".join(str(s) for s in sizes),
                       max_wait_us=a.max_wait_us, board=a.board,
                       layers=a.layers, filters=a.filters,
                       sims=a.sims, moves=a.moves, reps=a.reps,
                       opening_lines=a.opening_lines)
            if results["off"][1] != results["on"][1]:
                raise AssertionError(
                    "cache on/off move divergence — cache hits must "
                    "be bit-identical to device evals")
            report("serve_cache_speedup",
                   results["on"][0] / results["off"][0], "x",
                   sessions=n_sessions,
                   hit_rate=results["on"][2]["cache"]["hit_rate"],
                   board=a.board, layers=a.layers, filters=a.filters,
                   sims=a.sims, moves=a.moves, reps=a.reps,
                   opening_lines=a.opening_lines)
        return

    def unbatched_move(state):
        """The per-session fused path: one init + one k-sim program."""
        root = jaxgo.from_pygo(cfg, state)
        roots = jax.tree.map(lambda x: x[None], root)
        tree = searcher.init(pol.params, val.params, roots)
        tree = searcher.run_sims(pol.params, val.params, tree,
                                 k=a.sims)
        visits, _ = searcher.root_stats(tree)
        counts = np.asarray(jax.device_get(visits))[0]
        action = int(counts.argmax())
        if action >= cfg.num_points or counts[action] == 0:
            return None
        from rocalphago_tpu.utils.coords import unflatten_idx

        return unflatten_idx(action, cfg.size)

    # warm the unbatched programs once (compile excluded everywhere)
    if not a.skip_unbatched:
        unbatched_move(fresh_game())

    common = dict(board=a.board, layers=a.layers, filters=a.filters,
                  sims=a.sims, moves=a.moves)

    for n_sessions in session_counts:
        sizes = default_batch_sizes(cap=n_sessions)
        pool = ServePool(val, pol, n_sim=a.sims,
                         max_sessions=n_sessions,
                         queue_rows=4 * max(sizes),
                         batch_sizes=sizes,
                         max_wait_us=a.max_wait_us,
                         searcher=searcher)
        pool.warm()
        sessions = [pool.open_session(resilient=False)
                    for _ in range(n_sessions)]

        # ---- batched: the fleet driver — every simulation one
        # cross-game convoy through the shared evaluator
        driver = pool.driver(sessions)
        driver.warm()
        best = None
        for _ in range(a.reps):
            lats: list = []
            games = [fresh_game() for _ in range(n_sessions)]
            t_rep = time.monotonic()
            for _ in range(a.moves):
                t0 = time.monotonic()
                moves = driver.genmove_all(games)
                dt = time.monotonic() - t0
                lats.extend([dt] * n_sessions)
                for game, mv in zip(games, moves):
                    game.do_move(mv)
            wall = time.monotonic() - t_rep
            rate = n_sessions * a.moves / wall
            if best is None or rate > best[0]:
                best = (rate, sorted(lats))
        stats = pool.evaluator.stats()
        rate, lats = best
        report("serve_moves_per_s", rate, "moves/s",
               sessions=n_sessions, mode="batched",
               p50_s=round(_percentile(lats, 0.50), 4),
               p99_s=round(_percentile(lats, 0.99), 4),
               occupancy=stats["batch_occupancy"],
               batch_sizes=",".join(str(s) for s in sizes),
               max_wait_us=a.max_wait_us, **common)

        # ---- threaded: the latency-mode A/B — one thread per
        # session, per-sim leaf submits coalesced by the dispatcher
        if not a.skip_threaded:
            best = None
            for _ in range(a.reps):
                lats = []
                lat_lock = threading.Lock()
                games = [fresh_game() for _ in range(n_sessions)]

                def play(i):
                    game = games[i]
                    for _ in range(a.moves):
                        t0 = time.monotonic()
                        mv = sessions[i].get_move(game)
                        dt = time.monotonic() - t0
                        with lat_lock:
                            lats.append(dt)
                        game.do_move(mv)

                wall, errors = _run_threads(n_sessions, play)
                if errors:
                    raise errors[0]
                rate = n_sessions * a.moves / wall
                if best is None or rate > best[0]:
                    best = (rate, sorted(lats))
            rate, lats = best
            report("serve_moves_per_s", rate, "moves/s",
                   sessions=n_sessions, mode="threaded",
                   p50_s=round(_percentile(lats, 0.50), 4),
                   p99_s=round(_percentile(lats, 0.99), 4),
                   occupancy=pool.evaluator.stats()[
                       "batch_occupancy"],
                   max_wait_us=a.max_wait_us, **common)
        for s in sessions:
            s.close()
        pool.close()

        # ---- unbatched A/B: same sessions, fused per-game search
        if a.skip_unbatched:
            continue
        best = None
        for _ in range(a.reps):
            lats = []
            lat_lock = threading.Lock()
            games = [fresh_game() for _ in range(n_sessions)]

            def play_unbatched(i):
                game = games[i]
                for _ in range(a.moves):
                    t0 = time.monotonic()
                    mv = unbatched_move(game)
                    dt = time.monotonic() - t0
                    with lat_lock:
                        lats.append(dt)
                    game.do_move(mv)

            wall, errors = _run_threads(n_sessions, play_unbatched)
            if errors:
                raise errors[0]
            rate = n_sessions * a.moves / wall
            if best is None or rate > best[0]:
                best = (rate, sorted(lats))
        rate, lats = best
        report("serve_moves_per_s", rate, "moves/s",
               sessions=n_sessions, mode="unbatched",
               p50_s=round(_percentile(lats, 0.50), 4),
               p99_s=round(_percentile(lats, 0.99), 4), **common)


if __name__ == "__main__":
    main()
