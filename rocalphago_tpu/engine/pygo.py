"""Pure-Python Go rules oracle.

Mirrors the reference engine's public API (``AlphaGo/go.py::GameState`` —
``do_move``, ``is_legal``, ``get_legal_moves``, ``get_winner``, ``copy``,
``is_eye``, constants ``BLACK/WHITE/EMPTY/PASS_MOVE``; SURVEY.md §1 L0).
This implementation is host-side and deliberately simple: it is the
correctness oracle that the vectorized device engine
(:mod:`rocalphago_tpu.engine.jaxgo`) is differential-tested against, and
the bookkeeping engine behind SGF replay and the GTP adapter.

Rules: positional superko (optional, simple-ko always), suicide illegal,
two consecutive passes end the game, area (Chinese) scoring with komi.

Positions are identified by the same incremental uint32[2] Zobrist
hash the device engine carries (shared tables in
:mod:`rocalphago_tpu.engine.zobrist`, fixed seed): superko is hash
membership, and the hash crosses the ``jaxgo.from_pygo`` bridge
verbatim instead of being recomputed — pinned by the cross-engine
parity test in ``tests/test_pygo.py``.
"""

from __future__ import annotations

import numpy as np

from rocalphago_tpu.engine import zobrist as zobrist_tables

BLACK = 1
WHITE = -1
EMPTY = 0
PASS_MOVE = None

_NEIGHBOR_OFFSETS = ((1, 0), (-1, 0), (0, 1), (0, -1))
_DIAGONAL_OFFSETS = ((1, 1), (1, -1), (-1, 1), (-1, -1))


class IllegalMove(Exception):
    pass


class Suicide(IllegalMove):
    pass


class GameState:
    """Mutable Go position with full rules bookkeeping.

    Parameters
    ----------
    size : board edge length (default 19).
    komi : compensation added to White's area score.
    enforce_superko : if True, forbid recreating any earlier whole-board
        position (positional superko); simple ko is always enforced.
    """

    def __init__(self, size: int = 19, komi: float = 7.5,
                 enforce_superko: bool = False):
        self.size = size
        self.komi = komi
        self.enforce_superko = enforce_superko
        self.board = np.zeros((size, size), dtype=np.int8)
        self.current_player = BLACK
        self.ko = None  # point banned by simple ko, or None
        self.history: list = []  # moves as (x, y) or PASS_MOVE
        self.num_black_prisoners = 0
        self.num_white_prisoners = 0
        self.is_end_of_game = False
        self.passes_black = 0
        self.passes_white = 0
        # move number at which the stone currently at (x, y) was placed
        # (-1 for empty); backs the turns-since feature plane.
        self.stone_ages = np.full((size, size), -1, dtype=np.int32)
        self.turns_played = 0
        # incremental position hash (uint32[2], shared Zobrist scheme
        # with the device engine) and the insertion-ordered set of
        # hashes seen so far (for superko); the empty board hashes to
        # zeros in both engines.
        self.zobrist_hash = np.zeros(2, dtype=np.uint32)
        self._hash_history = dict.fromkeys([self.zobrist_hash.tobytes()])
        self.handicaps: list = []

    # ---------------------------------------------------------------- basics

    def copy(self) -> "GameState":
        other = GameState(self.size, self.komi, self.enforce_superko)
        other.board = self.board.copy()
        other.current_player = self.current_player
        other.ko = self.ko
        other.history = list(self.history)
        other.num_black_prisoners = self.num_black_prisoners
        other.num_white_prisoners = self.num_white_prisoners
        other.is_end_of_game = self.is_end_of_game
        other.passes_black = self.passes_black
        other.passes_white = self.passes_white
        other.stone_ages = self.stone_ages.copy()
        other.turns_played = self.turns_played
        other.zobrist_hash = self.zobrist_hash.copy()
        other._hash_history = dict(self._hash_history)
        other.handicaps = list(self.handicaps)
        return other

    def _on_board(self, point) -> bool:
        x, y = point
        return 0 <= x < self.size and 0 <= y < self.size

    def get_neighbors(self, point):
        x, y = point
        return [(x + dx, y + dy) for dx, dy in _NEIGHBOR_OFFSETS
                if self._on_board((x + dx, y + dy))]

    def get_diagonals(self, point):
        x, y = point
        return [(x + dx, y + dy) for dx, dy in _DIAGONAL_OFFSETS
                if self._on_board((x + dx, y + dy))]

    # ----------------------------------------------------------- group logic

    def get_group(self, point):
        """(stones, liberties) of the group containing ``point`` (BFS)."""
        color = self.board[point]
        if color == EMPTY:
            return set(), set()
        return _group_on(self.board, point, self.size)

    def liberty_count(self, point) -> int:
        return len(self.get_group(point)[1])

    # -------------------------------------------------------------- legality

    def _simulate(self, action, color):
        """Board after ``color`` plays ``action`` (with captures), plus the
        set of captured stones. Raises IllegalMove on occupied/suicide."""
        x, y = action
        if self.board[x, y] != EMPTY:
            raise IllegalMove(f"occupied point {action}")
        board = self.board.copy()
        board[x, y] = color
        captured = set()
        for n in self.get_neighbors(action):
            if board[n] == -color:
                stones, libs = _group_on(board, n, self.size)
                if not libs:
                    captured |= stones
        for p in captured:
            board[p] = EMPTY
        _, own_libs = _group_on(board, action, self.size)
        if not own_libs:
            raise Suicide(f"suicide at {action}")
        return board, captured

    def _hash_after(self, action, color, captured) -> np.ndarray:
        """Position hash after ``color`` plays ``action`` capturing the
        ``captured`` stones — incremental XOR off the carried hash."""
        zob = zobrist_tables.position_table(self.size)
        ci = 0 if color == BLACK else 1
        x, y = action
        h = self.zobrist_hash ^ zob[x * self.size + y, ci]
        for px, py in captured:
            h = h ^ zob[px * self.size + py, 1 - ci]
        return h

    def is_suicide(self, action) -> bool:
        if not self._on_board(action):
            return False
        try:
            self._simulate(action, self.current_player)
            return False
        except Suicide:
            return True
        except IllegalMove:
            return False

    def is_positional_superko(self, action) -> bool:
        """Would ``action`` recreate an earlier whole-board position?"""
        if not self._on_board(action):
            return False
        try:
            _, captured = self._simulate(action, self.current_player)
        except IllegalMove:
            return False
        h = self._hash_after(action, self.current_player, captured)
        return h.tobytes() in self._hash_history

    def is_legal(self, action) -> bool:
        if self.is_end_of_game:
            return False
        if action is PASS_MOVE:
            return True
        if not self._on_board(action):
            return False
        if self.board[action] != EMPTY:
            return False
        if self.ko is not None and action == self.ko:
            return False
        try:
            _, captured = self._simulate(action, self.current_player)
        except IllegalMove:
            return False
        if self.enforce_superko:
            h = self._hash_after(action, self.current_player, captured)
            if h.tobytes() in self._hash_history:
                return False
        return True

    # Eye heuristics follow the reference (``AlphaGo/go.py::is_eyeish`` /
    # ``is_eye``): eyeish = empty with all neighbors own; a true eye
    # additionally bounds opposing diagonals (1 allowed in the interior,
    # 0 on edge/corner).
    def is_eyeish(self, point, owner) -> bool:
        if self.board[point] != EMPTY:
            return False
        return all(self.board[n] == owner for n in self.get_neighbors(point))

    def is_eye(self, point, owner) -> bool:
        if not self.is_eyeish(point, owner):
            return False
        diagonals = self.get_diagonals(point)
        num_bad = sum(1 for d in diagonals if self.board[d] == -owner)
        num_off_board = 4 - len(diagonals)
        if num_off_board > 0:  # edge or corner point
            return num_bad == 0
        return num_bad <= 1

    def get_legal_moves(self, include_eyes: bool = True):
        moves = [(x, y) for x in range(self.size) for y in range(self.size)
                 if self.is_legal((x, y))]
        if not include_eyes:
            moves = [m for m in moves
                     if not self.is_eye(m, self.current_player)]
        return moves

    # --------------------------------------------------------------- playing

    def do_move(self, action, color=None):
        """Play ``action`` ((x, y) or PASS_MOVE) for ``color`` (default:
        current player). Returns True if the move ended the game."""
        color = self.current_player if color is None else color
        if self.is_end_of_game:
            raise IllegalMove("game is over")
        if action is PASS_MOVE:
            if color == BLACK:
                self.passes_black += 1
            else:
                self.passes_white += 1
            self.ko = None
            self.history.append(PASS_MOVE)
            self.turns_played += 1
            self.current_player = -color
            if (len(self.history) >= 2 and self.history[-2] is PASS_MOVE):
                self.is_end_of_game = True
            return self.is_end_of_game

        if not self._on_board(action) or self.board[action] != EMPTY:
            raise IllegalMove(f"illegal move {action}")
        if self.ko is not None and action == self.ko:
            raise IllegalMove(f"ko violation at {action}")
        board, captured = self._simulate(action, color)
        new_hash = self._hash_after(action, color, captured)
        if self.enforce_superko and new_hash.tobytes() in self._hash_history:
            raise IllegalMove(f"superko violation at {action}")

        # simple ko: single capture by a lone stone that itself has exactly
        # one liberty afterwards → that liberty (the captured point) is banned
        self.ko = None
        if len(captured) == 1:
            own_stones, own_libs = _group_on(board, action, self.size)
            if len(own_stones) == 1 and len(own_libs) == 1:
                self.ko = next(iter(captured))

        if color == BLACK:
            self.num_white_prisoners += len(captured)
        else:
            self.num_black_prisoners += len(captured)
        self.board = board
        for p in captured:
            self.stone_ages[p] = -1
        self.stone_ages[action] = self.turns_played
        self.turns_played += 1
        self.history.append(action)
        self.zobrist_hash = new_hash
        self._hash_history[new_hash.tobytes()] = None
        self.current_player = -color
        return False

    def place_handicaps(self, positions):
        """Place Black handicap stones before the game starts
        (reference: ``GameState.place_handicaps``)."""
        if self.turns_played > 0:
            raise IllegalMove("handicaps only before the first move")
        if not positions:
            return
        zob = zobrist_tables.position_table(self.size)
        for p in positions:
            if self.board[p] != EMPTY:
                raise IllegalMove(f"occupied handicap point {p}")
            self.board[p] = BLACK
            self.stone_ages[p] = 0
            self.handicaps.append(p)
            self.zobrist_hash = self.zobrist_hash ^ \
                zob[p[0] * self.size + p[1], 0]
        self._hash_history[self.zobrist_hash.tobytes()] = None
        self.current_player = WHITE

    # --------------------------------------------------------------- scoring

    def get_scores(self):
        """Area (Chinese) scores ``(black, white)``; white includes komi.

        Empty regions touching only one color count for that color;
        neutral (dame) regions touching both count for neither.
        """
        return score_board(self.board, self.komi)

    def get_winner(self):
        """BLACK, WHITE, or 0 for a drawn game (reference:
        ``GameState.get_winner``)."""
        black, white = self.get_scores()
        if black > white:
            return BLACK
        if white > black:
            return WHITE
        return 0

    def get_current_player(self):
        return self.current_player


def score_board(board: np.ndarray, komi: float):
    """Area (Chinese) scores ``(black, white + komi)`` of a raw board
    array — the single scoring implementation behind both
    :meth:`GameState.get_scores` and the benchmarks' batched host
    scorer (:func:`rocalphago_tpu.search.selfplay.host_winners`)."""
    board = np.asarray(board)
    size = board.shape[0]
    visited = np.zeros_like(board, dtype=bool)
    black = int(np.sum(board == BLACK))
    white = int(np.sum(board == WHITE))
    for x in range(size):
        for y in range(size):
            if board[x, y] != EMPTY or visited[x, y]:
                continue
            region, borders = [], set()
            frontier = [(x, y)]
            while frontier:
                p = frontier.pop()
                if visited[p]:
                    continue
                visited[p] = True
                region.append(p)
                px, py = p
                for nx, ny in ((px + 1, py), (px - 1, py),
                               (px, py + 1), (px, py - 1)):
                    if 0 <= nx < size and 0 <= ny < size:
                        if board[nx, ny] == EMPTY:
                            if not visited[nx, ny]:
                                frontier.append((nx, ny))
                        else:
                            borders.add(int(board[nx, ny]))
            if borders == {BLACK}:
                black += len(region)
            elif borders == {WHITE}:
                white += len(region)
    return float(black), float(white) + komi


def _group_on(board: np.ndarray, point, size: int):
    """(stones, liberties) of the group at ``point`` on an arbitrary board."""
    color = board[point]
    if color == EMPTY:
        return set(), set()
    stones, liberties = set(), set()
    frontier = [point]
    while frontier:
        p = frontier.pop()
        if p in stones:
            continue
        stones.add(p)
        x, y = p
        for dx, dy in _NEIGHBOR_OFFSETS:
            n = (x + dx, y + dy)
            if 0 <= n[0] < size and 0 <= n[1] < size:
                v = board[n]
                if v == color and n not in stones:
                    frontier.append(n)
                elif v == EMPTY:
                    liberties.add(n)
    return stones, liberties
