"""Go rules engines.

Two implementations with identical rules semantics:

* :mod:`rocalphago_tpu.engine.pygo` — a host-side pure-Python oracle,
  mirroring the reference engine's API (``AlphaGo/go.py::GameState``).
  Used for SGF replay, GTP bookkeeping, and as the correctness oracle
  for the device engine.
* :mod:`rocalphago_tpu.engine.jaxgo` — the TPU-native engine: a pure
  functional ``step(state, action)`` over a fixed-shape array pytree,
  jittable and vmappable. This is the centerpiece of the rebuild
  (SURVEY.md §2a) and replaces the reference's Python/Cython board.
"""

from rocalphago_tpu.engine.pygo import (  # noqa: F401
    BLACK,
    EMPTY,
    PASS_MOVE,
    WHITE,
    GameState,
)
