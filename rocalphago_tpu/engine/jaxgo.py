"""TPU-native Go engine: pure-functional, fixed-shape, jit/vmap-able.

This replaces the reference's Python/Cython board (``AlphaGo/go.py::
GameState``; SURVEY.md §2a "the centerpiece of the rebuild") with a
design that maps onto XLA:

* game state is a pytree of fixed-shape arrays (:class:`GoState`);
* ``step(cfg, state, action)`` is a pure function — thousands of
  concurrent games run as ``jax.vmap(step)`` with zero host round-trips;
* connected groups come from an iterative min-label flood fill under
  ``lax.while_loop`` (no dynamic shapes);
* liberties are dense bitmaps ``[groups, points]`` built with four
  scatters — one matrix yields liberty counts, capture detection, and
  the feature encoder's exact capture-size / liberties-after planes
  without simulating any candidate move;
* positional superko is *exact and vectorized*: the Zobrist hash of the
  position after any candidate move is ``hash ^ z[p] ^ xor(captured
  groups)``, where per-group Zobrist XORs come from a GF(2) parity
  matmul that runs on the MXU.

Rules semantics are identical to :mod:`rocalphago_tpu.engine.pygo`
(differential-tested in ``tests/test_jaxgo.py``): suicide illegal,
simple ko always, optional positional superko, two passes end the game,
area scoring with komi.

Actions are flat indices ``0..N*N-1`` plus ``N*N`` for pass.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from rocalphago_tpu.engine import zobrist as zobrist_tables

BLACK = 1
WHITE = -1


@dataclasses.dataclass(frozen=True)
class GoConfig:
    """Static engine parameters (hashable → usable as a jit static arg)."""

    size: int = 19
    komi: float = 7.5
    enforce_superko: bool = False
    # ring-buffer length for positional-superko hashes; >= max game
    # length gives exact superko (games are capped by move limits at the
    # agent layer, reference uses ~500)
    max_history: int = 512

    @property
    def num_points(self) -> int:
        return self.size * self.size

    @property
    def pass_action(self) -> int:
        return self.size * self.size


def default_komi(size: int) -> float:
    """Standard area-scoring komi per board size: 7.5 for 13×13 and
    up (the reference's and the zero papers' 19×19 value), 7.0 below
    (the CGOS 9×9 convention). Round-4 evidence for why this must be
    size-aware: a 9×9 zero run under the 19×19 default showed an 86%
    white win rate (``results/zero_scale_r4``) — most of that was the
    80-ply move cap truncating every game, but the komi default was
    the other half of the diagnosis (VERDICT r4 §weak 2;
    ``scripts/zero_balance.py`` measures both effects)."""
    return 7.5 if size >= 13 else 7.0


class GoState(NamedTuple):
    """One game. Batch by ``vmap``-ing the engine functions.

    All arrays are fixed-shape; ``N = size * size``.
    """

    board: jax.Array        # int8 [N]   0 empty, +1 black, -1 white
    turn: jax.Array         # int8 []    player to move (+1/-1)
    ko: jax.Array           # int32 []   point banned by simple ko, -1 none
    pass_count: jax.Array   # int8 []    consecutive passes
    done: jax.Array         # bool []
    step_count: jax.Array   # int32 []   moves played (incl. passes)
    hash: jax.Array         # uint32 [2] Zobrist hash of current position
    hash_history: jax.Array  # uint32 [H, 2] ring buffer of position hashes
    stone_ages: jax.Array   # int32 [N]  step at which stone placed, -1 empty
    prisoners: jax.Array    # int32 [2]  stones captured from [black, white]
    labels: jax.Array       # int32 [N]  carried group labeling: min flat
    #   index per group, sentinel N for empty — ALWAYS equal to
    #   compute_labels(board). step() maintains it incrementally
    #   (a move only adds one stone and removes whole captured groups,
    #   neither of which can split a group), so the per-move flood
    #   fill disappears from the hot loop; analysis consumers derive
    #   GroupData loop-free via group_data(..., labels=state.labels).


class GroupData(NamedTuple):
    """Whole-board group analysis — shared by step, legality and features.

    ``G = N + 1`` rows: one per possible group root (= min flat index of
    the group) plus a sentinel row ``N`` for empty/off-board.

    ``member`` and ``zxor`` are optional (``None`` unless requested):
    the hot step/legality path only needs the cheap [N,4]-scatter
    fields, while the feature encoder asks for the dense membership
    bitmap and superko legality for the per-group Zobrist XORs.
    """

    labels: jax.Array       # int32 [N]  group root per point (N for empty)
    sizes: jax.Array        # int32 [G]  stones per group
    lib_counts: jax.Array   # int32 [G]  distinct liberties per group
    member: jax.Array | None  # bool [G, N]  member[g, p]: stone p in group g
    zxor: jax.Array | None  # uint32 [G, 2] XOR of member stones' Zobrist keys


# --------------------------------------------------------------------------
# static per-size tables (host-side, cached)
# --------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _tables(size: int):
    """(neighbors [N,4], diagonals [N,4], zobrist [N,2,2]) as numpy.

    Neighbor/diagonal entries are ``N`` (sentinel) when off-board.
    Zobrist keys: ``zobrist[p, color_idx, 2xuint32]`` with color_idx
    0=black, 1=white; shared with the python oracle via
    :mod:`rocalphago_tpu.engine.zobrist` (fixed seed → identical
    hashes across engines and processes).
    """
    n = size * size
    neighbors = np.full((n, 4), n, dtype=np.int32)
    diagonals = np.full((n, 4), n, dtype=np.int32)
    for x in range(size):
        for y in range(size):
            p = x * size + y
            for k, (dx, dy) in enumerate(_NBR_SHIFTS):
                nx, ny = x + dx, y + dy
                if 0 <= nx < size and 0 <= ny < size:
                    neighbors[p, k] = nx * size + ny
            for k, (dx, dy) in enumerate(((1, 1), (1, -1), (-1, 1), (-1, -1))):
                nx, ny = x + dx, y + dy
                if 0 <= nx < size and 0 <= ny < size:
                    diagonals[p, k] = nx * size + ny
    return neighbors, diagonals, zobrist_tables.position_table(size)


def neighbors_for(size: int) -> jax.Array:
    return jnp.asarray(_tables(size)[0])


def diagonals_for(size: int) -> jax.Array:
    return jnp.asarray(_tables(size)[1])


def zobrist_for(size: int) -> jax.Array:
    return jnp.asarray(_tables(size)[2])


@functools.lru_cache(maxsize=1)
def _dense_engine() -> bool:
    """Dense (shift/matmul) vs scatter formulation of the per-ply group
    analysis.

    On TPU, scatter-adds with colliding indices and `[N,4]` index
    gathers serialize, while broadcast compares, 2-D grid shifts and
    small matmuls run at full vector/MXU width — measured round 5's
    on-chip A/B (batch 1024, 19x19, `benchmarks/tpu_hunt2_r5`): dense
    17,762 steps/s vs scatter 10,558 — dense wins 1.68x, so it is the
    TPU default by measurement. On CPU the scatter path wins (1444
    cheap serial updates beat 131k-cell dense compares), so the
    default follows the backend platform.

    Read once per process (trace-time; cached): override with
    ``ROCALPHAGO_ENGINE_DENSE=0/1`` **before the first engine trace**
    for A/B measurement — flipping it later in the same process has no
    effect on already-traced programs.
    """
    import os

    v = os.environ.get("ROCALPHAGO_ENGINE_DENSE", "")
    if v in ("0", "1"):
        return v == "1"
    return jax.default_backend() == "tpu"


def _shift2d(x: jax.Array, dx: int, dy: int, fill) -> jax.Array:
    """Read the value at ``(row+dx, col+dy)`` into each cell of the
    trailing 2-D grid (``fill`` off-board) — the gather-free neighbor
    access pattern shared by the dense group analysis and legality."""
    size = x.shape[-1]
    pad = [(0, 0)] * (x.ndim - 2) + [(1, 1), (1, 1)]
    p = jnp.pad(x, pad, constant_values=fill)
    return p[..., 1 + dx:1 + dx + size, 1 + dy:1 + dy + size]


_NBR_SHIFTS = ((1, 0), (-1, 0), (0, 1), (0, -1))


def _color_idx(color) -> jax.Array:
    """±1 color → 0/1 index into the Zobrist table."""
    return ((1 - color) // 2).astype(jnp.int32)


# --------------------------------------------------------------------------
# state construction
# --------------------------------------------------------------------------


def new_state(cfg: GoConfig) -> GoState:
    n = cfg.num_points
    return GoState(
        board=jnp.zeros((n,), jnp.int8),
        turn=jnp.int8(BLACK),
        ko=jnp.int32(-1),
        pass_count=jnp.int8(0),
        done=jnp.bool_(False),
        step_count=jnp.int32(0),
        hash=jnp.zeros((2,), jnp.uint32),
        hash_history=jnp.zeros((cfg.max_history, 2), jnp.uint32),
        stone_ages=jnp.full((n,), -1, jnp.int32),
        prisoners=jnp.zeros((2,), jnp.int32),
        labels=jnp.full((n,), n, jnp.int32),
    )


def new_states(cfg: GoConfig, batch: int) -> GoState:
    """A batch of fresh games (leading axis on every leaf)."""
    one = new_state(cfg)
    return jax.tree.map(lambda x: jnp.broadcast_to(x, (batch,) + x.shape), one)


def from_pygo(cfg: GoConfig, st, *, with_history: bool = True,
              with_labels: bool = True) -> GoState:
    """Bridge a host-side :class:`pygo.GameState` into engine state.

    Used at the GTP/SGF boundary where positions are built move-by-move
    on the host. Both engines share one Zobrist scheme
    (:mod:`rocalphago_tpu.engine.zobrist`), so the position hash and
    the superko history are carried over verbatim from the hashes pygo
    maintained incrementally (up to ``cfg.max_history``, most recent
    kept) — no host rehash. ``with_history=False`` skips the history
    transfer (correct whenever ``cfg.enforce_superko`` is off — e.g.
    the MCTS device-rollout path, which converts whole leaf waves per
    call).
    """
    board = np.asarray(st.board, dtype=np.int8).reshape(-1)

    # Place historical hashes so that the engine's future writes (at
    # slot ``step_count % H``, then ``step_count+1 % H``, ...) evict the
    # *oldest* entries first: newest-seen position sits at slot
    # ``(step_count - 1) % H``. ``_hash_history`` is insertion-ordered
    # (dict), so the suffix really is the most recent positions.
    hist = np.zeros((cfg.max_history, 2), np.uint32)
    if with_history:
        seen = [np.frombuffer(b, dtype=np.uint32)
                for b in st._hash_history.keys()]
        recent = seen[-cfg.max_history:]
        for i, h in enumerate(reversed(recent)):
            hist[(st.turns_played - 1 - i) % cfg.max_history] = h

    ko = -1 if st.ko is None else st.ko[0] * cfg.size + st.ko[1]
    passes = 0
    if st.history and st.history[-1] is None:
        passes = 2 if (len(st.history) > 1 and st.history[-2] is None) else 1

    # host-side min-root labeling (ascending scan ⇒ the BFS seed is the
    # group's min flat index), seeding the engine's carried labels.
    # ``with_labels=False`` skips it and leaves the field all-sentinel
    # (INVALID — callers batching many states must reseed with one
    # compiled fill via :func:`seed_labels` before any engine use).
    n = cfg.num_points
    lab = np.full(n, n, np.int32)
    if with_labels:
        nbrs_np = _tables(cfg.size)[0]
        for p in range(n):
            if board[p] != 0 and lab[p] == n:
                lab[p] = p
                stack = [p]
                while stack:
                    q = stack.pop()
                    for r in nbrs_np[q]:
                        if r < n and board[r] == board[p] and lab[r] == n:
                            lab[r] = p
                            stack.append(r)
    return GoState(
        board=jnp.asarray(board),
        turn=jnp.int8(st.current_player),
        ko=jnp.int32(ko),
        pass_count=jnp.int8(passes),
        done=jnp.bool_(st.is_end_of_game),
        step_count=jnp.int32(st.turns_played),
        hash=jnp.asarray(np.asarray(st.zobrist_hash, np.uint32)),
        hash_history=jnp.asarray(hist),
        stone_ages=jnp.asarray(
            np.asarray(st.stone_ages, np.int32).reshape(-1)),
        prisoners=jnp.asarray(
            np.array([st.num_black_prisoners, st.num_white_prisoners],
                     np.int32)),
        labels=jnp.asarray(lab),
    )


# --------------------------------------------------------------------------
# group analysis
# --------------------------------------------------------------------------


def compute_labels(cfg: GoConfig, board: jax.Array) -> jax.Array:
    """Connected-component root (min flat index) per point; N for empty.

    Min-label propagation over same-color neighbors as **2-D grid
    shifts** (pad + static slice — vector ops the TPU executes at full
    lane width, vs the index gathers of the naive formulation, which
    serialize): each ``while_loop`` trip runs several unrolled hook
    steps, then checks the fixed point, so convergence stays exact for
    any group shape while the per-trip launch/cond overhead is
    amortized ~8×. SURVEY.md §7 hard part #1.
    """
    n = cfg.num_points
    size = cfg.size
    b2 = board.reshape(size, size)
    stone = b2 != 0
    sentinel = jnp.int32(n)
    init = jnp.where(
        stone, jnp.arange(n, dtype=jnp.int32).reshape(size, size),
        sentinel)

    links = [(_shift2d(b2, dx, dy, 0) == b2) & stone
             for dx, dy in _NBR_SHIFTS]

    def hook(lab):
        for link, (dx, dy) in zip(links, _NBR_SHIFTS):
            nb = _shift2d(lab, dx, dy, sentinel)
            lab = jnp.minimum(lab, jnp.where(link, nb, sentinel))
        return lab

    def jump(lab):
        # pointer shortcutting (Shiloach–Vishkin): every point adopts
        # its current root's label, so the min propagates along the
        # already-discovered linkage exponentially — long snake groups
        # converge in O(log N) trips instead of O(diameter). Exactness
        # is unaffected (the while_loop still runs to fixpoint).
        flat = lab.reshape(-1)
        flat_pad = jnp.concatenate([flat, jnp.asarray([sentinel])])
        return jnp.minimum(flat, flat_pad[flat]).reshape(lab.shape)

    def body(carry):
        lab, _ = carry
        new = lab
        for _ in range(4):
            new = hook(new)
        new = jump(new)
        for _ in range(4):
            new = hook(new)
        new = jump(new)
        return new, lab

    def cond(carry):
        lab, prev = carry
        return jnp.any(lab != prev)

    lab, _ = lax.while_loop(cond, body, (hook(init), init))
    return lab.reshape(-1)


def neighbor_analysis(cfg: GoConfig, board: jax.Array, labels: jax.Array):
    """Per-point padded neighbor lookup shared by legality, stepping and
    the feature encoder: ``(nbr_color [N,4], nbr_root [N,4], uniq [N,4],
    valid [N,4])``. Off-board neighbors read color 0 and the sentinel
    root ``N``; ``uniq`` is True at the first occurrence of each root
    among a point's ≤4 neighbors (the dedup convention every caller
    must share)."""
    n = cfg.num_points
    nbrs = neighbors_for(cfg.size)
    board_pad = jnp.concatenate([board, jnp.zeros((1,), board.dtype)])
    lab_pad = jnp.concatenate([labels, jnp.full((1,), n, jnp.int32)])
    return (board_pad[nbrs], lab_pad[nbrs],
            jax.vmap(_dedup_mask)(lab_pad[nbrs]), nbrs < n)


def relabel_after_place(cfg: GoConfig, board: jax.Array,
                        labels: jax.Array, pt, color,
                        cap_mask: jax.Array) -> jax.Array:
    """Labels after placing ``color`` at ``pt`` (legality pre-checked)
    and removing the captured stones ``cap_mask`` — exact with zero
    flood fills, because a placement can only MERGE groups (min of
    min-rooted groups ∪ {pt} is the union's min flat index) and a
    capture removes whole groups (reset to the empty sentinel ``N``).
    The board itself is updated by the caller. Shared by the engine
    step and the ladder reader's carried chase analysis."""
    n = cfg.num_points
    nbrs = neighbors_for(cfg.size)
    board_pad = jnp.concatenate([board, jnp.zeros((1,), board.dtype)])
    lab_pad = jnp.concatenate([labels, jnp.full((1,), n, jnp.int32)])
    my = nbrs[pt]
    same = (my < n) & (board_pad[my] == color)
    roots = jnp.where(same, lab_pad[my], n)
    new_root = jnp.minimum(roots.min(), pt).astype(jnp.int32)
    merged = (labels[:, None] == jnp.where(
        same, roots, -2)[None, :]).any(axis=1)
    labels1 = jnp.where(merged, new_root, labels).at[pt].set(new_root)
    return jnp.where(cap_mask, n, labels1)


@functools.lru_cache(maxsize=None)
def _batched_fill(cfg: GoConfig):
    return jax.jit(jax.vmap(lambda bd: compute_labels(cfg, bd)))


def seed_labels(cfg: GoConfig, states: GoState) -> GoState:
    """Recompute the carried labels of a BATCHED state in one compiled
    device fill. Use at host→device wave boundaries (MCTS leaf
    conversion) together with ``from_pygo(..., with_labels=False)``:
    one vmapped fill beats a per-state interpreted host BFS."""
    return states._replace(labels=_batched_fill(cfg)(states.board))


def vgroup_data(cfg: GoConfig, *, with_member: bool = False,
                with_zxor: bool = False):
    """vmapped ``GoState → GroupData`` using the engine's carried
    labels — the loop-free per-ply analysis every batched game loop
    shares (self-play, rollouts, the value-corpus generator)."""
    return jax.vmap(lambda s: group_data(
        cfg, s.board, with_member=with_member, with_zxor=with_zxor,
        labels=s.labels))


def lib_counts_from_labels(cfg: GoConfig, board: jax.Array,
                           labels: jax.Array) -> jax.Array:
    """Loop-free liberty recount given ``labels``: int32 ``[N+1]``
    distinct-empty-point counts per group root (sentinel row ``N`` is
    0). Each empty point contributes one liberty to each *distinct*
    adjacent group via the deduped ``[N,4]`` scatter-add. Shared by
    :func:`group_data` and the ladder reader's carried incremental
    labeling (``features/ladders.py``)."""
    n = cfg.num_points
    empty = board == 0
    _, nbr_root, uniq, _ = neighbor_analysis(cfg, board, labels)
    contrib = empty[:, None] & uniq & (nbr_root < n)
    lib_counts = jnp.zeros((n + 1,), jnp.int32).at[
        jnp.where(contrib, nbr_root, n)].add(contrib.astype(jnp.int32))
    return lib_counts.at[n].set(0)


def group_data(cfg: GoConfig, board: jax.Array, *,
               with_member: bool = False,
               with_zxor: bool = False,
               labels: jax.Array | None = None) -> GroupData:
    """Group analysis of a board (one flood fill + small scatters).

    Liberty counts are *distinct* empty points per group, computed with
    a deduped [N,4] scatter-add (each empty point contributes once per
    distinct neighboring group) — no dense [G,N] intermediate in the
    hot path. Request ``with_member`` (feature encoder) or
    ``with_zxor`` (superko legality) explicitly.

    Pass ``labels`` (normally ``state.labels``, the engine's carried
    incremental labeling) to skip the flood fill entirely — the whole
    analysis is then loop-free scatters, which is how the self-play /
    training hot paths run.
    """
    n = cfg.num_points
    if labels is None:
        labels = compute_labels(cfg, board)
    empty = board == 0

    member = None
    zxor = None
    if _dense_engine():
        # scatter-free: membership by broadcast compare (empty points
        # carry the sentinel label N, so their row-n hits vanish under
        # ``& ~empty``), sizes by row reduce, distinct liberties by
        # dilating each group's stone mask one step (OR makes
        # distinctness free — no per-point dedup needed) and counting
        # empty cells under the dilation. All vector ops; the TPU
        # executes them at full lane width where the scatter
        # formulation below serializes on colliding indices.
        dense_member = (labels[None, :]
                        == jnp.arange(n + 1, dtype=jnp.int32)[:, None]
                        ) & (~empty)[None, :]                 # [N+1, N]
        sizes = dense_member.sum(axis=1, dtype=jnp.int32)
        m2 = dense_member.reshape(n + 1, cfg.size, cfg.size)
        dil = jnp.zeros_like(m2)
        for dx, dy in _NBR_SHIFTS:
            dil = dil | _shift2d(m2, dx, dy, False)
        lib_counts = (dil & empty.reshape(cfg.size, cfg.size)[None]).sum(
            axis=(1, 2), dtype=jnp.int32)
        if with_member or with_zxor:
            member = dense_member
    else:
        sizes = jnp.zeros((n + 1,), jnp.int32).at[labels].add(
            (~empty).astype(jnp.int32))
        lib_counts = lib_counts_from_labels(cfg, board, labels)
        if with_member or with_zxor:
            points = jnp.arange(n, dtype=jnp.int32)
            member = jnp.zeros((n + 1, n), jnp.bool_).at[
                labels, points].max(~empty)
            member = member.at[n].set(False)
    if with_zxor:
        # Per-group XOR of member Zobrist keys via GF(2) parity matmul
        # (rides the MXU; XLA has no segment-XOR).
        zob = zobrist_for(cfg.size)
        key_per_point = jnp.where(
            (board == BLACK)[:, None], zob[:, 0], zob[:, 1])  # uint32 [N,2]
        key_bits = _unpack_bits(key_per_point)                # bool [N,64]
        parity = (member.astype(jnp.int32) @ key_bits.astype(jnp.int32)) % 2
        zxor = _pack_bits(parity.astype(jnp.bool_))           # uint32 [G,2]
        if not with_member:
            member = None
    return GroupData(labels, sizes, lib_counts, member, zxor)


def _unpack_bits(words: jax.Array) -> jax.Array:
    """uint32 [..., W] → bool [..., W*32] (little-endian bit order)."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (words[..., None] >> shifts) & jnp.uint32(1)
    return bits.reshape(*words.shape[:-1], -1).astype(jnp.bool_)


def _pack_bits(bits: jax.Array) -> jax.Array:
    """bool [..., W*32] → uint32 [..., W]."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    words = bits.reshape(*bits.shape[:-1], -1, 32).astype(jnp.uint32)
    return (words << shifts).sum(axis=-1, dtype=jnp.uint32)


def _xor_reduce_masked(keys: jax.Array, mask: jax.Array) -> jax.Array:
    """XOR of ``keys[i]`` (uint32 [..., 2]) where ``mask[i]`` — via bit
    parity, since XLA lacks a segment-XOR."""
    bits = _unpack_bits(keys) & mask[..., None]
    parity = bits.sum(axis=-2) % 2
    return _pack_bits(parity.astype(jnp.bool_))


def _dedup_mask(roots: jax.Array) -> jax.Array:
    """For a small [K] int vector: True at the first occurrence of each
    value (used to dedup ≤4 neighbor group roots)."""
    k = roots.shape[0]
    eq = roots[:, None] == roots[None, :]
    earlier = jnp.tril(jnp.ones((k, k), jnp.bool_), k=-1)
    return ~(eq & earlier).any(axis=1)


# --------------------------------------------------------------------------
# legality
# --------------------------------------------------------------------------


def legal_mask(cfg: GoConfig, state: GoState,
               gd: GroupData | None = None) -> jax.Array:
    """Boolean mask over the ``N+1`` actions (last = pass, always legal
    while the game is live).

    Matches ``pygo.GameState.is_legal`` exactly, including positional
    superko when ``cfg.enforce_superko`` (candidate hashes via the
    group-XOR trick — no per-candidate simulation).
    """
    n = cfg.num_points
    if gd is None:
        gd = group_data(cfg, state.board, with_zxor=cfg.enforce_superko,
                        labels=state.labels)
    board, me = state.board, state.turn
    empty = board == 0

    if _dense_engine() and not cfg.enforce_superko:
        # gather-free: a placement at an empty point is non-suicide iff
        # some neighbor is empty, OR an own group with ≥2 liberties, OR
        # an opponent group in atari — one OR-field dilated by the four
        # grid shifts replaces the [N,4] neighbor gathers (which
        # serialize on TPU). Superko needs per-slot capture roots, so
        # it keeps the gather formulation below.
        lib_at = gd.lib_counts[gd.labels]       # [N]: one small gather
        src = (empty | ((board == me) & (lib_at >= 2))
               | ((board == -me) & (lib_at == 1))
               ).reshape(cfg.size, cfg.size)
        not_suicide = jnp.zeros_like(src)
        for dx, dy in _NBR_SHIFTS:
            not_suicide = not_suicide | _shift2d(src, dx, dy, False)
        ok = empty & not_suicide.reshape(-1)
    else:
        nbr_color, nbr_root, uniq, valid_nbr = neighbor_analysis(
            cfg, board, gd.labels)
        nbr_libs = gd.lib_counts[nbr_root]

        has_empty_nbr = (valid_nbr & (nbr_color == 0)).any(axis=1)
        own_safe = (valid_nbr & (nbr_color == me)
                    & (nbr_libs >= 2)).any(axis=1)
        captures = valid_nbr & (nbr_color == -me) & (nbr_libs == 1)
        not_suicide = has_empty_nbr | own_safe | captures.any(axis=1)
        ok = empty & not_suicide
    ok = ok & (jnp.arange(n) != state.ko)

    if cfg.enforce_superko:
        zob = zobrist_for(cfg.size)
        ci = _color_idx(me)
        cap_xor = _xor_reduce_masked(
            gd.zxor[nbr_root], captures & uniq)      # [N, 2]
        cand = state.hash[None, :] ^ zob[:, ci, :] ^ cap_xor
        seen = (cand[:, None, :] == state.hash_history[None, :, :]).all(
            axis=-1).any(axis=1)
        ok = ok & ~seen

    live = ~state.done
    return jnp.concatenate([ok & live, jnp.ones((1,), jnp.bool_) & live])


# --------------------------------------------------------------------------
# eval signature (transposition key for the NN evaluation cache)
# --------------------------------------------------------------------------


def eval_signature(cfg: GoConfig, state: GoState) -> jax.Array:
    """uint32 [2] key under which the NN evaluation of ``state`` may be
    cached: equal signatures ⇒ identical feature planes ⇒ identical
    device outputs (bar a 64-bit hash collision).

    The planes (``features/planes.py``) are a function of the board,
    the player to move, the simple-ko point, the done flag, and the
    per-stone age *bucket* ``clip(step_count - 1 - stone_age, 0, 7)``
    (the ``turns_since`` one-hots saturate at 8 — absolute move number
    never appears); the terminal-value komi rescore reads only
    ``done`` and the score, both covered. So the signature is the
    carried position hash XOR one age-bucket key per stone XOR
    ko/turn/done keys — keys from an independent fixed-seed family
    (:func:`rocalphago_tpu.engine.zobrist.signature_tables`).

    NOT valid under ``cfg.enforce_superko``: there the sensible-move
    mask depends on the hash *history*, which is not part of the
    signature — the serve pool refuses to cache in that mode.
    """
    n = cfg.num_points
    tabs = zobrist_tables.signature_tables(cfg.size)
    age_t = jnp.asarray(tabs.age)
    bucket = jnp.clip(state.step_count - 1 - state.stone_ages, 0,
                      zobrist_tables.AGE_BUCKETS - 1)
    keys = age_t[jnp.arange(n), bucket]                       # [N, 2]
    occupied = (state.board != 0) & (state.stone_ages >= 0)
    sig = state.hash ^ _xor_reduce_masked(keys, occupied)
    sig = sig ^ jnp.asarray(tabs.ko)[state.ko + 1]
    turn_t = jnp.asarray(tabs.turn)
    sig = sig ^ jnp.where(state.turn == WHITE, turn_t,
                          jnp.zeros_like(turn_t))
    done_t = jnp.asarray(tabs.done)
    sig = sig ^ jnp.where(state.done, done_t, jnp.zeros_like(done_t))
    return sig


# --------------------------------------------------------------------------
# step
# --------------------------------------------------------------------------


def step(cfg: GoConfig, state: GoState, action: jax.Array,
         gd: GroupData | None = None) -> GoState:
    """Play ``action`` (flat index, ``N`` = pass) for the player to move.

    Pure function of (state, action); assumes the action is legal (use
    :func:`legal_mask` — sampling already needs it). Occupied-point
    actions degrade to a pass rather than corrupting state. A finished
    game is frozen: any action returns the state unchanged.

    Pass ``gd`` (the :func:`group_data` of ``state.board``) to reuse the
    analysis :func:`legal_mask` already computed — inside one jitted
    sample-and-step program this halves the per-move engine cost.
    """
    n = cfg.num_points
    new = lax.cond(
        state.done,
        lambda s: s,
        lambda s: lax.cond(
            (action >= n) | (s.board[jnp.minimum(action, n - 1)] != 0),
            functools.partial(_step_pass, cfg),
            functools.partial(_step_place, cfg, action=action, gd=gd),
            s),
        state)
    return new


def _step_pass(cfg: GoConfig, state: GoState) -> GoState:
    pc = state.pass_count + 1
    return state._replace(
        turn=-state.turn,
        ko=jnp.int32(-1),
        pass_count=pc,
        done=pc >= 2,
        step_count=state.step_count + 1,
        hash_history=state.hash_history.at[
            state.step_count % cfg.max_history].set(state.hash),
    )


def _step_place(cfg: GoConfig, state: GoState, action,
                gd: GroupData | None = None) -> GoState:
    n = cfg.num_points
    nbrs = neighbors_for(cfg.size)
    zob = zobrist_for(cfg.size)
    board, me = state.board, state.turn
    if gd is None:
        gd = group_data(cfg, board, labels=state.labels)

    my_nbrs = nbrs[action]                               # [4]
    nbr_color = jnp.concatenate(
        [board, jnp.zeros((1,), board.dtype)])[my_nbrs]
    nbr_root = jnp.concatenate(
        [gd.labels, jnp.full((1,), n, jnp.int32)])[my_nbrs]

    # opponent neighbor groups in atari (their single liberty is `action`)
    cap_roots = jnp.where(
        (nbr_color == -me) & (gd.lib_counts[nbr_root] == 1), nbr_root, -2)
    captured = (gd.labels[:, None] == cap_roots[None, :]).any(axis=1)
    num_captured = captured.sum(dtype=jnp.int32)

    board2 = jnp.where(captured, 0, board).at[action].set(me)

    # simple ko: lone new stone, exactly one capture, one liberty left
    placed_alone = ~(nbr_color == me).any()
    board2_pad = jnp.concatenate([board2, jnp.ones((1,), board2.dtype)])
    p_libs = (board2_pad[my_nbrs] == 0).sum(dtype=jnp.int32)
    ko_point = jnp.argmax(captured).astype(jnp.int32)
    ko = jnp.where(
        (num_captured == 1) & placed_alone & (p_libs == 1), ko_point, -1)

    ci = _color_idx(me)
    cap_keys = jnp.where((me == BLACK), zob[:, 1, :], zob[:, 0, :])
    new_hash = (state.hash ^ zob[action, ci, :]
                ^ _xor_reduce_masked(cap_keys, captured))

    prisoners = state.prisoners.at[_color_idx(-me)].add(num_captured)
    return state._replace(
        board=board2,
        turn=-me,
        ko=ko,
        pass_count=jnp.int8(0),
        step_count=state.step_count + 1,
        hash=new_hash,
        hash_history=state.hash_history.at[
            state.step_count % cfg.max_history].set(new_hash),
        stone_ages=jnp.where(captured, -1, state.stone_ages).at[action].set(
            state.step_count),
        prisoners=prisoners,
        labels=relabel_after_place(cfg, board, gd.labels, action, me,
                                   captured),
    )


# --------------------------------------------------------------------------
# scoring
# --------------------------------------------------------------------------


def area_scores(cfg: GoConfig, state: GoState) -> tuple[jax.Array, jax.Array]:
    """Area (Chinese) scores ``(black, white_plus_komi)`` — empty regions
    bordering exactly one color count for it. Same flood-fill machinery
    as group labels, run on the empty graph."""
    n = cfg.num_points
    nbrs = neighbors_for(cfg.size)
    board = state.board
    empty = board == 0

    # label empty regions: treat empty as the "color"
    region = compute_labels(cfg, jnp.where(empty, jnp.int8(9), jnp.int8(0)))
    board_pad = jnp.concatenate([board, jnp.zeros((1,), board.dtype)])
    nbr_color = board_pad[nbrs]
    touches_b_pt = empty & (nbr_color == BLACK).any(axis=1)
    touches_w_pt = empty & (nbr_color == WHITE).any(axis=1)
    touches_b = jnp.zeros((n + 1,), jnp.bool_).at[region].max(touches_b_pt)
    touches_w = jnp.zeros((n + 1,), jnp.bool_).at[region].max(touches_w_pt)

    terr_b = (empty & touches_b[region] & ~touches_w[region]).sum()
    terr_w = (empty & touches_w[region] & ~touches_b[region]).sum()
    black = (board == BLACK).sum() + terr_b
    white = (board == WHITE).sum() + terr_w
    return black.astype(jnp.float32), white.astype(jnp.float32) + cfg.komi


def winner(cfg: GoConfig, state: GoState) -> jax.Array:
    """+1 black wins, -1 white wins, 0 draw."""
    b, w = area_scores(cfg, state)
    return jnp.sign(b - w).astype(jnp.int32)


# --------------------------------------------------------------------------
# convenience wrapper
# --------------------------------------------------------------------------


class GoEngine:
    """Jitted single-game and batched closures over a fixed config.

    ``step/legal_mask/...`` operate on one game; the ``v``-prefixed
    variants are ``vmap``-ed over a leading batch axis — the rebuild's
    self-play scaling axis (SURVEY.md §2b "environment parallelism").
    """

    def __init__(self, cfg: GoConfig):
        self.cfg = cfg
        self.init = jax.jit(functools.partial(new_state, cfg))
        self.step = jax.jit(functools.partial(step, cfg))
        self.legal_mask = jax.jit(
            lambda state: legal_mask(cfg, state))
        self.area_scores = jax.jit(functools.partial(area_scores, cfg))
        self.winner = jax.jit(functools.partial(winner, cfg))
        self.group_data = jax.jit(
            lambda board: group_data(cfg, board, with_member=True,
                                     with_zxor=True))
        self.vstep = jax.jit(jax.vmap(functools.partial(step, cfg)))
        self.vlegal_mask = jax.jit(
            jax.vmap(lambda state: legal_mask(cfg, state)))
        self.vwinner = jax.jit(jax.vmap(functools.partial(winner, cfg)))

    def init_batch(self, batch: int) -> GoState:
        return new_states(self.cfg, batch)
