"""Shared Zobrist tables: one hashing scheme for BOTH engines.

The device engine (:mod:`rocalphago_tpu.engine.jaxgo`) maintains an
exact incremental uint32[2] Zobrist hash per position (vectorized
superko); the Python oracle (:mod:`rocalphago_tpu.engine.pygo`)
maintains the SAME hash move-by-move on the host. Both read their
per-point keys from :func:`position_table` here — one fixed seed, one
``integers()`` call, so a position's hash is identical across engines
and across processes (pinned by the cross-engine parity test in
``tests/test_pygo.py``). That identity is what lets the serving
stack's transposition-keyed evaluation cache
(:mod:`rocalphago_tpu.serve.evalcache`) use the engine's carried hash
as a cache key instead of rehashing boards on the host.

This module is NUMPY-ONLY by design: pygo must stay importable
without jax (it is the correctness oracle), so the tables live below
both engines.

Two key families:

* :func:`position_table` — the POSITION keys (``[N, 2, 2]``:
  per-point, per-color, 2×uint32). ``position_table(size)`` MUST
  reproduce the exact draw the device engine has always made
  (seed ``POSITION_SEED``, one ``integers`` call) — every persisted
  hash, superko history and differential test depends on it.
* :func:`signature_tables` — the EVAL-SIGNATURE keys (a second,
  independent fixed seed). The NN evaluation of a state is a function
  of more than stone placement: the feature planes read the player to
  move, the simple-ko point, the done flag and the per-stone age
  BUCKET (``turns_since`` one-hots ``clip(step_count - 1 -
  stone_age, 0, 7)`` — ``features/planes.py``). The eval signature
  XORs keys for each of those onto the position hash, so two states
  share a signature only when every plane the nets read (and the
  terminal-value rescore) is identical — which is what makes a cache
  hit bit-identical to a device eval by construction.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np

#: the device engine's historical seed — DO NOT change: every carried
#: hash, superko ring buffer and differential test pins these values
POSITION_SEED = 20260729

#: the eval-signature family's own seed (independent of the position
#: keys so signature terms never cancel against stone keys)
SIGNATURE_SEED = 20260806

#: number of stone-age buckets the ``turns_since`` planes one-hot
#: (``features/planes.py::_one_hot8`` — ages clip into ``0..7``)
AGE_BUCKETS = 8


@functools.lru_cache(maxsize=None)
def position_table(size: int) -> np.ndarray:
    """Per-point position keys ``uint32 [N, 2, 2]``.

    ``table[p, color_idx]`` is the 2×uint32 key of a stone at flat
    point ``p``; ``color_idx`` 0 = black, 1 = white. Fixed seed →
    identical hashes across engines and processes.
    """
    n = size * size
    rng = np.random.default_rng(POSITION_SEED)
    return rng.integers(0, 2**32, size=(n, 2, 2), dtype=np.uint32)


class SignatureTables(NamedTuple):
    """The eval-signature key families (all uint32, trailing dim 2)."""

    age: np.ndarray   # [N, AGE_BUCKETS, 2]  per-point per-age-bucket
    ko: np.ndarray    # [N + 1, 2]           indexed ``ko + 1`` (0 = none)
    turn: np.ndarray  # [2]                  XORed when white to move
    done: np.ndarray  # [2]                  XORed when the game is over


@functools.lru_cache(maxsize=None)
def signature_tables(size: int) -> SignatureTables:
    """Keys for the non-positional terms of the eval signature."""
    n = size * size
    rng = np.random.default_rng(SIGNATURE_SEED)
    return SignatureTables(
        age=rng.integers(0, 2**32, size=(n, AGE_BUCKETS, 2),
                         dtype=np.uint32),
        ko=rng.integers(0, 2**32, size=(n + 1, 2), dtype=np.uint32),
        turn=rng.integers(0, 2**32, size=(2,), dtype=np.uint32),
        done=rng.integers(0, 2**32, size=(2,), dtype=np.uint32),
    )
