"""Replay over the wire: the networked replay service.

PR 11's actor/learner split kept actors as in-process threads
feeding one :class:`~rocalphago_tpu.data.replay.ReplayBuffer`. This
package puts a wire between them — the Pgx/KataGo distributed
shape: actor processes (other cores, other hosts) stream finished
self-play games to a replay service the learner consumes from —
with fault tolerance as the headline, not an afterthought:

* :mod:`~rocalphago_tpu.replaynet.protocol` — the NDJSON protocol
  content (``put_games``/``next_batch``/``stats`` over schema-v2
  game records) on the shared :mod:`rocalphago_tpu.net` framing;
* :mod:`~rocalphago_tpu.replaynet.server` — :class:`~rocalphago_tpu
  .replaynet.server.ReplayService`: at-least-once ingestion made
  effectively exactly-once (content-hash ``game_id`` dedup window,
  ack only after the buffer accepts), structured ``overload``/
  ``draining`` shedding with ``retry_after_s``, per-request fault
  barriers ``replay.put``/``replay.take``/``replay.conn``, and a
  graceful drain that leaves the buffer spilled for restart;
* :mod:`~rocalphago_tpu.replaynet.client` — :class:`~rocalphago_tpu
  .replaynet.client.ReplayClient` (deadline-bounded requests,
  reconnect with deterministic-jitter backoff honoring
  ``retry_after_s``, and DEGRADED MODE: games spool to a local
  crash-safe WAL while the service is unreachable and re-ship in
  order on reconnect) plus the learner-side
  :class:`~rocalphago_tpu.replaynet.client.RemoteReplayBuffer`;
* :mod:`~rocalphago_tpu.replaynet.actor` — the actor process
  entrypoint (real self-play from saved model specs, or the
  synthetic generator the chaos soak storms).

Wire format, ack/dedup semantics, the degraded-mode state machine,
probe schema and measured numbers: docs/REPLAYNET.md. Chaos
verdicts: ``scripts/replay_soak.py``.
"""

from rocalphago_tpu.replaynet.protocol import PROTO_VERSION  # noqa: F401
