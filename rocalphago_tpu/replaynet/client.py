"""Replay client: reconnecting transport plus the degraded-mode WAL.

Three layers, innermost first:

* :class:`ReplayConn` — one raw connection: blocking request/
  response correlated by ``id``; typed refusals surface as
  :class:`ReplayRefused` (carrying the server's ``retry_after_s``),
  a drop as :class:`ReplayClosed` — both names the shared
  :func:`rocalphago_tpu.net.client.default_transient` classifier
  recognizes, so every retry loop below honors the hint for free.
* :class:`ReplayClient` — the actor-side handle. Its headline is
  DEGRADED MODE: with a ``spool_dir``, every finished game is first
  written to a local crash-safe WAL (atomic tmp+fsync+rename, one
  ``game.<n>.json`` per record), and only then shipped. While the
  service is unreachable the actor keeps playing and spooling; on
  reconnect the spool re-ships strictly head-to-tail (FIFO order
  preserved). An ack appends the ``game_id`` to ``acked.jsonl``
  BEFORE the spool file is unlinked, so every crash window leaves
  either the spool file, the acked line, or both — and the server's
  dedup window collapses whichever re-ship that implies. The
  produced-set accounting the soak green-gates on is therefore
  exact: ``produced = acked ∪ still-spooled``.
* :class:`RemoteReplayBuffer` — the learner-side adapter: the
  ``next_batch``/``sample`` surface of :class:`~rocalphago_tpu.data
  .replay.ReplayBuffer`, backed by wire requests with reconnect.
  Retrying a ``next_batch`` whose reply was lost is safe by server
  construction (the popped entry requeues on send failure).

State machine, crash-window table, measured numbers:
docs/REPLAYNET.md.
"""

from __future__ import annotations

import glob
import json
import os
import socket
import time

from rocalphago_tpu.data import replay
from rocalphago_tpu.net import client as net_client
from rocalphago_tpu.obs import registry as obs_registry
from rocalphago_tpu.replaynet import protocol
from rocalphago_tpu.runtime import atomic


class ReplayError(Exception):
    """A typed error frame; ``code`` is one of
    :data:`~rocalphago_tpu.replaynet.protocol.ERROR_CODES`."""

    def __init__(self, code: str, msg: str,
                 retry_after_s: float | None = None):
        super().__init__(f"{code}: {msg}")
        self.code = code
        self.retry_after_s = retry_after_s


class ReplayRefused(ReplayError):
    """The service shed (``overload``/``draining``) — back off at
    least ``retry_after_s`` and retry (or keep spooling)."""


class ReplayClosed(Exception):
    """The connection dropped mid-conversation (kill, drain nudge,
    service restart)."""


_REFUSAL_CODES = ("overload", "draining")


def _raise_error(frame: dict) -> None:
    code = frame.get("code", "internal")
    msg = frame.get("msg", "")
    retry = frame.get("retry_after_s")
    if code in _REFUSAL_CODES:
        raise ReplayRefused(code, msg, retry_after_s=retry)
    raise ReplayError(code, msg, retry_after_s=retry)


class ReplayConn:
    """One wire connection to a replay service.

    Connecting reads the server's ``hello`` (protocol version,
    record schema, buffer capacity) — or raises
    :class:`ReplayRefused` when the service sheds at accept.
    """

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout)
        self._reader = self.sock.makefile("rb")
        self._next_id = 0
        self.hello = self._recv()
        if self.hello.get("type") == "error":
            self.close()
            _raise_error(self.hello)
        self.capacity = self.hello.get("capacity")

    def _recv(self) -> dict:
        try:
            frame = protocol.read_frame(self._reader)
        except protocol.ProtocolError as e:
            raise ReplayClosed(f"unreadable frame: {e}")
        if frame is None:
            raise ReplayClosed("connection closed by service")
        return frame

    def request(self, msg: dict) -> dict:
        """Send one frame, return its (id-matched) reply. Typed
        errors raise; a ``goodbye`` or stray frame is
        :class:`ReplayClosed`."""
        self._next_id += 1
        msg = dict(msg, id=self._next_id)
        try:
            self.sock.sendall(protocol.encode_frame(msg))
        except OSError:
            raise ReplayClosed("send failed: connection closed")
        reply = self._recv()
        if reply.get("type") == "goodbye":
            raise ReplayClosed(
                f"service said goodbye ({reply.get('reason', '?')})")
        if reply.get("id") != self._next_id:
            raise ReplayClosed(f"unexpected frame {reply!r}")
        if reply.get("type") == "error":
            _raise_error(reply)
        return reply

    def settimeout(self, timeout: float) -> None:
        self.sock.settimeout(timeout)

    def close(self) -> None:
        # the makefile reader holds the fd: close it too or the
        # server side never sees the FIN (same rule as the gateway)
        try:
            self._reader.close()
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


#: spool WAL filename pattern (index preserves ship order)
_SPOOL_GLOB = "game.*.json"
#: append-only ledger of acked game ids (the durable half of the
#: produced set; the spool is the other half)
_ACKED_FILE = "acked.jsonl"


class ReplayClient:
    """Actor-side handle: spool-first shipping with reconnect.

    Without a ``spool_dir`` the client is a plain reliable sender
    (ship with backoff, raise after the attempt budget). With one,
    :meth:`put_games` NEVER raises on service unavailability — the
    game is already durable in the WAL when shipping starts, and a
    failed flush just leaves it (and everything behind it) spooled
    for the next :meth:`flush`. ``sleep`` is injectable so tests
    assert the backoff schedule instead of waiting it out.
    """

    def __init__(self, host: str, port: int, *,
                 spool_dir: str | None = None, timeout: float = 30.0,
                 attempts: int = 6, base_delay: float = 0.25,
                 max_delay: float = 5.0, seed: int = 0,
                 sleep=time.sleep):
        self.host = host
        self.port = port
        self.spool_dir = spool_dir
        self.timeout = float(timeout)
        self.attempts = int(attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.seed = int(seed)
        self._sleep = sleep
        self._conn: ReplayConn | None = None
        self._connected_once = False
        self.reconnects = 0
        self.shipped = 0
        self.shipped_games = 0
        self.dup_acks = 0
        self.degraded = False
        self._acked: set[str] = set()
        self._spool_next = 0
        if spool_dir:
            os.makedirs(spool_dir, exist_ok=True)
            self._acked = set(self._read_acked())
            indices = [self._spool_index(p)
                       for p in self._spool_paths()]
            self._spool_next = max(indices, default=-1) + 1

    # --------------------------------------------------------- wire

    def _ensure_conn(self) -> ReplayConn:
        if self._conn is None:
            self._conn = ReplayConn(self.host, self.port,
                                    timeout=self.timeout)
            if self._connected_once:
                self.reconnects += 1
                obs_registry.counter(
                    "replaynet_reconnects_total").inc()
            self._connected_once = True
        return self._conn

    def _drop_conn(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def _request(self, msg: dict, *, key: str,
                 timeout: float | None = None) -> dict:
        """One request with the shared reconnect/backoff loop: a
        drop reconnects, a refusal sleeps at least the server's
        ``retry_after_s``; the final attempt's exception
        propagates."""

        def attempt():
            conn = self._ensure_conn()
            if timeout is not None:
                conn.settimeout(timeout)
            try:
                return conn.request(msg)
            except (ReplayClosed, OSError):
                self._drop_conn()
                raise

        def transient(e):
            # a typed ``internal`` is the server's fault wall talking
            # (an injected transient, or a kill that aborted the
            # connection): the request had no durable effect — it is
            # exactly the retry the dedup window exists to absorb
            return (net_client.default_transient(e)
                    or (isinstance(e, ReplayError)
                        and e.code == "internal"))

        return net_client.call_with_backoff(
            attempt, attempts=self.attempts,
            base_delay=self.base_delay, max_delay=self.max_delay,
            seed=self.seed, key=key, transient=transient,
            sleep=self._sleep)

    # -------------------------------------------------------- spool

    def _spool_paths(self) -> list[str]:
        return sorted(glob.glob(os.path.join(self.spool_dir,
                                             _SPOOL_GLOB)))

    @staticmethod
    def _spool_index(path: str) -> int:
        try:
            return int(os.path.basename(path).split(".")[1])
        except (IndexError, ValueError):
            return -1

    def _read_acked(self) -> list[str]:
        path = os.path.join(self.spool_dir, _ACKED_FILE)
        ids = []
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if line:
                        ids.append(line)
        except OSError:
            pass
        return ids

    def _append_acked(self, gid: str) -> None:
        path = os.path.join(self.spool_dir, _ACKED_FILE)
        with open(path, "a", encoding="utf-8") as f:
            f.write(gid + "\n")
        self._acked.add(gid)

    @property
    def spool_depth(self) -> int:
        """Unshipped games waiting in the WAL (0 without a spool)."""
        return len(self._spool_paths()) if self.spool_dir else 0

    def produced_ids(self) -> set[str]:
        """Every game id this actor has DURABLY produced: acked ∪
        still-spooled. Exact across any crash window — a game is in
        the WAL before its first ship, its id is in the ledger
        before the WAL entry is unlinked, and the ambiguous overlap
        (both present) is what the server dedups."""
        ids = set(self._acked)
        for path in self._spool_paths():
            try:
                with open(path, encoding="utf-8") as f:
                    gid = json.load(f).get("game_id")
                if gid:
                    ids.add(str(gid))
            except (OSError, ValueError):
                continue
        return ids

    # --------------------------------------------------------- puts

    def put_games(self, games: replay.ZeroGames,
                  version: int = 0) -> str:
        """Durably hand off one finished batch; returns its
        ``game_id``.

        Spool mode: WAL-write first (the game is safe the moment
        this returns), then best-effort :meth:`flush` — service
        down means ``degraded`` flips True and the game waits.
        Direct mode (no spool): ship with backoff, raising the
        final attempt's exception."""
        gid = replay.compute_game_id(games)
        rec = replay.games_to_record(games, version=version,
                                     game_id=gid)
        if not self.spool_dir:
            self._ship(rec)
            return gid
        atomic.atomic_write_json(
            os.path.join(self.spool_dir,
                         f"game.{self._spool_next:08d}.json"),
            rec, indent=None)
        self._spool_next += 1
        self.flush(best_effort=True)
        return gid

    def _ship(self, rec: dict) -> dict:
        reply = self._request({"type": "put_games", "record": rec},
                              key="replaynet.put")
        self.shipped += 1
        if reply.get("dup"):
            self.dup_acks += 1
        else:
            self.shipped_games += len(rec.get("winners", ()))
            obs_registry.counter(
                "replaynet_shipped_games_total").inc(
                len(rec.get("winners", ())))
        return reply

    def flush(self, best_effort: bool = False) -> int:
        """Re-ship the spool strictly head-to-tail; returns games
        shipped this call.

        Order is the FIFO guarantee: nothing at index n+1 ships
        before index n is acked (or known-acked from the ledger).
        ``best_effort`` swallows the transport failure after the
        backoff budget — degraded mode — leaving the tail spooled;
        otherwise the exception propagates with the spool intact.
        """
        if not self.spool_dir:
            return 0
        shipped = 0
        try:
            for path in self._spool_paths():
                try:
                    with open(path, encoding="utf-8") as f:
                        rec = json.load(f)
                    gid = str(rec.get("game_id", ""))
                except (OSError, ValueError):
                    # torn/unreadable WAL entry: can't have been
                    # produced (writes are atomic) — drop it
                    os.unlink(path)
                    continue
                if gid and gid in self._acked:
                    # crashed between ledger append and unlink:
                    # already durable server-side
                    os.unlink(path)
                    continue
                self._ship(rec)
                if gid:
                    self._append_acked(gid)
                os.unlink(path)
                shipped += 1
            self.degraded = False
        except (ReplayError, ReplayClosed, OSError):
            self.degraded = True
            if not best_effort:
                raise
        finally:
            obs_registry.gauge("replaynet_spool_depth").set(
                self.spool_depth)
        return shipped

    # --------------------------------------------------------- take

    def next_batch(self, timeout_s: float = 0.0) -> dict | None:
        """One ``next_batch`` request: the raw ``batch`` frame, or
        None when the server answered ``empty``. Reconnects under
        the shared backoff; safe to retry (a popped entry whose
        reply was lost requeues server-side)."""
        reply = self._request(
            {"type": "next_batch", "timeout_s": float(timeout_s)},
            key="replaynet.take",
            timeout=self.timeout + float(timeout_s))
        if reply.get("type") == "empty":
            return None
        return reply

    def stats(self) -> dict:
        return self._request({"type": "stats"},
                             key="replaynet.stats")["replaynet"]

    def close(self) -> None:
        self._drop_conn()

    def __enter__(self) -> "ReplayClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RemoteReplayBuffer:
    """The learner's buffer surface over the wire.

    Duck-types the consumer half of :class:`~rocalphago_tpu.data
    .replay.ReplayBuffer` (``next_batch``/``sample`` returning
    :class:`~rocalphago_tpu.data.replay.ReplayEntry` or None) so
    ``ZeroLearner`` runs unchanged against a remote service —
    ``run_training --replay-connect`` wires this in. ``sample``
    aliases ``next_batch``: the service owns the FIFO; recency
    sampling stays a server-side concern.
    """

    def __init__(self, client: ReplayClient):
        self.client = client
        self._closed = False

    def next_batch(self, timeout: float | None = None) \
            -> replay.ReplayEntry | None:
        if self._closed:
            return None
        try:
            reply = self.client.next_batch(
                timeout_s=0.0 if timeout is None else float(timeout))
        except (ReplayError, ReplayClosed, OSError):
            # service unreachable past the backoff budget: to the
            # learner that's indistinguishable from (and handled
            # like) an empty buffer — idle a beat and re-ask
            return None
        if reply is None:
            return None
        games, version = replay.record_to_games(reply["record"])
        return replay.ReplayEntry(int(reply.get("seq", 0)), version,
                                  games, time.monotonic())

    def sample(self, timeout: float | None = None) \
            -> replay.ReplayEntry | None:
        return self.next_batch(timeout=timeout)

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        self._closed = True
        self.client.close()
