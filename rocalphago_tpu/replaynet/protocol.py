"""The replaynet wire protocol: NDJSON frames for game transport.

Framing (sorted-key encoding, the frame-bound / torn-frame / blank-
line reader rules) is the shared :mod:`rocalphago_tpu.net.protocol`
core — this module pins the replay service's protocol CONTENT. The
server speaks first (a ``hello`` carrying ``proto``, the record
``schema`` it accepts and the buffer capacity — or a structured
refusal when the service sheds at accept); after that the client
drives request/response pairs correlated by ``id``:

==============  ======================================================
request         response
==============  ======================================================
``hello``       ``ok`` (optional; pins the protocol version — a
                mismatch is ``bad_proto``)
``put_games``   ``ok`` with the ``game_id`` and ``dup`` flag — sent
                ONLY after the buffer accepted (and spilled) the
                record, so an ack in hand means the game is durable
                server-side; a retry of an already-ingested id acks
                ``dup: true`` without re-inserting (errors:
                ``bad_schema``, ``overload`` + ``retry_after_s``)
``next_batch``  ``batch`` with the record and its buffer ``seq``, or
                ``empty`` when nothing arrived within ``timeout_s``
``stats``       ``stats`` with the service probe block
                (docs/REPLAYNET.md schema)
==============  ======================================================

``put_games`` carries one schema-v2 game record
(:func:`rocalphago_tpu.data.replay.games_to_record`) including its
content-hash ``game_id`` — the identity every dedup decision keys
on. Typed error codes are the refusal surface — a shed NEVER looks
like a hang: ``overload`` (buffer full or connection cap) and
``draining`` carry ``retry_after_s`` so actors back off into their
spool instead of spinning. Frames are bounded at
``ROCALPHAGO_REPLAYNET_MAX_FRAME`` bytes (default 8 MiB — a frame
carries a whole game batch, not a genmove); a line over the bound
is refused with ``frame_too_big`` and the connection drops.

Schema and examples: docs/REPLAYNET.md.
"""

from __future__ import annotations

import os

from rocalphago_tpu.data.replay import RECORD_SCHEMA
from rocalphago_tpu.net import protocol as _net

#: protocol revision carried in every hello; bumped on any frame
#: schema change a deployed client could observe
PROTO_VERSION = 1

#: bound on one wire frame (bytes, newline included); env override.
#: Replay frames carry whole game batches, so the default is 8 MiB
#: where the gateway's is 64 KiB.
MAX_FRAME_ENV = "ROCALPHAGO_REPLAYNET_MAX_FRAME"

#: every error code a frame may carry (docs/REPLAYNET.md)
ERROR_CODES = (
    "bad_request",     # unparseable JSON / missing required field
    "bad_proto",       # client hello pinned an unsupported version
    "frame_too_big",   # line crossed the frame bound; connection drops
    "unknown_type",    # message type outside the protocol table
    "bad_schema",      # record schema newer than this server reads
    "overload",        # shed (buffer/conn cap); retry_after_s set
    "draining",        # server is drain-stopping; retry_after_s set
    "internal",        # handler fault; this request failed, conn holds
)

ProtocolError = _net.ProtocolError

encode_frame = _net.encode_frame


def max_frame_bytes() -> int:
    raw = os.environ.get(MAX_FRAME_ENV, "")
    return int(raw) if raw else 8 << 20


def read_frame(reader, limit: int | None = None):
    """Next frame off a buffered binary reader, bounded at the
    replaynet frame limit by default (shared reader rules:
    :func:`rocalphago_tpu.net.protocol.read_frame`)."""
    return _net.read_frame(
        reader, max_frame_bytes() if limit is None else limit)


def error_frame(code: str, msg: str, id=None,
                retry_after_s: float | None = None) -> dict:
    return _net.error_frame(code, msg, id=id,
                            retry_after_s=retry_after_s,
                            codes=ERROR_CODES)


def hello_frame(capacity: int) -> dict:
    return {"type": "hello", "proto": PROTO_VERSION,
            "name": "rocalphago-replaynet",
            "schema": RECORD_SCHEMA,
            "capacity": int(capacity)}
