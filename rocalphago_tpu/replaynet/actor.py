"""Actor process: self-play games over the wire, crash-resumable.

The out-of-process half of the wire rig: each actor process owns a
:class:`~rocalphago_tpu.replaynet.client.ReplayClient` with a local
spool WAL and ships finished games to the replay service —
degraded-mode rules apply (service down: keep playing, keep
spooling; reconnect: re-ship in order).

Two game sources:

* ``--mode synthetic`` (default) — a jax-free deterministic
  generator: game ``i`` of actor ``k`` is a pure function of
  ``(seed, k, i)``, so a SIGKILLed actor restarted with the same
  arguments regenerates byte-identical content → identical
  ``game_id``s → every replayed overlap collapses in the server's
  dedup window. That determinism is what lets the chaos soak
  (``scripts/replay_soak.py``) assert exact produced-vs-ingested
  set equality through kill storms.
* ``--mode selfplay`` — real self-play from the tiny bench model
  (same flags as ``benchmarks/bench_zero_scale.py``), for the
  ``--wire`` scaling sweep. Params stay at version 0 (parameter
  distribution is out of scope for this rig).

Resume protocol: on start the actor counts its durably produced
games (``acked ∪ spooled`` — :meth:`ReplayClient.produced_ids`) and
continues from that index; the crash window between "generated" and
"WAL-written" is the only replayed work, and it replays to the same
id. Exit status: 0 once every requested game is produced AND the
spool drained; 2 when games remain spooled at the flush deadline
(the service stayed unreachable — the WAL holds them for the next
run).
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from rocalphago_tpu.data.replay import ZeroGames
from rocalphago_tpu.replaynet.client import ReplayClient


def synth_games(seed: int, actor_id: int, index: int, *,
                batch: int = 2, plies: int = 4,
                board: int = 5) -> ZeroGames:
    """Deterministic synthetic batch: content (hence ``game_id``) is
    a pure function of ``(seed, actor_id, index)``."""
    rng = np.random.default_rng(
        np.random.SeedSequence((seed, actor_id, index)))
    actions = board * board + 1
    return ZeroGames(
        actions=rng.integers(0, actions, size=(plies, batch),
                             dtype=np.int32),
        live=np.ones((plies, batch), dtype=bool),
        visits=rng.integers(0, 8, size=(plies, batch, actions),
                            dtype=np.int32),
        winners=rng.choice(np.array([-1, 1], dtype=np.int32),
                           size=(batch,)),
        finished=np.ones((batch,), dtype=bool),
    )


def _drain_spool(client: ReplayClient, timeout: float) -> bool:
    """Final flush loop: True once the spool is empty."""
    deadline = time.monotonic() + timeout
    while client.spool_depth:
        client.flush(best_effort=True)
        if not client.spool_depth:
            break
        if time.monotonic() >= deadline:
            return False
        time.sleep(0.25)
    return True


def _run_synthetic(a, client: ReplayClient) -> int:
    done = len(client.produced_ids())
    while done < a.games:
        games = synth_games(a.seed, a.actor_id, done,
                            batch=a.batch, plies=a.plies,
                            board=a.board)
        client.put_games(games, version=0)
        done += 1
        if a.rate_s:
            time.sleep(a.rate_s)
    return done


def _run_selfplay(a, client: ReplayClient) -> int:
    """Real self-play on the tiny bench model (one process, own
    mesh); ships one batch per produced game index."""
    import jax
    import optax

    from rocalphago_tpu.engine.jaxgo import GoConfig
    from rocalphago_tpu.models import CNNPolicy, CNNValue
    from rocalphago_tpu.parallel import mesh as meshlib
    from rocalphago_tpu.training.zero import make_zero_iteration

    feats = ("board", "ones")
    vfeats = feats + ("color",)
    pol = CNNPolicy(feats, board=a.board, layers=1,
                    filters_per_layer=4)
    val = CNNValue(vfeats, board=a.board, layers=1,
                   filters_per_layer=4)
    n_dev = len(jax.devices())
    while a.batch % n_dev:
        n_dev -= 1
    mesh = meshlib.make_mesh(n_dev)
    iteration = make_zero_iteration(
        GoConfig(size=a.board), feats, vfeats, pol.module.apply,
        val.module.apply, optax.sgd(0.01), optax.sgd(0.01),
        batch=a.batch, move_limit=a.move_limit, n_sim=a.sims,
        max_nodes=16, sim_chunk=a.sim_chunk, mesh=mesh)
    pp = meshlib.replicate(mesh, pol.params)
    vp = meshlib.replicate(mesh, val.params)
    key = jax.random.PRNGKey(a.seed + 1000 * (a.actor_id + 1))
    done = len(client.produced_ids())
    # selfplay content is NOT restart-deterministic (the rng chain
    # isn't checkpointed) — the count-based resume still never
    # under- or over-produces, which is all the bench needs
    for _ in range(done, a.games):
        key, game_key = jax.random.split(key)
        games = jax.device_get(
            iteration.play(pp, vp, game_key))
        client.put_games(ZeroGames(
            *(None if x is None else np.asarray(x)
              for x in games)), version=0)
        done += 1
    return done


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Replay actor process: generate self-play games "
                    "and ship them to a replay service "
                    "(docs/REPLAYNET.md)")
    ap.add_argument("--connect", required=True,
                    metavar="HOST:PORT",
                    help="replay service address")
    ap.add_argument("--spool-dir", required=True,
                    help="local WAL directory (degraded-mode spool "
                         "+ acked ledger; also the resume state)")
    ap.add_argument("--actor-id", type=int, default=0)
    ap.add_argument("--games", type=int, default=16,
                    help="total games to produce (resume-aware)")
    ap.add_argument("--mode", choices=("synthetic", "selfplay"),
                    default="synthetic")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--board", type=int, default=5)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--plies", type=int, default=4,
                    help="synthetic: plies per game batch")
    ap.add_argument("--rate-s", type=float, default=0.0,
                    help="synthetic: sleep between games (pacing)")
    ap.add_argument("--move-limit", type=int, default=16,
                    help="selfplay: move cap")
    ap.add_argument("--sims", type=int, default=4,
                    help="selfplay: search budget")
    ap.add_argument("--sim-chunk", type=int, default=2)
    ap.add_argument("--attempts", type=int, default=6,
                    help="ship attempts before degrading to spool")
    ap.add_argument("--flush-timeout", type=float, default=30.0,
                    help="final spool-drain budget (seconds)")
    a = ap.parse_args(argv)

    host, _, port = a.connect.rpartition(":")
    client = ReplayClient(host or "127.0.0.1", int(port),
                          spool_dir=a.spool_dir,
                          attempts=a.attempts,
                          base_delay=0.1, max_delay=1.0,
                          seed=a.actor_id)
    try:
        if a.mode == "synthetic":
            done = _run_synthetic(a, client)
        else:
            done = _run_selfplay(a, client)
        drained = _drain_spool(client, a.flush_timeout)
    finally:
        client.close()
    print(f"actor {a.actor_id}: produced {done}/{a.games} games, "
          f"spool_depth={client.spool_depth} "
          f"reconnects={client.reconnects} "
          f"dup_acks={client.dup_acks}", flush=True)
    return 0 if drained else 2


if __name__ == "__main__":
    sys.exit(main())
