"""The replay service: a ReplayBuffer behind the wire, lossless.

:class:`ReplayService` fronts one :class:`~rocalphago_tpu.data
.replay.ReplayBuffer` with the shared :class:`~rocalphago_tpu.net
.server.LineServerCore` (the gateway's proven accept/admission/
drain machinery) and the replaynet protocol. The design center is
the ISSUE's invariant: a killed connection, a restarted service or
a slow learner may cost latency, never a game —

* **ack-after-accept**: the ``ok`` for a ``put_games`` is sent only
  after the buffer accepted the record (and, with a spill dir,
  atomically persisted it) — an ack in hand means durable;
* **exactly-once via dedup**: every record carries its content-hash
  ``game_id``; a bounded id window (newest ``dedup_window`` ids,
  rebuilt from the spill + ``dedup.json`` on restart) absorbs the
  retries at-least-once delivery implies, acking ``dup: true``
  without re-inserting. One game id is shipped by one connection at
  a time (each actor re-ships its own spool sequentially), which is
  what makes claim-then-put race-free;
* **lossless shedding**: a full buffer turns ``put_games`` into a
  typed ``overload`` refusal with ``retry_after_s`` (the buffer's
  evict-the-oldest mode is never used here) — the actor backs off
  into its local spool instead of the service dropping games;
* **take-side requeue**: a popped ``next_batch`` entry whose reply
  cannot be sent (peer died mid-response) goes BACK to the head of
  the FIFO and re-spills;
* **fault walls**: every request runs behind ``replay.conn``, the
  put path behind ``replay.put`` (before any side effect — a kill
  aborts the connection before the accept, so the client re-ships),
  the take path behind ``replay.take`` (before the pop). Injected
  transients fail the request with a typed ``internal``; kills
  abort the connection; nothing escapes the handler (``requests
  .unhandled`` counts any escape, the soak green-gates on zero);
* **drain leaves the spill**: SIGTERM (via the supervisor in
  :func:`main`) stops the accept loop, finishes in-flight requests,
  joins every handler, persists the dedup window — and leaves every
  unconsumed entry spilled on disk, so the next incarnation's
  :meth:`ReplayService.recover` restores buffer AND window.

Probe schema (the ``replaynet-probe-drift`` lint contract), frame
tables, measured numbers: docs/REPLAYNET.md.
"""

from __future__ import annotations

import glob
import json
import os
import time

from rocalphago_tpu.analysis import lockcheck
from rocalphago_tpu.data import replay
from rocalphago_tpu.net.server import LineServerCore
from rocalphago_tpu.obs import registry as obs_registry
from rocalphago_tpu.replaynet import protocol
from rocalphago_tpu.runtime import atomic, faults

#: cap on concurrently served connections (env override)
MAX_CONNS_ENV = "ROCALPHAGO_REPLAYNET_MAX_CONNS"
#: drain grace: seconds in-flight handlers get to finish
DRAIN_ENV = "ROCALPHAGO_REPLAYNET_DRAIN_S"
#: bounded dedup window: newest N game ids remembered
DEDUP_ENV = "ROCALPHAGO_REPLAYNET_DEDUP"

#: retry hint a shed/refused client receives (seconds)
RETRY_AFTER_S = 1.0

#: longest server-side wait one next_batch request may hold (the
#: client re-issues; bounding it keeps drain prompt)
_TAKE_CAP_S = 30.0

#: dedup-window snapshot filename (inside the spill dir)
_DEDUP_FILE = "dedup.json"


def _env_float(name: str, default):
    raw = os.environ.get(name, "")
    return float(raw) if raw else default


class ReplayService:
    """Threaded NDJSON replay front end (module docstring).

    Pass an existing ``buffer`` or let the service build one from
    ``capacity``/``spill_dir``. ``max_conns``/``drain_s``/
    ``dedup_window`` default from their env knobs; ``metrics`` gets
    the drain-phase events.
    """

    def __init__(self, buffer: replay.ReplayBuffer | None = None, *,
                 host: str = "127.0.0.1", port: int = 0,
                 capacity: int | None = None,
                 spill_dir: str | None = None,
                 max_conns: int | None = None,
                 drain_s: float | None = None,
                 dedup_window: int | None = None,
                 evict: bool = False, metrics=None):
        if buffer is None:
            buffer = replay.ReplayBuffer(capacity,
                                         spill_dir=spill_dir)
        self.buffer = buffer
        self.metrics = metrics
        self.max_conns = (int(_env_float(MAX_CONNS_ENV, 64))
                          if max_conns is None else int(max_conns))
        self.drain_s = (_env_float(DRAIN_ENV, 10.0)
                        if drain_s is None else float(drain_s))
        self.dedup_window = (int(_env_float(DEDUP_ENV, 4096))
                             if dedup_window is None
                             else int(dedup_window))
        # sliding-window mode for SAMPLING learners (which never pop
        # the FIFO): a full buffer evicts the oldest entry instead of
        # refusing — the KataGo-style window. Lossless rigs (the
        # soak's exactly-once gate) keep the default refusal.
        self.evict = bool(evict)
        self._max_frame = protocol.max_frame_bytes()
        self._lock = lockcheck.make_lock("ReplayService._lock")
        self._dedup: dict = {}       # guarded-by: self._lock
        self._requests = 0           # guarded-by: self._lock
        self._errors = 0             # guarded-by: self._lock
        self._unhandled = 0          # guarded-by: self._lock
        self._puts = 0               # guarded-by: self._lock
        self._put_games = 0          # guarded-by: self._lock
        self._dup_hits = 0           # guarded-by: self._lock
        self._refused = 0            # guarded-by: self._lock
        self._takes = 0              # guarded-by: self._lock
        self._empties = 0            # guarded-by: self._lock
        self._requeued = 0           # guarded-by: self._lock
        self._faults = 0             # guarded-by: self._lock
        self._kills = 0              # guarded-by: self._lock
        self._put_kills = 0          # guarded-by: self._lock
        self._take_kills = 0         # guarded-by: self._lock
        self._conn_kills = 0         # guarded-by: self._lock
        self._put_attempts = 0       # guarded-by: self._lock
        self._take_attempts = 0      # guarded-by: self._lock
        self._closed = False
        self._live_g = obs_registry.gauge("replaynet_conns_live")
        self._acc_c = obs_registry.counter(
            "replaynet_connections_total", result="accepted")
        self._shed_c = obs_registry.counter(
            "replaynet_connections_total", result="shed")
        self._core = LineServerCore(
            host=host, port=port, max_conns=self.max_conns,
            drain_s=self.drain_s, handler=self._handle,
            refusal=self._refusal_frame, name="replaynet",
            metrics=metrics, live_gauge=self._live_g,
            accepted_counter=self._acc_c, shed_counter=self._shed_c)

    # ------------------------------------------------------ lifecycle

    def recover(self) -> int:
        """Restore the previous incarnation's durable state BEFORE
        serving: the dedup window (``dedup.json`` + the ids of every
        spilled record — so an ack lost in the old incarnation's
        last moments still dedups) and the spilled entries
        themselves. Returns the number of restored entries."""
        if not self.buffer.spill_dir:
            return 0
        ids: list[str] = []
        dedup_path = os.path.join(self.buffer.spill_dir, _DEDUP_FILE)
        try:
            with open(dedup_path, encoding="utf-8") as f:
                ids.extend(str(g) for g in json.load(f))
        except (OSError, ValueError):
            pass
        for path in sorted(glob.glob(os.path.join(
                self.buffer.spill_dir, "entry.*.json"))):
            try:
                with open(path, encoding="utf-8") as f:
                    gid = json.load(f).get("game_id")
                if gid:
                    ids.append(str(gid))
            except (OSError, ValueError):
                continue
        with self._lock:
            for gid in ids:
                self._dedup[gid] = None
            while len(self._dedup) > self.dedup_window:
                self._dedup.pop(next(iter(self._dedup)))
        return self.buffer.restore()

    def start(self) -> "ReplayService":
        self._core.start()
        return self

    @property
    def port(self) -> int:
        return self._core.port

    @property
    def draining(self) -> bool:
        return self._core.draining

    def drain(self, reason: str = "requested",
              timeout: float | None = None) -> None:
        """Graceful stop: refuse new work, finish in-flight
        requests, quiesce every thread, persist the dedup window —
        and leave every unconsumed entry spilled for
        :meth:`recover`. Idempotent; bounded by ``timeout``."""
        self._core.drain(reason=reason, timeout=timeout)
        if self.buffer.spill_dir:
            with self._lock:
                ids = list(self._dedup)
            atomic.atomic_write_json(
                os.path.join(self.buffer.spill_dir, _DEDUP_FILE),
                ids, indent=None)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.drain(reason="close")
        self.buffer.close()

    def __enter__(self) -> "ReplayService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------- handler

    def _refusal_frame(self, code: str) -> dict:
        """At-accept shed (``overload``/``draining``): the typed
        refusal the core sends before closing the connection."""
        self._count_error(code)
        return protocol.error_frame(
            code,
            f"replaynet {code}: {self.max_conns} connections live",
            retry_after_s=RETRY_AFTER_S)

    def _count_error(self, code: str) -> None:
        obs_registry.counter("replaynet_errors_total",
                             code=code).inc()
        with self._lock:
            self._errors += 1

    def _handle(self, conn, reader, cid: int) -> None:
        if not self._core.send(conn,
                               protocol.hello_frame(
                                   self.buffer.capacity)):
            return
        n = 0
        while True:
            if self._core.draining:
                self._core.send(conn, {"type": "goodbye",
                                       "reason": "draining"})
                break
            try:
                msg = protocol.read_frame(reader, self._max_frame)
            except protocol.ProtocolError as e:
                self._count_error(e.code)
                self._core.send(conn,
                                protocol.error_frame(e.code, str(e)))
                if e.fatal:
                    break
                continue
            if msg is None:
                break                  # disconnect / torn frame
            n += 1
            with self._lock:
                self._requests += 1
            obs_registry.counter("replaynet_requests_total",
                                 type=str(msg.get("type"))).inc()
            rid = msg.get("id")
            # the per-request fault wall (docs/RESILIENCE.md): a
            # transient fails this request, a kill this connection —
            # never the server, and never a game (no side effect has
            # happened yet)
            try:
                faults.barrier("replay.conn", iteration=n)
            except faults.InjectedKill as e:
                with self._lock:
                    self._kills += 1
                    self._conn_kills += 1
                obs_registry.counter("replaynet_faults_total",
                                     kind="kill").inc()
                self._core.send(conn, protocol.error_frame(
                    "internal", f"connection aborted: {e}", id=rid))
                break
            except Exception as e:  # noqa: BLE001 — injected
                with self._lock:
                    self._faults += 1
                obs_registry.counter("replaynet_faults_total",
                                     kind="fault").inc()
                self._count_error("internal")
                self._core.send(conn, protocol.error_frame(
                    "internal", f"transient fault: {e}", id=rid))
                continue
            popped = None
            try:
                reply, popped = self._dispatch(msg)
            except _ConnAbort as e:
                self._core.send(conn, protocol.error_frame(
                    "internal", f"connection aborted: {e}", id=rid))
                break
            except Exception as e:  # noqa: BLE001 — fault wall: the
                #   connection must answer, the service live on
                with self._lock:
                    self._unhandled += 1
                self._count_error("internal")
                reply = protocol.error_frame(
                    "internal", f"{type(e).__name__}: {e}", id=rid)
            if reply is not None and not self._core.send(conn, reply):
                # peer died mid-response: a popped entry goes back
                # to the head of the FIFO (and back to the spill) —
                # the failed delivery costs nothing
                if popped is not None and self.buffer.requeue(popped):
                    with self._lock:
                        self._requeued += 1
                break

    # ------------------------------------------------------ dispatch

    def _dispatch(self, msg: dict):
        """One request → (reply frame, popped entry or None).
        Refusals are typed error frames; only genuine bugs raise
        (counted unhandled)."""
        rid = msg.get("id")
        mtype = msg.get("type")
        if mtype == "hello":
            proto = msg.get("proto", protocol.PROTO_VERSION)
            if proto != protocol.PROTO_VERSION:
                self._count_error("bad_proto")
                return protocol.error_frame(
                    "bad_proto",
                    f"server speaks proto {protocol.PROTO_VERSION}, "
                    f"client pinned {proto}", id=rid), None
            return {"type": "ok", "id": rid,
                    "proto": protocol.PROTO_VERSION}, None
        if mtype == "put_games":
            return self._put(msg), None
        if mtype == "next_batch":
            return self._take(msg)
        if mtype == "stats":
            return {"type": "stats", "id": rid,
                    "replaynet": self.stats()}, None
        self._count_error("unknown_type")
        return protocol.error_frame(
            "unknown_type", f"unknown message type {mtype!r}",
            id=rid), None

    def _put(self, msg: dict) -> dict:
        rid = msg.get("id")
        rec = msg.get("record")
        # client fields parse BEFORE any side effect: a malformed
        # record is a typed refusal, never a half-ingested game
        if not isinstance(rec, dict):
            self._count_error("bad_request")
            return protocol.error_frame(
                "bad_request", "put_games needs a 'record' object",
                id=rid)
        try:
            games, version = replay.record_to_games(rec)
            gid = replay.record_game_id(rec, games)
        except replay.UnknownSchemaError as e:
            self._count_error("bad_schema")
            return protocol.error_frame("bad_schema", str(e), id=rid)
        except (ValueError, KeyError, TypeError) as e:
            self._count_error("bad_request")
            return protocol.error_frame(
                "bad_request", f"unparseable record: {e}", id=rid)
        with self._lock:
            self._put_attempts += 1
            it = self._put_attempts
        # the put fault wall: a kill lands BEFORE the buffer accept,
        # so the client holds no ack, re-ships, and the dedup window
        # makes the retry exactly-once
        try:
            faults.barrier("replay.put", iteration=it)
        except faults.InjectedKill as e:
            with self._lock:
                self._kills += 1
                self._put_kills += 1
            obs_registry.counter("replaynet_faults_total",
                                 kind="kill").inc()
            raise _ConnAbort(str(e))
        except Exception as e:  # noqa: BLE001 — injected
            with self._lock:
                self._faults += 1
            obs_registry.counter("replaynet_faults_total",
                                 kind="fault").inc()
            self._count_error("internal")
            return protocol.error_frame(
                "internal", f"transient fault: {e}", id=rid)
        if self._core.draining:
            self._count_error("draining")
            return protocol.error_frame(
                "draining", "service is draining", id=rid,
                retry_after_s=RETRY_AFTER_S)
        with self._lock:
            if gid in self._dedup:
                self._dup_hits += 1
                dup = True
            else:
                self._dedup[gid] = None
                while len(self._dedup) > self.dedup_window:
                    self._dedup.pop(next(iter(self._dedup)))
                dup = False
        if dup:
            obs_registry.counter("replaynet_dedup_hits_total").inc()
            return {"type": "ok", "id": rid, "game_id": gid,
                    "dup": True}
        # default mode never evicts: a full buffer is a structured
        # refusal, not a silent drop of the oldest game
        if not self.buffer.put(games, version=version, block=False,
                               evict=self.evict):
            with self._lock:
                self._dedup.pop(gid, None)
                self._refused += 1
            code = ("draining" if self.buffer.closed else "overload")
            self._count_error(code)
            return protocol.error_frame(
                code, f"buffer full ({self.buffer.capacity} entries)"
                if code == "overload" else "buffer closed",
                id=rid, retry_after_s=RETRY_AFTER_S)
        n_games = int(games.winners.shape[0])
        with self._lock:
            self._puts += 1
            self._put_games += n_games
        obs_registry.counter("replaynet_ingest_games_total").inc(
            n_games)
        # the ack: sent by the caller only now, AFTER accept+spill
        return {"type": "ok", "id": rid, "game_id": gid,
                "dup": False}

    def _take(self, msg: dict):
        rid = msg.get("id")
        try:
            timeout_s = float(msg.get("timeout_s", 0.0))
        except (TypeError, ValueError) as e:
            self._count_error("bad_request")
            return protocol.error_frame(
                "bad_request", f"unparseable timeout_s: {e}",
                id=rid), None
        timeout_s = min(max(timeout_s, 0.0), _TAKE_CAP_S)
        with self._lock:
            self._take_attempts += 1
            it = self._take_attempts
        # the take fault wall sits BEFORE the pop: a kill can't
        # strand a popped entry
        try:
            faults.barrier("replay.take", iteration=it)
        except faults.InjectedKill as e:
            with self._lock:
                self._kills += 1
                self._take_kills += 1
            obs_registry.counter("replaynet_faults_total",
                                 kind="kill").inc()
            raise _ConnAbort(str(e))
        except Exception as e:  # noqa: BLE001 — injected
            with self._lock:
                self._faults += 1
            obs_registry.counter("replaynet_faults_total",
                                 kind="fault").inc()
            self._count_error("internal")
            return protocol.error_frame(
                "internal", f"transient fault: {e}", id=rid), None
        # wait in bounded slices so a long take never holds drain
        # hostage — the drained client re-issues elsewhere/later
        deadline = time.monotonic() + timeout_s
        entry = None
        while entry is None:
            if self._core.draining:
                break
            rem = deadline - time.monotonic()
            entry = self.buffer.next_batch(
                timeout=max(0.0, min(0.25, rem)))
            if entry is None and rem <= 0:
                break
        if entry is None:
            with self._lock:
                self._empties += 1
            return {"type": "empty", "id": rid}, None
        rec = replay.games_to_record(entry.games, entry.version,
                                     entry.seq)
        with self._lock:
            self._takes += 1
        obs_registry.counter("replaynet_batches_out_total").inc()
        return {"type": "batch", "id": rid, "seq": entry.seq,
                "record": rec}, entry

    # --------------------------------------------------------- stats

    def stats(self) -> dict:
        """The probes' ``replaynet`` block (schema:
        docs/REPLAYNET.md — the ``replaynet-probe-drift`` lint rule
        diffs this literal against the documented schema both
        ways)."""
        wire = self._core.counters()
        with self._lock:
            requests = self._requests
            errors = self._errors
            unhandled = self._unhandled
            puts = self._puts
            put_games = self._put_games
            dup_hits = self._dup_hits
            refused = self._refused
            takes = self._takes
            empties = self._empties
            requeued = self._requeued
            injected = self._faults
            kills = self._kills
            put_kills = self._put_kills
            take_kills = self._take_kills
            conn_kills = self._conn_kills
            window = len(self._dedup)
        return {
            "proto": protocol.PROTO_VERSION,
            "schema": replay.RECORD_SCHEMA,
            "draining": wire["draining"],
            "conns": {
                "live": wire["live"],
                "max": self.max_conns,
                "accepted": wire["accepted"],
                "shed": wire["shed"],
            },
            "requests": {
                "total": requests,
                "errors": errors,
                "unhandled": unhandled,
            },
            "ingest": {
                "puts": puts,
                "games": put_games,
                "dup_hits": dup_hits,
                "refused": refused,
            },
            "takes": {
                "batches": takes,
                "empties": empties,
                "requeued": requeued,
            },
            "faults": {
                "injected": injected,
                "kills": kills,
                "put_kills": put_kills,
                "take_kills": take_kills,
                "conn_kills": conn_kills,
            },
            "buffer": {
                "fill": self.buffer.fill,
                "capacity": self.buffer.capacity,
                "ingested_games": self.buffer.ingested_games,
            },
            "dedup_window": {
                "size": window,
                "max": self.dedup_window,
            },
            "evict": self.evict,
            "drain_s": self.drain_s,
        }


class _ConnAbort(Exception):
    """Internal: an injected kill aborts this connection (the client
    re-ships; the dedup window absorbs the retry)."""


def main(argv=None) -> int:
    """Launch a replay service and serve until SIGTERM (the
    supervisor's drain — stop accepting, finish in-flight requests,
    persist the dedup window, leave the spill for the next
    incarnation, exit 0) or Ctrl-C."""
    import argparse

    ap = argparse.ArgumentParser(
        description="Networked replay service over a ReplayBuffer "
                    "(docs/REPLAYNET.md)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9464)
    ap.add_argument("--capacity", type=int, default=None,
                    help="buffer capacity in entries (default "
                         "ROCALPHAGO_REPLAY_CAPACITY / 8)")
    ap.add_argument("--spill-dir", default=None,
                    help="crash-safe spill directory (durability "
                         "across restarts; restored at startup)")
    ap.add_argument("--max-conns", type=int, default=None,
                    help="connection cap (default "
                         "ROCALPHAGO_REPLAYNET_MAX_CONNS / 64)")
    ap.add_argument("--drain-s", type=float, default=None,
                    help="drain grace (default "
                         "ROCALPHAGO_REPLAYNET_DRAIN_S / 10)")
    ap.add_argument("--dedup-window", type=int, default=None,
                    help="dedup id window (default "
                         "ROCALPHAGO_REPLAYNET_DEDUP / 4096)")
    ap.add_argument("--evict", action="store_true",
                    help="sliding-window mode: a full buffer evicts "
                         "the oldest entry instead of refusing "
                         "(sampling learners; NOT lossless)")
    ap.add_argument("--fresh", action="store_true",
                    help="discard any existing spill instead of "
                         "restoring it")
    ap.add_argument("--metrics", default=None,
                    help="JSONL path for drain/lifecycle events")
    a = ap.parse_args(argv)

    from rocalphago_tpu.runtime.supervisor import Supervisor

    metrics = None
    if a.metrics:
        from rocalphago_tpu.io.metrics import MetricsLogger

        metrics = MetricsLogger(a.metrics, echo=False)
    service = ReplayService(host=a.host, port=a.port,
                            capacity=a.capacity,
                            spill_dir=a.spill_dir,
                            max_conns=a.max_conns,
                            drain_s=a.drain_s,
                            dedup_window=a.dedup_window,
                            evict=a.evict, metrics=metrics)
    if a.fresh:
        service.buffer.discard_spill()
    else:
        restored = service.recover()
        if restored:
            print(f"replaynet: restored {restored} spilled entries")
    service.start()
    sup = Supervisor(metrics=metrics)
    sup.install_sigterm()
    print(f"replaynet: serving on {a.host}:{service.port}",
          flush=True)
    try:
        while not sup.draining:
            time.sleep(0.2)
    except KeyboardInterrupt:
        sup.request_drain(reason="keyboard")
    service.drain(reason="sigterm")
    service.buffer.close()
    if metrics is not None:
        obs_registry.log_to(metrics)
        metrics.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
