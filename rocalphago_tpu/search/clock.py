"""Shared GTP move-clock: seconds budget → search-unit budget.

Both searchers (the host-tree :class:`~rocalphago_tpu.search.mcts.
MCTSPlayer` and the on-device :class:`~rocalphago_tpu.search.
device_mcts.DeviceMCTSPlayer`) convert the per-move second budget the
GTP engine hands them (``set_move_time``) into their own unit —
playouts or simulations — via a measured units/sec estimate. One
implementation serves both so the two players cannot drift apart
(the reference's time handling lives in its GTP wrapper; SURVEY.md
§1 L6 — here the wrapper owns the clock arithmetic and THIS owns the
rate conversion).

Rate hygiene: a sample is folded into the EMA only when its ``key``
(whatever granularity the caller compiles programs at — per-komi,
per-simulation-tier) has run before. A key's FIRST run pays the XLA
compiles; folding its wall time in would collapse subsequent budgets
far below what the clock affords.
"""

from __future__ import annotations


class MoveClock:
    """Per-move wall budget + warmed-keyed units/sec EMA."""

    def __init__(self) -> None:
        self.move_time: float | None = None   # seconds; None = off
        self.rate: float | None = None        # units/sec EMA
        self._warmed: set = set()

    def set_move_time(self, seconds) -> None:
        """Per-move wall budget in seconds (None = no clock). The GTP
        engine calls this before every genmove from the game clock."""
        self.move_time = (None if seconds is None
                          else max(float(seconds), 0.0))

    def allowed_units(self) -> int | None:
        """Units the budget affords, or None (no clock / no estimate
        yet — callers run their full configured budget, which also
        seeds the estimate)."""
        if self.move_time is None or self.rate is None:
            return None
        return int(self.move_time * self.rate)

    def note(self, key, units: int, wall: float) -> None:
        """Record a finished search: ``units`` ran in ``wall`` secs
        under ``key``'s compiled programs. First run per key only
        warms the key (compile-bearing — never sampled)."""
        if key not in self._warmed:
            self._warmed.add(key)
            return
        if wall <= 0:
            return
        r = units / wall
        self.rate = r if self.rate is None else 0.5 * self.rate + 0.5 * r
