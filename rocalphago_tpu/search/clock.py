"""Shared GTP move-clock: seconds budget → search-unit budget.

Both searchers (the host-tree :class:`~rocalphago_tpu.search.mcts.
MCTSPlayer` and the on-device :class:`~rocalphago_tpu.search.
device_mcts.DeviceMCTSPlayer`) convert the per-move second budget the
GTP engine hands them (``set_move_time``) into their own unit —
playouts or simulations — via a measured units/sec estimate. One
implementation serves both so the two players cannot drift apart
(the reference's time handling lives in its GTP wrapper; SURVEY.md
§1 L6 — here the wrapper owns the clock arithmetic and THIS owns the
rate conversion).

Rate hygiene: a sample is folded in only when its ``key`` (whatever
granularity the caller compiles programs at — per-komi,
per-simulation-tier) has run before. A key's FIRST run pays the XLA
compiles; folding its wall time in would collapse subsequent budgets
far below what the clock affords.

Robustness (VERDICT r4 weak #7): the estimate is the MEDIAN of the
last ``WINDOW`` post-warm samples, not a 50/50 EMA — one anomalous
wall time (GC pause, background load, an OS scheduling hiccup) would
otherwise halve or double the next move's budget, which matters in
exactly the timed tournament play the feature exists for. A median
ignores a single outlier entirely until it repeats.

The clock is the PLANNER only: its sims/playouts budget is a
prediction, and nothing here stops a search whose chunks run slower
than predicted. The ENFORCER is :class:`~rocalphago_tpu.runtime.
deadline.Deadline` — the device player arms one from the same
``move_time`` and the chunked search checks it between compiled
chunks, serving the anytime argmax-visits answer on expiry
(docs/RESILIENCE.md "Hard deadlines").
"""

from __future__ import annotations

import statistics
from collections import deque


class MoveClock:
    """Per-move wall budget + warmed-keyed units/sec estimate."""

    WINDOW = 5      # samples kept; median of these is the rate

    def __init__(self) -> None:
        self.move_time: float | None = None   # seconds; None = off
        self.rate: float | None = None        # units/sec estimate
        self._warmed: set = set()
        self._samples: deque = deque(maxlen=self.WINDOW)

    def set_move_time(self, seconds) -> None:
        """Per-move wall budget in seconds (None = no clock). The GTP
        engine calls this before every genmove from the game clock."""
        self.move_time = (None if seconds is None
                          else max(float(seconds), 0.0))

    def allowed_units(self) -> int | None:
        """Units the budget affords, or None (no clock / no estimate
        yet — callers run their full configured budget, which also
        seeds the estimate)."""
        if self.move_time is None or self.rate is None:
            return None
        return int(self.move_time * self.rate)

    def note(self, key, units: int, wall: float) -> None:
        """Record a finished search: ``units`` ran in ``wall`` secs
        under ``key``'s compiled programs. First run per key only
        warms the key (compile-bearing — never sampled)."""
        if key not in self._warmed:
            self._warmed.add(key)
            return
        if wall <= 0:
            return
        self._samples.append(units / wall)
        self.rate = statistics.median(self._samples)
