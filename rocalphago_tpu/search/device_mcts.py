"""Fully on-device batched MCTS: the whole search is ONE jitted program.

The reference's search (``AlphaGo/mcts.py`` — host tree, batch-1 NN
evals) and its rebuild :class:`~rocalphago_tpu.search.mcts.ParallelMCTS`
(host tree, batched leaf waves) both pay a host↔device round trip per
evaluation wave. This module removes the host from the loop entirely,
mctx-style: the tree itself lives in fixed-shape device arrays (a
``max_nodes`` slab per game), and select → expand → evaluate → backup
is a ``lax.fori_loop`` over simulations, with each simulation stepping
ALL games in lockstep — so every policy/value forward runs at the full
game batch, and the only host↔device traffic for an entire search is
the root states in and the visit counts out.

Search semantics match the host tree (λ=0 APV — PUCT select, policy
priors over sensible moves, value-net leaf evaluation, sign-alternating
backup; same ``c_puct`` formula), with two deliberate differences:
simulations are strictly sequential per game (no virtual loss — the
batch axis provides the parallelism), and the tree is capacity-bounded
(``max_nodes``; a full slab keeps evaluating leaves but stops
allocating, so extra simulations still improve Q estimates).
:func:`make_gumbel_mcts` swaps the ROOT rule for Gumbel-top-k
candidate sampling + sequential halving (the mctx pattern) — the
stronger decision procedure at the low simulation budgets this search
serves at; selection below the root stays PUCT.

Layout notes (TPU): per game the slab holds the node states (a stacked
:class:`GoState` pytree), edge stats ``P/N/W [M, A]`` and the child
index table ``[M, A]`` — all static shapes; descend and backup are
``while_loop``s over int32 scalars with array gathers, and the
per-simulation NN evaluation uses the same nested-feature fusion as
the host waves (value planes encoded once; the policy forward reads
the prefix slice when ``value_features == policy_features + color``).

Multi-chip: the search shards over a device mesh BY PLACEMENT ALONE —
every per-game slab is independent, so passing root states sharded
over the ``data`` axis (``parallel.mesh.shard_batch``) with replicated
params shards the whole search, bit-identically
(``tests/test_device_mcts.py``); no search-code mesh plumbing needed.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from rocalphago_tpu.engine.jaxgo import (
    GoConfig,
    GoState,
    area_scores,
    eval_signature,
    group_data,
    new_states,
    step,
    winner,
)
from rocalphago_tpu.features.incremental import (
    batched_delta_encoder,
    init_caches,
)
from rocalphago_tpu.features.planes import batched_encoder, needs_member
from rocalphago_tpu.features.pyfeatures import output_planes
from rocalphago_tpu.obs import jaxobs
from rocalphago_tpu.obs import registry as obs_registry
from rocalphago_tpu.runtime import faults
from rocalphago_tpu.runtime.pipeline import ChunkPipeline
from rocalphago_tpu.search.clock import MoveClock
from rocalphago_tpu.search.selfplay import sensible_mask


class SimStep(NamedTuple):
    """One simulation's device-side context between SELECT/EXPAND and
    EVALUATE — the seam the serving subsystem's cross-game leaf
    batching cuts the search at (``rocalphago_tpu/serve``):
    ``prepare_sim`` descends + steps and returns this (with
    ``eval_states`` = the leaf states to evaluate), an EXTERNAL
    evaluator produces ``(priors, values)`` for those states — for
    serving, coalesced with other games' leaves into one device batch
    — and ``apply_sim`` writes the node + backs the value up. The
    fused in-search path composes the same two halves around its own
    ``eval_batch``, so the split path is the fused path by
    construction, not a re-implementation."""

    node: jax.Array         # i32 [B] node the descent ended on
    safe_action: jax.Array  # i32 [B] selected edge (pass where none)
    expanding: jax.Array    # bool [B] True = a new leaf was stepped
    eval_states: GoState    # [B, ...] states the evaluator must
    #   score. Where ``expanding`` these ARE the stepped children
    #   (the only rows the apply half writes), so one materialized
    #   GoState serves both the evaluator and the node write.
    eval_keys: jax.Array    # u32 [B, 2] eval signature of each
    #   ``eval_states`` row (``jaxgo.eval_signature``): the external
    #   evaluator's transposition-cache key, computed on device where
    #   the carried hash already lives. Unused by ``apply_sim`` and
    #   dead-code-eliminated out of the fused in-search path.


class DeviceTree(NamedTuple):
    """Per-game search slab (leading axis = game batch B).

    ``A = N + 1`` actions (last = pass); ``M = max_nodes``.
    """

    states: GoState      # node states, arrays shaped [B, M, ...]
    prior: jax.Array     # f32 [B, M, A]
    visits: jax.Array    # i32 [B, M, A]
    value_sum: jax.Array  # f32 [B, M, A] — from the node player's view
    child: jax.Array     # i32 [B, M, A]  node index, -1 = unexpanded
    parent: jax.Array    # i32 [B, M]     -1 at the root
    paction: jax.Array   # i32 [B, M]
    n_nodes: jax.Array   # i32 [B]
    root: jax.Array      # i32 [B]  current root node (0 at init;
    #   advance_root moves it down a child edge for subtree reuse —
    #   backups above it waste a few adds but root_stats never reads
    #   them, and allocation keeps appending to the shared slab)


def _state_at(states: GoState, idx) -> GoState:
    """Node ``idx``'s state out of a [M, ...]-stacked GoState."""
    return jax.tree.map(lambda x: x[idx], states)


def _set_state(states: GoState, idx, st: GoState) -> GoState:
    return jax.tree.map(lambda buf, v: buf.at[idx].set(v), states, st)


def _where_rows(active, new, old):
    """Per-game pytree select: row ``b`` takes ``new`` where
    ``active[b]`` else keeps ``old`` — the per-row budget mask of the
    playout-cap programs (every field's leading axis is the game
    batch)."""
    return jax.tree.map(
        lambda a, b: jnp.where(
            active.reshape((-1,) + (1,) * (a.ndim - 1)), a, b),
        new, old)


def _terminal_value(cfg: GoConfig, st: GoState) -> jax.Array:
    """Outcome in {-1, 0, 1} from the player to move's perspective."""
    w = winner(cfg, st)
    return (w * st.turn).astype(jnp.float32)


def _terminal_value_komi(cfg: GoConfig, st: GoState,
                         komi: jax.Array) -> jax.Array:
    """:func:`_terminal_value` rescored under a per-game ``komi`` (f32
    scalar) instead of the static ``cfg.komi``. ``area_scores`` bakes
    ``cfg.komi`` into white's total, so the rescore just shifts the
    margin by the komi delta — at ``komi == cfg.komi`` the shift is
    exactly ``0.0`` and the result is identical to the pinned path."""
    b, w = area_scores(cfg, st)
    margin = (b - w) + (jnp.float32(cfg.komi) - komi)
    return (jnp.sign(margin) * st.turn).astype(jnp.float32)


def make_device_mcts(cfg: GoConfig, policy_features: tuple,
                     value_features: tuple,
                     policy_apply: Callable, value_apply: Callable,
                     n_sim: int, max_nodes: int | None = None,
                     c_puct: float = 5.0, forced_k: float = 0.0):
    """Build the jitted searcher.

    Returns ``search(params_p, params_v, root_states) ->
    (root_visits i32 [B, A], root_q f32 [B, A])`` where ``root_states``
    is a batched :class:`GoState` (leading axis B) and ``root_q`` is
    the mean backed-up value per root action from the root player's
    perspective (0 where unvisited). ``value_features`` must be
    ``policy_features + ("color",)`` (the canonical nested 48/49
    layout) so one encode serves both nets. ``max_nodes=None`` sizes
    the slab to ``2 * n_sim`` (root + every expanded leaf fit).

    ``forced_k > 0`` enables FORCED PLAYOUTS at the root ("Accelerating
    Self-Play Learning in Go", PAPERS.md): any prior-supported root
    child with fewer than ``sqrt(forced_k · p(c) · N)`` visits (N =
    total root visits so far) is selected ahead of PUCT — cheap
    guaranteed exploration for self-play roots. The matching training
    target prunes those forced visits back out
    (``search.pruned_targets``); serving keeps the default ``0.0``
    (bit-identical programs).
    """
    if max_nodes is None:
        max_nodes = 2 * n_sim
    if tuple(value_features[:-1]) != tuple(policy_features) or \
            value_features[-1] != "color":
        raise ValueError(
            "device MCTS requires the nested feature layout: "
            "value_features == policy_features + ('color',); got "
            f"{policy_features} / {value_features}")
    n = cfg.num_points
    num_actions = n + 1
    m = max_nodes
    n_policy_planes = output_planes(policy_features)

    vgd = jax.vmap(lambda s: group_data(
        cfg, s.board, with_member=needs_member(value_features),
        with_zxor=cfg.enforce_superko, labels=s.labels))
    venc = batched_encoder(cfg, value_features)
    denc = batched_delta_encoder(cfg, value_features)
    vsens = jax.vmap(functools.partial(sensible_mask, cfg))
    vstep = jax.vmap(functools.partial(step, cfg))
    vterm = jax.vmap(functools.partial(_terminal_value, cfg))
    vterm_komi = jax.vmap(functools.partial(_terminal_value_komi, cfg))

    def _eval_from(params_p, params_v, states: GoState, gd, planes,
                   komi=None):
        """The NN half of :func:`eval_batch`, on precomputed analysis
        + planes (shared with the delta-encode root path)."""
        sens = vsens(states, gd)                       # [B, N]
        logits = policy_apply(params_p,
                              planes[..., :n_policy_planes])
        neg = jnp.finfo(logits.dtype).min
        masked = jnp.where(sens, logits, neg)
        board_p = jax.nn.softmax(masked, axis=-1)
        any_sens = sens.any(axis=-1, keepdims=True)
        board_p = jnp.where(any_sens, board_p, 0.0)
        pass_p = jnp.where(any_sens[:, 0], 0.0, 1.0)
        priors = jnp.concatenate(
            [board_p, pass_p[:, None]], axis=-1).astype(jnp.float32)
        values = value_apply(params_v, planes).astype(jnp.float32)
        term = vterm(states) if komi is None \
            else vterm_komi(states, komi)
        values = jnp.where(states.done, term, values)
        return priors, values

    def eval_batch(params_p, params_v, states: GoState):
        """One fused NN evaluation of a [B]-batched GoState:
        ``(priors f32 [B, A], values f32 [B])``. Priors are a masked
        softmax over sensible moves; the pass action gets probability
        1 exactly when no sensible move exists. Values are the value
        net's output where live, the terminal outcome where done."""
        gd = vgd(states)
        planes = venc(states, gd)                      # [B, s, s, Fv]
        return _eval_from(params_p, params_v, states, gd, planes)

    def eval_batch_komi(params_p, params_v, states: GoState, komi):
        """:func:`eval_batch` with a PER-ROW komi (f32 [B]): terminal
        rows are rescored as if the game were played under
        ``komi[i]`` instead of the static ``cfg.komi``. The serving
        layer uses this to give each session its own komi without a
        per-komi recompile — one program per batch size serves every
        komi, and rows at the default komi score identically to the
        pinned :func:`eval_batch` path."""
        gd = vgd(states)
        planes = venc(states, gd)                      # [B, s, s, Fv]
        return _eval_from(params_p, params_v, states, gd, planes,
                          komi=komi)

    def _assemble_tree(roots: GoState, root_priors) -> DeviceTree:
        batch = roots.board.shape[0]
        # node-state slab: every slot starts as a fresh state (cheap,
        # valid shapes), root state written into slot 0
        slab = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (batch,) + x.shape),
            new_states(cfg, m))
        slab = jax.vmap(_set_state, in_axes=(0, None, 0))(
            slab, 0, roots)
        prior = jnp.zeros((batch, m, num_actions), jnp.float32) \
            .at[:, 0, :].set(root_priors)
        return DeviceTree(
            states=slab,
            prior=prior,
            visits=jnp.zeros((batch, m, num_actions), jnp.int32),
            value_sum=jnp.zeros((batch, m, num_actions), jnp.float32),
            child=jnp.full((batch, m, num_actions), -1, jnp.int32),
            parent=jnp.full((batch, m), -1, jnp.int32),
            paction=jnp.zeros((batch, m), jnp.int32),
            n_nodes=jnp.ones((batch,), jnp.int32),
            root=jnp.zeros((batch,), jnp.int32),
        )

    def init_tree(params_p, params_v, roots: GoState) -> DeviceTree:
        root_priors, _ = eval_batch(params_p, params_v, roots)
        return _assemble_tree(roots, root_priors)

    def init_tree_cached(params_p, params_v, roots: GoState, caches):
        """:func:`init_tree` with the root planes through the
        incremental encoder (``features/incremental.py``): serving
        advances the root ONE move per ``get_move``, so successive
        root encodes reuse the previous move's ladder-chase verdicts.
        Bit-identical priors (the delta path's contract); returns
        ``(tree, caches')`` — the caller carries the cache across
        moves (``DeviceMCTSPlayer._enc_cache``)."""
        gd = vgd(roots)
        planes, caches = denc(roots, caches, gd)
        priors, _ = _eval_from(params_p, params_v, roots, gd, planes)
        return _assemble_tree(roots, priors), caches

    def _select_action(prior_n, visits_n, value_n):
        """PUCT argmax over one node's edges ([A] arrays).

        ``sqrt(sum(edge visits) + 1)`` IS the host tree's
        ``sqrt(parent node visits)``: in the host ``TreeNode`` the
        parent's visit count equals the sum of its edge visits plus
        the one evaluation that ended at the parent itself when it was
        expanded — so the two formulas agree at every node, not just
        asymptotically."""
        nv = visits_n.astype(jnp.float32)
        q = jnp.where(visits_n > 0, value_n / jnp.maximum(nv, 1.0), 0.0)
        u = (c_puct * prior_n * jnp.sqrt(nv.sum() + 1.0) / (1.0 + nv))
        score = jnp.where(prior_n > 0, q + u, -jnp.inf)
        return jnp.argmax(score).astype(jnp.int32)

    def _select_action_root(prior_n, visits_n, value_n):
        """Root selection under forced playouts: a prior-supported
        child short of its visit floor ``sqrt(forced_k · p · N)`` is
        taken first (largest deficit); PUCT otherwise. At N = 0 every
        floor is 0, so the first simulation is plain PUCT."""
        nv = visits_n.astype(jnp.float32)
        floor = jnp.sqrt(jnp.float32(forced_k) * prior_n * nv.sum())
        deficit = jnp.where(prior_n > 0, floor - nv, -jnp.inf)
        a_puct = _select_action(prior_n, visits_n, value_n)
        return jnp.where(jnp.max(deficit) > 0,
                         jnp.argmax(deficit).astype(jnp.int32),
                         a_puct)

    def _descend_one(prior, visits, value_sum, child, done_m,
                     root_action, root):
        """Single-game descend ([M, ...] arrays): walk existing child
        pointers from ``root`` until an unexpanded edge or a terminal
        node. Returns ``(node, action)``; ``action`` = -1 when the
        walk ended ON a terminal node (evaluate that node itself).

        ``root_action >= 0`` forces the FIRST edge out of the root
        (the Gumbel searcher's scheduled candidate); selection below
        the root is PUCT either way. ``-1`` = free PUCT from the root.
        """
        def cond(carry):
            node, action, stop = carry
            return ~stop

        def body(carry):
            node, _, _ = carry
            at_term = done_m[node]
            sel = _select_action(prior[node], visits[node],
                                 value_sum[node])
            if forced_k:
                # trace-time gate: serving/default searchers (0.0)
                # compile exactly the pre-forced-playout program
                sel = jnp.where(
                    node == root,
                    _select_action_root(prior[node], visits[node],
                                        value_sum[node]), sel)
            action = jnp.where(at_term, -1, sel)
            nxt = jnp.where(action >= 0, child[node, action], -1)
            stop = at_term | (nxt < 0)
            return (jnp.where(stop, node, nxt), action, stop)

        # pre-execute the root step with the forced action (if any):
        # the carry then starts at the forced edge's child — or stops
        # on the root edge itself when it is unexpanded/terminal
        at_term0 = done_m[root]
        forced = (root_action >= 0) & ~at_term0
        nxt0 = jnp.where(forced, child[root, root_action], -1)
        stop0 = at_term0 | (forced & (nxt0 < 0))
        init = (jnp.where(stop0 | ~forced, root, nxt0)
                .astype(jnp.int32),
                jnp.where(at_term0, -1,
                          jnp.where(forced, root_action, -1))
                .astype(jnp.int32),
                stop0)
        node, action, _ = lax.while_loop(cond, body, init)
        return node, action

    def _backup_one(visits, value_sum, parent, paction, start_node,
                    start_action, v_child):
        """Single-game backup: add the evaluation along the path back
        to the root, alternating sign each level. ``v_child`` is from
        the evaluated state's player-to-move perspective, so the edge
        into it scores ``-v_child`` for its chooser."""
        def cond(carry):
            node, *_ = carry
            return node >= 0

        def body(carry):
            node, action, v, visits, value_sum = carry
            visits = visits.at[node, action].add(1)
            value_sum = value_sum.at[node, action].add(v)
            return (parent[node], paction[node], -v, visits, value_sum)

        _, _, _, visits, value_sum = lax.while_loop(
            cond, body,
            (start_node, start_action, -v_child, visits, value_sum))
        return visits, value_sum

    def prepare_sim(tree: DeviceTree, root_actions) -> SimStep:
        """SELECT + EXPAND half of one lockstep simulation: descend,
        step the selected edge, and return the :class:`SimStep` whose
        ``eval_states`` an evaluator must score. ``root_actions``
        (i32 [B], -1 = free) forces each game's first edge — the
        Gumbel searcher's scheduled candidates."""
        node, action = jax.vmap(_descend_one)(
            tree.prior, tree.visits, tree.value_sum, tree.child,
            tree.states.done, root_actions, tree.root)

        # candidate child states: step the selected edge (terminal
        # descends step a no-op pass on an already-done state — the
        # result is discarded for those games)
        parent_states = jax.vmap(_state_at)(tree.states, node)
        safe_action = jnp.where(action >= 0, action, n)
        new_states_b = vstep(parent_states, safe_action)

        expanding = action >= 0                       # bool [B]

        # evaluate: expanded games evaluate the new child state;
        # terminal descends evaluate the terminal node's own state
        eval_states = jax.tree.map(
            lambda a, b: jnp.where(
                expanding.reshape((-1,) + (1,) * (a.ndim - 1)), a, b),
            new_states_b, parent_states)
        # transposition key per eval row — a handful of XOR lanes off
        # the carried hash; dead-code-eliminated in the fused
        # ``simulate`` path (where no external evaluator reads it)
        eval_keys = jax.vmap(functools.partial(eval_signature, cfg))(
            eval_states)
        return SimStep(node=node, safe_action=safe_action,
                       expanding=expanding, eval_states=eval_states,
                       eval_keys=eval_keys)

    def apply_sim(tree: DeviceTree, ctx: SimStep, priors,
                  values) -> DeviceTree:
        """WRITE + BACKUP half of one simulation: store the evaluated
        leaf (where expanding & slab not full) and back ``values`` up
        the path. ``(priors, values)`` must be the evaluation of
        ``ctx.eval_states`` — from the in-search ``eval_batch`` or an
        external (cross-game batching) evaluator; the two compose to
        exactly the fused ``simulate``."""
        node, safe_action = ctx.node, ctx.safe_action
        # the written rows are exactly the expanding ones, where
        # eval_states IS the stepped child (SimStep docstring)
        expanding, new_states_b = ctx.expanding, ctx.eval_states
        full = tree.n_nodes >= m
        idx = jnp.where(expanding & ~full,
                        jnp.minimum(tree.n_nodes, m - 1), 0)

        # write the new node (only where expanding & not full)
        write = expanding & ~full

        def write_state(slab, i, st, w):
            return jax.tree.map(
                lambda buf, v: jnp.where(w, buf.at[i].set(v), buf),
                slab, st)

        states = jax.vmap(write_state)(tree.states, idx, new_states_b,
                                       write)
        prior = jax.vmap(
            lambda p, i, row, w: jnp.where(w, p.at[i].set(row), p))(
                tree.prior, idx, priors, write)
        child = jax.vmap(
            lambda c, nd, a, i, w: jnp.where(
                w, c.at[nd, a].set(i), c))(
                tree.child, node, safe_action, idx, write)
        parent = jax.vmap(
            lambda p, i, nd, w: jnp.where(w, p.at[i].set(nd), p))(
                tree.parent, idx, node, write)
        paction = jax.vmap(
            lambda p, i, a, w: jnp.where(w, p.at[i].set(a), p))(
                tree.paction, idx, safe_action, write)
        n_nodes = tree.n_nodes + write.astype(jnp.int32)

        # backup start: the edge INTO the evaluated state — (node,
        # action) for expansions (stored or capacity-skipped alike),
        # the terminal node's own parent edge otherwise. A terminal
        # ROOT (parent -1) skips the backup loop entirely.
        start_node = jnp.where(expanding, node,
                               jax.vmap(lambda p, nd: p[nd])(
                                   tree.parent, node))
        start_action = jnp.where(
            expanding, safe_action,
            jax.vmap(lambda p, nd: p[nd])(tree.paction, node))
        visits, value_sum = jax.vmap(_backup_one)(
            tree.visits, tree.value_sum, parent, paction,
            start_node, start_action, values)

        return DeviceTree(states, prior, visits, value_sum, child,
                          parent, paction, n_nodes, tree.root)

    def simulate(params_p, params_v, tree: DeviceTree,
                 root_actions=None) -> DeviceTree:
        """One lockstep simulation across the whole game batch —
        :func:`prepare_sim` → :func:`eval_batch` → :func:`apply_sim`
        fused into the caller's trace."""
        if root_actions is None:
            root_actions = jnp.full(
                (tree.n_nodes.shape[0],), -1, jnp.int32)
        ctx = prepare_sim(tree, root_actions)
        priors, values = eval_batch(params_p, params_v,
                                    ctx.eval_states)
        return apply_sim(tree, ctx, priors, values)

    def advance_sim(tree: DeviceTree, ctx: SimStep, priors, values,
                    root_actions):
        """Serving's steady-state program: APPLY this simulation and
        PREPARE the next in ONE compiled call — halves the
        per-simulation dispatch count of the split path and lets XLA
        fuse the node write into the next descent's reads. Returns
        ``(tree', ctx')``."""
        tree = apply_sim(tree, ctx, priors, values)
        return tree, prepare_sim(tree, root_actions)

    def _root_stats(tree: DeviceTree):
        idx = tree.root[:, None, None]
        root_visits = jnp.take_along_axis(tree.visits, idx,
                                          axis=1)[:, 0, :]
        root_vsum = jnp.take_along_axis(tree.value_sum, idx,
                                        axis=1)[:, 0, :]
        root_q = jnp.where(
            root_visits > 0,
            root_vsum
            / jnp.maximum(root_visits.astype(jnp.float32), 1.0),
            0.0)
        return root_visits, root_q

    @jax.jit
    def advance_root(tree: DeviceTree, actions):
        """Move each game's root down the ``actions`` edge (subtree
        reuse after a move is played). Returns ``(tree, ok bool [B])``
        — where the edge is unexpanded (``ok`` False) the root is
        unchanged and the caller must rebuild with :func:`init`."""
        nxt = jax.vmap(lambda c, r, a: c[r, a])(
            tree.child, tree.root, actions.astype(jnp.int32))
        ok = nxt >= 0
        return tree._replace(
            root=jnp.where(ok, nxt, tree.root).astype(jnp.int32)), ok

    @functools.partial(jax.jit, static_argnames=("k",))
    def run_sims(params_p, params_v, tree: DeviceTree, k: int):
        """``k`` simulations as one compiled program (tree in/out) —
        the chunking unit for watchdog-limited backends: drive
        ``init`` + repeated ``run_sims`` from a host loop, with the
        tree device-resident between calls, then ``root_stats``."""
        return lax.fori_loop(
            0, k, lambda _, t: simulate(params_p, params_v, t), tree)

    @jax.jit
    def search(params_p, params_v, roots: GoState):
        tree = init_tree(params_p, params_v, roots)
        tree = run_sims(params_p, params_v, tree, n_sim)
        return _root_stats(tree)

    # the chunk loop's program: same trace as run_sims, but the tree
    # slab is DONATED into the program so a pipelined loop (one chunk
    # in flight while the next is prepared) never holds two slabs.
    # Callers that keep their tree use `run_sims` (non-donating);
    # the loop below protects a non-owned input with one copy.
    run_sims_donated = functools.partial(
        jax.jit, static_argnames=("k",), donate_argnums=(2,))(
        lambda params_p, params_v, tree, k: lax.fori_loop(
            0, k, lambda _, t: simulate(params_p, params_v, t), tree))

    def _run_sims_budget_impl(params_p, params_v, tree, budget, j0,
                              k: int):
        """``k`` simulations with a PER-GAME sim budget (i32 [B]):
        global sim index ``j0 + i`` runs only on rows still under
        their budget — retired rows keep their slab bit-for-bit (the
        playout-cap randomization mask; the chunk loop's early exit
        at ``max(budget)`` is where the wall-clock saving is)."""
        def body(i, t):
            t2 = simulate(params_p, params_v, t)
            return _where_rows((j0 + i) < budget, t2, t)

        return lax.fori_loop(0, k, body, tree)

    copy_tree = jax.jit(lambda t: jax.tree.map(jnp.copy, t))

    def run_sims_chunked(params_p, params_v, tree: DeviceTree,
                         chunk: int, n: int | None = None,
                         deadline=None, depth: int | None = None,
                         pipeline: ChunkPipeline | None = None,
                         owned: bool = False, budget=None):
        """The one owner of the watchdog chunk schedule: ``n``
        (default ``n_sim``; a game clock may ask for fewer)
        simulations as ``chunk``-sized compiled programs, tree
        device-resident in between. Returns ``(tree, ran)`` — the
        simulations actually dispatched.

        PIPELINED (``runtime.pipeline``): the loop dispatches through
        a :class:`ChunkPipeline` (``depth`` in-flight chunks; default
        env/1, ``depth=0`` = the old fully-sync behavior; pass
        ``pipeline`` to share one across calls, e.g. a bench A/B) and
        DONATES the tree slab into each chunk program so pipelining
        never doubles slab memory. The input ``tree`` is treated as
        caller-owned and copied once before the first donation —
        callers that hand the tree over (the player, the self-play
        loop) pass ``owned=True`` to skip the copy. Results are
        bit-identical to the sync path at any depth: same programs,
        same operands, same order.

        ``deadline`` (a :class:`~rocalphago_tpu.runtime.deadline.
        Deadline` or None) is the hard wall-clock enforcer: it is
        checked before every chunk AFTER the first (the anytime floor
        — an already-expired deadline still yields one searched
        chunk). The pipeline paces the host to real device completion
        lagged by ``depth`` chunks, so on expiry at most ``depth``
        chunks (one, at the default) are still in flight — they
        complete, their simulations count, and argmax of the returned
        tree's visits is the anytime answer; the hard-stop overshoot
        is bounded by those in-flight chunks (docs/RESILIENCE.md).

        Observability: per-chunk latency is recorded only at
        ``depth=0`` (the only mode that can attribute wall time to
        one chunk); the pipeline records ``dispatch_gap_s`` /
        ``device_occupancy`` at any depth, and sims-per-sec plus the
        deadline-margin gauge are recorded while a deadline is armed
        (the enforced path drains, so the numbers are real execution
        time)."""
        n = n_sim if n is None else n
        if budget is not None:
            # per-row budgets (i32 [B], playout-cap randomization):
            # the caller usually passes n = host-known max(budget) so
            # the loop early-exits; without it the mask alone keeps
            # results right at full-loop cost
            budget = budget.astype(jnp.int32)
        enforce = deadline is not None and not deadline.unlimited
        pipe = pipeline if pipeline is not None else ChunkPipeline(
            depth, runner="device_mcts")
        if not owned and n > 0:
            tree = copy_tree(tree)   # first donation eats our copy,
            #                          never the caller's buffers
        ran = 0
        t_start = time.monotonic()
        for done in range(0, n, chunk):
            if ran and enforce and deadline.expired():
                break
            faults.barrier("search.chunk", done // chunk)
            k = min(chunk, n - done)
            # the chunk program is read off the ``search`` attribute
            # (not the closure) so tests/instrumentation can wrap it
            t0 = time.monotonic()
            if budget is None:
                tree = search.run_sims_donated(params_p, params_v,
                                               tree, k=k)
            else:
                tree = search.run_sims_budget_donated(
                    params_p, params_v, tree, budget,
                    jnp.int32(done), k=k)
            # the pipeline handle must be a FRESH array: the next
            # chunk donates the tree itself, which would delete
            # n_nodes out from under the retire's block
            pipe.push(tree.n_nodes + 0)
            if enforce and pipe.depth == 0:
                _chunk_h.observe(time.monotonic() - t0)
            ran += k
        _sims_c.inc(ran)
        if enforce:
            pipe.drain()
            elapsed = time.monotonic() - t_start
            if elapsed > 0:
                _rate_h.observe(ran / elapsed)
            rem = deadline.remaining()
            if rem is not None:
                _margin_g.set(rem)
        else:
            pipe.finish()
        return tree, ran

    def run_chunked(params_p, params_v, roots: GoState, chunk: int,
                    tree: DeviceTree | None = None, deadline=None,
                    depth: int | None = None,
                    pipeline: ChunkPipeline | None = None,
                    owned: bool = False, n: int | None = None,
                    budget=None):
        """Full search as ``chunk``-simulation compiled programs with
        the tree device-resident in between — THE way to drive this
        on watchdog-limited backends (the ~40s TPU worker limit);
        identical results to :func:`search` (deterministic, the tree
        carry is the entire state) unless a ``deadline`` expires
        mid-search, in which case the stats reflect the simulations
        that fit. Pass ``tree`` to resume from a prepared tree (e.g.
        root priors mixed with exploration noise, or a reused
        subtree) instead of ``init(roots)``; ``depth``/``pipeline``/
        ``owned`` thread through to :func:`run_sims_chunked` (the
        loop donates the tree slab — ``owned=True`` hands a passed
        tree over). ``n``/``budget`` are the playout-cap seam: ``n``
        caps the sims this search runs (host-known, so the chunk loop
        early-exits), ``budget`` adds per-row i32 [B] masking for a
        mixed-budget batch."""
        if tree is None:
            tree = search.init(params_p, params_v, roots)
            owned = True             # init's output is loop-internal
        tree, ran = run_sims_chunked(params_p, params_v, tree, chunk,
                                     n=n, deadline=deadline,
                                     depth=depth, pipeline=pipeline,
                                     owned=owned, budget=budget)
        search.last_ran = ran
        return search.root_stats(tree)

    def _pruned_targets(tree: DeviceTree):
        """Policy target with forced playouts PRUNED back out (the
        KataGo policy-target-pruning rule, vectorized in-jit): per
        root child except the most-visited, subtract its forced-visit
        floor ``sqrt(forced_k · p · N)``, zero children left below one
        real visit (forced-only exploration must not teach the
        policy), keep the most-visited child whole, renormalize.
        Returns ``(target f32 [B, A] summing to 1 per searched row,
        pruned i32 [B] visits removed)``. With ``forced_k == 0`` the
        floor is 0 and the target is exactly the normalized visit
        distribution."""
        visits, _ = _root_stats(tree)
        idx = tree.root[:, None, None]
        prior = jnp.take_along_axis(tree.prior, idx, axis=1)[:, 0, :]
        nv = visits.astype(jnp.float32)
        total = nv.sum(axis=-1, keepdims=True)
        floor = jnp.sqrt(jnp.float32(forced_k) * prior * total)
        on_best = (jnp.arange(nv.shape[-1])[None, :]
                   == jnp.argmax(nv, axis=-1)[:, None])
        kept = jnp.maximum(nv - floor, 0.0)
        kept = jnp.where(kept < 1.0, 0.0, kept)
        kept = jnp.where(on_best, nv, kept)
        norm = kept.sum(axis=-1, keepdims=True)
        target = jnp.where(norm > 0, kept / jnp.maximum(norm, 1.0),
                           0.0)
        pruned = (total - norm)[:, 0].astype(jnp.int32)
        return target, pruned

    # serving-path telemetry (obs.registry): hoisted once per searcher
    # so the chunk loop pays a method call, not a registry lookup
    _chunk_h = obs_registry.histogram("device_mcts_chunk_seconds")
    _rate_h = obs_registry.histogram("device_mcts_sims_per_s",
                                     edges=obs_registry.RATE_EDGES)
    _margin_g = obs_registry.gauge("device_mcts_deadline_margin_s")
    _sims_c = obs_registry.counter("device_mcts_sims_total")

    # chunk-driving surface (same convention as the chunked runners):
    # search.init → DeviceTree, search.run_sims(…, k=) → DeviceTree,
    # search.root_stats(tree) → (visits, q); search.run_chunked =
    # all three composed. init/run_sims are compile-tracked
    # (obs.jaxobs): an unexpected recompile — a new chunk size, a new
    # komi — surfaces as a named `compile` event.
    # run_sims_donated is the chunk loop's program (tree slab donated
    # in — see run_sims_chunked); wrap THAT attribute to intercept
    # the loop's chunks. Its donates_buffers marks it unretryable
    # (runtime.retries refuses to wrap it).
    search.init = jaxobs.track("device_mcts.init", jax.jit(init_tree))
    # incremental-root sibling: (params_p, params_v, roots, caches) →
    # (tree, caches') — the GTP/DeviceMCTSPlayer root advance carries
    # the cache across moves; make_caches builds the cold carry
    search.init_cached = jaxobs.track(
        "device_mcts.init_cached", jax.jit(init_tree_cached))
    search.make_caches = functools.partial(init_caches, cfg)
    search.run_sims = jaxobs.track("device_mcts.run_sims", run_sims)
    search.run_sims_donated = jaxobs.track(
        "device_mcts.run_sims", run_sims_donated)
    search.run_sims_donated.donates_buffers = True
    # playout-cap sibling of run_sims_donated: per-row sim budgets
    # masked in-program (same donation discipline; budget/j0 traced
    # so one program serves every draw)
    search.run_sims_budget_donated = jaxobs.track(
        "device_mcts.run_sims_budget",
        functools.partial(jax.jit, static_argnames=("k",),
                          donate_argnums=(2,))(_run_sims_budget_impl))
    search.run_sims_budget_donated.donates_buffers = True
    # forced-playout training target (f32 distribution); the plain
    # visit-count target when forced_k == 0
    search.pruned_targets = jax.jit(_pruned_targets)
    search.run_sims_chunked = run_sims_chunked
    search.root_stats = jax.jit(_root_stats)
    search.run_chunked = run_chunked
    search.simulate = simulate          # forced-root hook (Gumbel)
    # injectable-evaluator surface (rocalphago_tpu/serve): the serving
    # subsystem drives prepare_sim → [shared cross-game evaluator] →
    # apply_sim per simulation, with eval_batch as the evaluator's
    # compiled program (padded to a few fixed batch sizes). The fused
    # paths above compose the SAME two halves around the in-trace
    # eval, so the split path cannot drift from the fused one.
    search.prepare_sim = jax.jit(prepare_sim)
    search.apply_sim = jax.jit(apply_sim)
    search.advance_sim = jax.jit(advance_sim)
    search.assemble_tree = jax.jit(_assemble_tree)
    search.eval_batch = jaxobs.track("device_mcts.eval_batch",
                                     jax.jit(eval_batch))
    # per-session komi variant (rocalphago_tpu/serve): the evaluator
    # switches to this program only when a custom-komi request is in
    # the batch, so default-komi traffic stays on eval_batch bit-for-
    # bit. Compiled lazily, once per batch size, for ALL komi values.
    search.eval_batch_komi = jaxobs.track(
        "device_mcts.eval_batch_komi", jax.jit(eval_batch_komi))
    # transposition key of a batch of states (uint32 [B, 2]) — the
    # serving evaluator's cache key program for rows that don't come
    # through prepare_sim (root evals); SimStep.eval_keys covers the
    # in-search rows without a second dispatch.
    search.eval_key = jaxobs.track(
        "device_mcts.eval_key",
        jax.jit(jax.vmap(functools.partial(eval_signature, cfg))))
    search.advance_root = advance_root  # subtree reuse across moves
    search.max_nodes = max_nodes        # the slab size actually built
    search.last_ran = None              # sims the last chunked run ran
    return search


def _halving_schedule(n_sim: int, m: int) -> list[tuple[int, int]]:
    """Sequential-halving plan: ``[(k_candidates, visits_per_cand)]``.

    Candidate count halves each phase (m, m//2, …, 2); the simulation
    budget is split evenly across phases, and whatever the integer
    division leaves over goes to the final (2-candidate) phase, where
    extra visits sharpen exactly the comparison that decides the move.
    Every phase visits each surviving candidate at least once, so for
    tiny ``n_sim`` the actual total can exceed ``n_sim`` (documented
    in :func:`make_gumbel_mcts`)."""
    ks, k = [], m
    while k >= 2:
        ks.append(k)
        k //= 2
    p = len(ks)
    sched = [(k, max(1, n_sim // (p * k))) for k in ks]
    used = sum(k * v for k, v in sched)
    leftover = n_sim - used
    if leftover >= ks[-1]:
        k, v = sched[-1]
        sched[-1] = (k, v + leftover // k)
    return sched


def gumbel_plan_sims(n_sim: int, m_root: int, num_actions: int) -> int:
    """Real simulation count of a Gumbel search's halving plan.

    Every halving phase must visit each surviving candidate at least
    once, so for small ``n_sim`` the plan total exceeds the nominal
    budget (e.g. n_sim=8, m_root=16 → 30). Slabs sized from nominal
    ``n_sim`` silently saturate; size them from THIS instead."""
    m = max(2, min(m_root, num_actions))
    return sum(k * v for k, v in _halving_schedule(n_sim, m))


def make_gumbel_mcts(cfg: GoConfig, policy_features: tuple,
                     value_features: tuple,
                     policy_apply: Callable, value_apply: Callable,
                     n_sim: int, max_nodes: int | None = None,
                     m_root: int = 16,
                     c_visit: float = 50.0, c_scale: float = 0.1,
                     c_puct: float = 5.0):
    """Gumbel root search over the device tree (Danihelka et al. 2022,
    the mctx pattern): the move decision at low simulation budgets.

    PUCT spends its root budget proportionally to priors + optimism —
    at 16–64 sims/move (the regime the on-device search serves in) it
    often never tries the 2nd-best prior twice. Gumbel instead:

    1. samples ``m_root`` root candidates without replacement via
       Gumbel-top-k on the masked policy logits (``g(a) = logits(a) +
       Gumbel noise``) — a principled exploration draw;
    2. runs SEQUENTIAL HALVING over the candidates: every survivor
       gets the same number of simulations per phase (scheduled by
       :func:`_halving_schedule`; below the root, selection stays
       PUCT), then the worse half is dropped by the score
       ``g(a) + σ(q̂(a))``, where σ min–max-rescales the completed q̂
       to [0, 1] and scales by ``(c_visit + max_N)·c_scale`` (see
       :func:`_sigma_completed`);
    3. returns the last survivor as ``best`` — the action the player
       should take (argmax root visits is the PUCT convention; under
       a halving schedule visit counts reflect the schedule, not the
       conclusion, so callers must use ``best``).

    Returns ``search(params_p, params_v, roots, rng) ->
    (root_visits [B, A], root_q [B, A], best [B], pi [B, A])`` — with
    ``pi`` the improved policy ``softmax(logits + σ(completed q̂))``,
    the Gumbel MuZero training target — plus the same chunk-driving
    surface as :func:`make_device_mcts`
    (``init/run_phase/rerank/root_stats/improved_policy/
    run_chunked``). For tiny
    ``n_sim`` (< one visit per candidate per phase) the actual
    simulation count can exceed ``n_sim`` — every phase must visit
    each survivor once to have a score to halve on.
    """
    num_actions = cfg.num_points + 1
    m = max(2, min(m_root, num_actions))
    schedule = _halving_schedule(n_sim, m)
    if max_nodes is None:
        # the halving plan's REAL simulation count, not nominal n_sim
        # — a 2*n_sim slab silently saturates small-budget searches
        max_nodes = 2 * gumbel_plan_sims(n_sim, m_root, num_actions)
    base = make_device_mcts(cfg, policy_features, value_features,
                            policy_apply, value_apply, n_sim=n_sim,
                            max_nodes=max_nodes, c_puct=c_puct)
    neg = jnp.float32(jnp.finfo(jnp.float32).min)

    def _root_draw(tree: DeviceTree, rng):
        """Gumbel-top-k root candidate draw off an initialized tree:
        ``(tree, g, cand, logits)`` — shared by the from-scratch and
        incremental-root inits."""
        root_prior = tree.prior[:, 0, :]
        logits = jnp.where(root_prior > 0, jnp.log(
            jnp.maximum(root_prior, 1e-38)), neg)
        gumbel = jax.random.gumbel(rng, logits.shape, jnp.float32)
        g = jnp.where(root_prior > 0, logits + gumbel, neg)
        _, cand = lax.top_k(g, m)
        return tree, g, cand.astype(jnp.int32), logits

    def init(params_p, params_v, roots: GoState, rng):
        """-> (tree, g f32 [B, A], cand i32 [B, m], logits f32 [B, A])
        — the tree with root priors, the gumbel-perturbed root logits,
        the ranked candidate actions, and the raw (noise-free) masked
        logits the improved-policy target is built from."""
        tree = base.init(params_p, params_v, roots)
        return _root_draw(tree, rng)

    def init_cached(params_p, params_v, roots: GoState, rng, caches):
        """:func:`init` with the root encode through the incremental
        path (``base.init_cached``) → ``(tree, g, cand, logits,
        caches')``. Gumbel rebuilds its tree every move by design, so
        the root encode is per-move serving cost — exactly the
        successive-positions pattern the delta cache pays for."""
        tree, caches = base.init_cached(params_p, params_v, roots,
                                        caches)
        return _root_draw(tree, rng) + (caches,)

    def _sigma_completed(tree: DeviceTree):
        """σ(completed q̂) over every root action — the Gumbel value
        transform shared by halving ranking and the π' target
        (mctx's ``qtransform_completed_by_mix_value`` shape):

        1. complete: unvisited actions take the visit-weighted mean
           of the visited q̂ (a no-extra-eval simplification of
           mctx's prior-weighted mixed value);
        2. rescale completed q̂ to [0, 1] per state (min–max over the
           prior-supported actions) — without this, raw q ∈ [-1, 1]
           times (c_visit + maxN) swamps the logits and π' collapses
           to argmax-of-value-noise (observed: a π'-target zero run
           whose policy loss would not fall);
        3. scale by ``(c_visit + max_N) · c_scale`` (mctx defaults:
           50.0 / 0.1), growing value weight as evidence accumulates.

        Returns ``(visits, sigma)``.
        """
        visits, q = base.root_stats(tree)
        nv = visits.astype(jnp.float32)
        total = nv.sum(axis=-1, keepdims=True)
        q_bar = (nv * q).sum(axis=-1, keepdims=True) \
            / jnp.maximum(total, 1.0)
        completed = jnp.where(visits > 0, q, q_bar)
        valid = tree.prior[:, 0, :] > 0
        lo = jnp.min(jnp.where(valid, completed, jnp.inf),
                     axis=-1, keepdims=True)
        hi = jnp.max(jnp.where(valid, completed, -jnp.inf),
                     axis=-1, keepdims=True)
        rescaled = (completed - lo) / jnp.maximum(hi - lo, 1e-8)
        rescaled = jnp.where(valid & (hi > lo), rescaled, 0.0)
        maxn = visits.max(axis=-1, keepdims=True).astype(jnp.float32)
        return visits, (c_visit + maxn) * c_scale * rescaled

    def _scores(tree: DeviceTree, g):
        visits, sigma = _sigma_completed(tree)
        return jnp.where(visits > 0, g + sigma, g)

    def improved_policy(tree: DeviceTree, logits):
        """π' = softmax(logits + σ(completed q̂)) — the Gumbel MuZero
        training target (see :func:`_sigma_completed`)."""
        _, sigma = _sigma_completed(tree)
        masked = jnp.where(logits > neg / 2, logits + sigma, neg)
        return jax.nn.softmax(masked, axis=-1)

    def rerank(tree: DeviceTree, g, cand, k: int):
        """Sort the first ``k`` candidates by ``g + σ(q̂)`` descending
        (the halving step: the next phase reads the first k//2)."""
        s = jnp.take_along_axis(_scores(tree, g), cand[:, :k], axis=-1)
        order = jnp.argsort(-s, axis=-1)
        head = jnp.take_along_axis(cand[:, :k], order, axis=-1)
        return jnp.concatenate([head, cand[:, k:]], axis=-1)

    def _forced_candidate(g, cand, slot):
        """Root candidate forced by schedule slot ``slot`` (i32
        scalar): candidates beyond the sensible set (possible when
        fewer than m moves are sensible) carry ``-inf`` g — those
        slots redirect to the top candidate instead of forcing an
        unreachable edge."""
        forced = jnp.take_along_axis(
            cand, jnp.broadcast_to(slot, (cand.shape[0], 1)),
            axis=-1)[:, 0]
        g_f = jnp.take_along_axis(g, forced[:, None], axis=-1)[:, 0]
        return jnp.where(g_f > neg / 2, forced, cand[:, 0])

    def _run_phase_impl(params_p, params_v, tree: DeviceTree, g, cand,
                        j0, count: int, k: int):
        """``count`` scheduled simulations (one compiled program):
        sim ``j`` forces root candidate ``(j0 + j) % k`` (see
        :func:`_forced_candidate` for the -inf-slot redirect)."""
        def body(i, t):
            forced = _forced_candidate(g, cand, (j0 + i) % k)
            return base.simulate(params_p, params_v, t, forced)

        return lax.fori_loop(0, count, body, tree)

    def _run_phase_budget_impl(params_p, params_v, tree: DeviceTree,
                               g, cand, j0, ran0, budget, count: int,
                               k: int):
        """:func:`_run_phase_impl` under per-game sim budgets
        (playout-cap randomization): the budget counts GLOBAL sims
        across the whole halving plan (``ran0`` = sims already run),
        and a row past its budget keeps its slab bit-for-bit — the
        between-phase rerank then ranks whatever evidence that row
        gathered, the same anytime rule a deadline expiry applies."""
        def body(i, t):
            forced = _forced_candidate(g, cand, (j0 + i) % k)
            t2 = base.simulate(params_p, params_v, t, forced)
            return _where_rows((ran0 + i) < budget, t2, t)

        return lax.fori_loop(0, count, body, tree)

    run_phase = functools.partial(
        jax.jit, static_argnames=("count", "k"))(_run_phase_impl)

    def search_impl(params_p, params_v, roots: GoState, rng):
        tree, g, cand, logits = init(params_p, params_v, roots, rng)
        for k, v in schedule:        # static plan — unrolls into jit
            tree = run_phase(params_p, params_v, tree, g, cand,
                             jnp.int32(0), count=k * v, k=k)
            cand = rerank(tree, g, cand, k)
        visits, q = base.root_stats(tree)
        return visits, q, cand[:, 0], improved_policy(tree, logits)

    search = jax.jit(search_impl)

    def run_chunked(params_p, params_v, roots: GoState, rng,
                    chunk: int, deadline=None,
                    depth: int | None = None,
                    pipeline: ChunkPipeline | None = None,
                    caches=None, n: int | None = None, budget=None):
        """Phase-by-phase, ``chunk``-simulation compiled programs with
        the tree device-resident in between (the ~40s TPU worker
        watchdog); identical results to :func:`search` unless a
        ``deadline`` (:class:`~rocalphago_tpu.runtime.deadline.
        Deadline`) expires mid-plan. On expiry the halving stops
        where it is, the SURVIVING candidates are reranked by the
        evidence gathered so far, and ``best`` is the anytime answer
        (``g + σ(q̂)`` argmax — the same rule a completed phase
        applies, on a truncated schedule). The first chunk always
        runs; ``search.last_ran`` reports the real simulation count.

        Pipelined like the PUCT loop (``runtime.pipeline``): the host
        dispatches through a :class:`ChunkPipeline` (``depth`` chunks
        in flight, default env/1) and each phase-chunk program
        DONATES the tree slab (the tree is loop-internal — ``init``'s
        output — so no defensive copy is needed; ``g``/``cand`` are
        reused across phases and stay un-donated). The between-phase
        rerank is a device-side dependency of the next phase, so it
        needs no host sync; deadline expiry may leave up to ``depth``
        chunks in flight — they complete and count, the overshoot
        bound (docs/RESILIENCE.md).

        ``n``/``budget`` are the playout-cap seam: ``n`` (host int)
        truncates the halving plan at that many sims — the loop stops
        dispatching, the surviving candidates are reranked on the
        evidence so far and ``best``/π' are the anytime answer, the
        SAME rule a deadline expiry applies; ``budget`` (i32 [B])
        additionally masks per-row for a mixed-budget batch (rows
        past their budget freeze; sims count globally across
        phases)."""
        if caches is None:
            tree, g, cand, logits = init_j(params_p, params_v, roots,
                                           rng)
        else:
            # incremental root encode; the refreshed carry comes back
            # on search.last_caches (same convention as last_ran) —
            # the return tuple stays (visits, q, best, pi)
            tree, g, cand, logits, caches = init_cached_j(
                params_p, params_v, roots, rng, caches)
        search.last_caches = caches
        enforce = deadline is not None and not deadline.unlimited
        pipe = pipeline if pipeline is not None else ChunkPipeline(
            depth, runner="gumbel")
        ran, out_of_time, chunk_i = 0, False, 0
        t_start = time.monotonic()
        if budget is not None:
            budget = budget.astype(jnp.int32)
        for k, v in schedule:
            total = k * v
            for j0 in range(0, total, chunk):
                if ran and enforce and deadline.expired():
                    out_of_time = True
                    break
                if n is not None and ran >= n:
                    # playout cap reached: stop dispatching — the
                    # rerank below is the anytime answer
                    out_of_time = True
                    break
                faults.barrier("search.chunk", chunk_i)
                chunk_i += 1
                count = min(chunk, total - j0)
                if n is not None:
                    count = min(count, n - ran)
                # read off the attribute (not the closure) so tests/
                # instrumentation can wrap the compiled phase program
                t0 = time.monotonic()
                if budget is None:
                    tree = search.run_phase_donated(
                        params_p, params_v, tree, g, cand,
                        jnp.int32(j0), count=count, k=k)
                else:
                    tree = search.run_phase_budget_donated(
                        params_p, params_v, tree, g, cand,
                        jnp.int32(j0), jnp.int32(ran), budget,
                        count=count, k=k)
                # fresh handle: the next chunk donates the tree (see
                # the PUCT loop)
                pipe.push(tree.n_nodes + 0)
                if enforce and pipe.depth == 0:
                    _chunk_h.observe(time.monotonic() - t0)
                ran += count
            # rerank even a truncated phase: the anytime ``best`` is
            # the top candidate under whatever evidence exists
            cand = rerank_j(tree, g, cand, k)
            if out_of_time:
                break
        _sims_c.inc(ran)
        if enforce:
            pipe.drain()
            elapsed = time.monotonic() - t_start
            if elapsed > 0:
                _rate_h.observe(ran / elapsed)
            rem = deadline.remaining()
            if rem is not None:
                _margin_g.set(rem)
        else:
            pipe.finish()
        search.last_ran = ran
        visits, q = base.root_stats(tree)
        return visits, q, cand[:, 0], improved_j(tree, logits)

    init_j = jax.jit(init)
    init_cached_j = jaxobs.track("device_mcts.init_cached",
                                 jax.jit(init_cached))
    rerank_j = jax.jit(rerank, static_argnames=("k",))
    improved_j = jax.jit(improved_policy)

    # same serving-path telemetry as the PUCT chunk loop (shared
    # metric names — one histogram serves both searchers)
    _chunk_h = obs_registry.histogram("device_mcts_chunk_seconds")
    _rate_h = obs_registry.histogram("device_mcts_sims_per_s",
                                     edges=obs_registry.RATE_EDGES)
    _margin_g = obs_registry.gauge("device_mcts_deadline_margin_s")
    _sims_c = obs_registry.counter("device_mcts_sims_total")

    search.init = init_j
    search.init_cached = init_cached_j
    search.make_caches = base.make_caches
    search.last_caches = None   # refreshed carry from run_chunked
    search.rerank = rerank_j
    search.run_phase = jaxobs.track("device_mcts.run_phase", run_phase)
    # the chunk loop's program: run_phase with the tree slab donated
    # in (g/cand are NOT donated — they live across phases); wrap
    # THIS attribute to intercept the loop's chunks
    search.run_phase_donated = jaxobs.track(
        "device_mcts.run_phase",
        functools.partial(jax.jit, static_argnames=("count", "k"),
                          donate_argnums=(2,))(_run_phase_impl))
    search.run_phase_donated.donates_buffers = True
    # playout-cap sibling: per-row GLOBAL sim budgets masked into the
    # phase program (budget/ran0 traced — one program per (count, k))
    search.run_phase_budget_donated = jaxobs.track(
        "device_mcts.run_phase_budget",
        functools.partial(jax.jit, static_argnames=("count", "k"),
                          donate_argnums=(2,))(_run_phase_budget_impl))
    search.run_phase_budget_donated.donates_buffers = True
    search.root_stats = base.root_stats
    search.improved_policy = improved_j
    search.run_chunked = run_chunked
    search.schedule = schedule
    search.m_root = m
    search.max_nodes = max_nodes        # the slab size actually built
    search.last_ran = None              # sims the last chunked run ran
    return search


class DeviceMCTSPlayer:
    """GTP/tournament-facing agent over the on-device search.

    ``get_move(pygo.GameState) -> move | None`` (None = pass): the
    host state is bridged once (:func:`jaxgo.from_pygo`), the whole
    search runs on device (chunk-driven under the worker watchdog),
    and the argmax-visits move comes back — two host↔device transfers
    per move, total.

    PUCT serving REUSES the previous move's subtree: the tree is
    carried across ``get_move`` calls and its root walked down the
    moves actually played (``advance_root``), so the new search
    starts from the visits the old one already spent below that child
    — the host-tree player's ``update_with_move`` economy, in slab
    form. Falls back to a fresh tree on komi/board change, undo, an
    unexpanded edge, a near-full slab, or any position mismatch
    (handicap stones placed outside the history); ``reuse=False``
    disables, ``.reuses`` counts engagements. Gumbel mode always
    rebuilds (its root draw is per-move by design).

    TIME CONTROL: ``set_move_time(seconds)`` (wired from GTP
    ``time_settings``/``time_left`` by the engine) caps the next
    searches' simulation count at ``seconds × measured sims/sec``
    (EMA over past searches; the first timed move runs the full
    budget and seeds the estimate). PUCT shrinks to any chunk
    multiple — only already-compiled chunk programs run; gumbel
    quantizes to halvings of ``n_sim`` so at most log₂ tiers ever
    compile. ``last_n_sim`` reports what the last search really ran.

    DEADLINE: the clock plan is predictive; the same ``seconds``
    budget also arms a hard :class:`~rocalphago_tpu.runtime.deadline.
    Deadline` checked between compiled chunks — a mispredicted
    sims/sec rate or a slow chunk stops the search where it is and
    the ANYTIME answer (argmax visits so far; the gumbel rerank of
    the surviving candidates) goes out instead of blowing the wall
    clock. The floor is one chunk; under the default pipelined
    dispatch (``runtime.pipeline``, one chunk in flight while the
    host decides) the hard stop may additionally let that one
    in-flight chunk complete — its simulations count toward the
    anytime answer and the overshoot is bounded by one chunk's wall
    time (``ROCALPHAGO_PIPELINE_DEPTH=0`` restores the fully-sync
    check). ``last_deadline_hit`` / ``deadline_hits`` report
    enforcement; ``last_n_sim`` then shows the truncated count.

    ``sim_limit`` (int or None) caps the next searches' budget
    regardless of the clock — the degradation ladder's reduced-sims
    retry rung (:class:`~rocalphago_tpu.interface.resilient.
    ResilientPlayer`) sets it for its one cheap re-dispatch after a
    transient device error.
    """

    def __init__(self, value_net, policy_net, n_sim: int = 100,
                 max_nodes: int | None = None, c_puct: float = 5.0,
                 sim_chunk: int = 8, gumbel: bool = False,
                 m_root: int = 16, seed: int = 0,
                 reuse: bool = True,
                 incremental: bool | None = None):
        self.policy = policy_net
        self.value = value_net
        self.board = policy_net.board
        self._cfg = policy_net.cfg
        self._chunk = sim_chunk
        self._n_sim = n_sim
        # None → the factory's own default (2*n_sim for PUCT, 2× the
        # halving plan's real sim count for gumbel — advisor r3);
        # read back from the built searcher below so the reuse
        # check's capacity bound always matches the real slab
        self._max_nodes = max_nodes
        self._c_puct = c_puct
        self._gumbel = gumbel
        self._m_root = m_root
        self._rng = jax.random.key(seed)
        # subtree reuse (PUCT only — gumbel redraws its root noise
        # every move, so its tree is rebuilt by design): the previous
        # move's tree + the (komi, turns_played) it was searched at;
        # get_move walks the actual history delta down child pointers
        # and resumes the search from the shifted root when possible
        self._reuse = reuse and not gumbel
        self._carry = None
        self.reuses = 0     # observability: # of reused searches
        # incremental ROOT encode (features/incremental.py): serving
        # advances the root one move per get_move, so the root
        # planes' ladder chases are re-run only where the one-move
        # board delta touched their recorded footprints. Default ON
        # for this sequential path (env ROCALPHAGO_ENCODE_INCR
        # forces either way); bit-identical priors, so search results
        # never depend on the cache. The cache rides across komi
        # changes (planes don't read komi) and any position jump
        # (board-diff invalidation is the correctness mechanism);
        # reset() drops it per game for honest reuse stats.
        from rocalphago_tpu.features import incremental as _incr

        self._incr = (_incr.enabled(default=True)
                      if incremental is None else incremental)
        self._enc_cache = None
        self._enc_stats = None
        # GTP time control (see class docstring): shared clock, rate
        # samples keyed per searcher so each key's compile-bearing
        # first run never pollutes the sims/sec EMA
        self._clock = MoveClock()
        self.last_n_sim = None      # sims the last get_move ran
        # hard-deadline enforcement stats (class docstring DEADLINE)
        self.last_deadline_hit = False
        self.deadline_hits = 0
        # external per-search sim cap (degradation ladder's reduced
        # rung); None = uncapped
        self.sim_limit: int | None = None
        # per-move telemetry (obs.registry): get_move is fully synced
        # (the visit fetch), so these are real wall numbers
        self._move_h = obs_registry.histogram(
            "device_mcts_get_move_seconds")
        self._rate_h = obs_registry.histogram(
            "device_mcts_sims_per_s", edges=obs_registry.RATE_EDGES)
        # searchers are cached PER KOMI: the search's terminal-node
        # evaluations score with its GoConfig's komi, and GTP can set
        # any komi per game — same handling as the host MCTSPlayer's
        # per-komi rollout programs (search/mcts.py)
        self._searchers: dict = {}
        # build the default-komi searcher NOW: feature-layout
        # validation must fail at construction (like build_player's
        # missing-value guard), not on the first genmove
        self._max_nodes = self._searcher_for(
            self._cfg.komi)[1].max_nodes

    @property
    def n_sim(self) -> int:
        """Nominal per-move simulation budget (uncapped)."""
        return self._n_sim

    def reset(self, reason: str = "new_game") -> None:
        """Forget cross-move search state (new game): the carried
        subtree and the incremental-encode cache (counted per
        ``reason`` — ``encode_cache_resets_total{reason=...}``)."""
        self._carry = None
        if self._enc_cache is not None:
            from rocalphago_tpu.features.api import count_cache_reset

            count_cache_reset(reason)
        self._enc_cache = None
        self._enc_stats = None

    def set_move_time(self, seconds) -> None:
        """Per-move wall budget in seconds (None = no clock). The GTP
        engine calls this before every genmove from the game clock."""
        self._clock.set_move_time(seconds)

    def _effective_sims(self) -> int:
        """Simulation budget for the next search under the clock.

        ``move_time × measured sims/sec``, floored at one chunk and
        capped at nominal ``n_sim``. No clock, or no measurement yet
        (the very first search — which pays the compiles anyway and
        seeds the estimate): full budget."""
        allowed = self._clock.allowed_units()
        if self.sim_limit is not None:
            allowed = (self.sim_limit if allowed is None
                       else min(allowed, self.sim_limit))
        if allowed is None:
            return self._n_sim
        if self._gumbel:
            # halving tiers only: each distinct n_sim compiles its
            # own phase programs, so at most log2(n_sim) tiers exist.
            # The plan has a floor (every phase visits each survivor
            # once) — stop when halving no longer shrinks it, or a
            # starved clock would burn compiles on identical plans
            tier = self._n_sim
            num_actions = self._cfg.num_points + 1
            plan = gumbel_plan_sims(tier, self._m_root, num_actions)
            while tier > 2 and plan > allowed:
                nxt = max(2, tier // 2)
                nxt_plan = gumbel_plan_sims(nxt, self._m_root,
                                            num_actions)
                if nxt_plan >= plan:
                    break               # plan floor reached
                tier, plan = nxt, nxt_plan
            return tier
        # PUCT shrinks to any chunk multiple: only the already-
        # compiled chunk-sized program runs, never a new compile
        return min(self._n_sim,
                   max(self._chunk,
                       allowed // self._chunk * self._chunk))

    def _searcher_for(self, komi: float, n_sim: int | None = None):
        key = (komi, n_sim or self._n_sim)
        if key not in self._searchers:
            import dataclasses

            cfg = dataclasses.replace(self._cfg, komi=komi)
            make = (functools.partial(make_gumbel_mcts,
                                      m_root=self._m_root)
                    if self._gumbel else make_device_mcts)
            self._searchers[key] = (cfg, make(
                cfg, self.policy.feature_list, self.value.feature_list,
                self.policy.module.apply, self.value.module.apply,
                n_sim=key[1], max_nodes=self._max_nodes,
                c_puct=self._c_puct))
        return self._searchers[key]

    def _reused_tree(self, search, state, komi, bridged):
        """Walk the carried tree's root down the moves actually played
        since it was searched; None when a rebuild is needed (no
        carry, komi/board changed, undo, unexpanded edge, the shared
        slab is nearly full, or the walked-to position does not match
        the real one — e.g. free handicap stones placed outside the
        move history)."""
        import numpy as np

        from rocalphago_tpu.utils.coords import flatten_idx

        if self._carry is None:
            return None
        ck, csize, cturns, tree = self._carry
        if (ck != komi or csize != state.size
                or state.turns_played < cturns):
            return None
        n = csize * csize
        for mv in state.history[cturns:]:
            a = n if mv is None else flatten_idx(mv, csize)
            tree, ok = search.advance_root(
                tree, jnp.array([a], jnp.int32))
            if not bool(jax.device_get(ok)[0]):
                return None
        if int(jax.device_get(tree.n_nodes)[0]) \
                > 0.75 * self._max_nodes:
            return None                # slab nearly full: rebuild
        # identity check: the reused root must BE the position we
        # were asked to search (board + turn + ko) — anything the
        # history walk can't see (handicap placement, clear_board)
        # falls back to a fresh tree instead of searching a stale one
        r = int(jax.device_get(tree.root)[0])
        rs = jax.device_get(jax.tree.map(
            lambda x: x[0, r], tree.states))
        ok_pos = (np.array_equal(np.asarray(rs.board),
                                 np.asarray(jax.device_get(
                                     bridged.board)))
                  and int(rs.turn) == int(jax.device_get(bridged.turn))
                  and int(rs.ko) == int(jax.device_get(bridged.ko)))
        return tree if ok_pos else None

    def get_move(self, state):
        import numpy as np

        from rocalphago_tpu.engine import jaxgo as _jaxgo
        from rocalphago_tpu.utils.coords import unflatten_idx

        from rocalphago_tpu.runtime.deadline import Deadline

        komi = float(state.komi)
        eff = self._effective_sims()
        skey = (komi, eff if self._gumbel else self._n_sim)
        cfg, search = self._searcher_for(
            komi, eff if self._gumbel else None)
        root = _jaxgo.from_pygo(cfg, state)
        roots = jax.tree.map(lambda x: x[None], root)
        # the clock PLANNED eff sims; the deadline ENFORCES the wall
        # budget between chunks (anytime answer on expiry). The first
        # search per komi pays the compiles — no rate estimate exists
        # yet and no deadline would be meaningful through a compile —
        # so enforcement starts once the clock is warmed.
        deadline = Deadline.after(
            self._clock.move_time if self._clock.rate is not None
            else None)
        t0 = time.monotonic()
        if self._gumbel:
            self._rng, sub = jax.random.split(self._rng)
            if self._incr and self._enc_cache is None:
                self._enc_cache = search.make_caches(1)
            visits, _, best, _ = search.run_chunked(
                self.policy.params, self.value.params, roots, sub,
                self._chunk, deadline=deadline,
                caches=self._enc_cache if self._incr else None)
            if self._incr:
                self._enc_cache = search.last_caches
            action = int(jax.device_get(best)[0])
            counts = np.asarray(jax.device_get(visits))[0]
            # a halving plan really runs its schedule total, not eff
            planned = sum(k * v for k, v in search.schedule)
            ran = search.last_ran if search.last_ran is not None \
                else planned
        else:
            tree = (self._reused_tree(search, state, komi, root)
                    if self._reuse else None)
            if tree is not None:
                self.reuses += 1
            elif self._incr:
                # incremental root encode: one move past the last
                # encoded root in serving, so the cached ladder
                # verdicts mostly survive the one-stone board delta
                if self._enc_cache is None:
                    self._enc_cache = search.make_caches(1)
                tree, self._enc_cache = search.init_cached(
                    self.policy.params, self.value.params, roots,
                    self._enc_cache)
            else:
                tree = search.init(self.policy.params,
                                   self.value.params, roots)
            # hand the tree over to the donating chunk loop
            # (owned=True): a reused tree shares buffers with the
            # carry, so the carry is dropped FIRST — if a transient
            # fault aborts the search mid-loop (the resilient
            # ladder's retry path), the next get_move must rebuild
            # instead of walking a donated-away slab
            self._carry = None
            # the clock owns the sim count: eff ≤ n_sim simulations
            # in chunk-sized compiled programs (same programs the
            # full budget runs — shrinking never recompiles)
            tree, ran = search.run_sims_chunked(
                self.policy.params, self.value.params, tree,
                self._chunk, n=eff, deadline=deadline, owned=True)
            planned = eff
            visits, _ = search.root_stats(tree)
            counts = np.asarray(jax.device_get(visits))[0]
            action = int(counts.argmax())
            if self._reuse:
                self._carry = (komi, state.size, state.turns_played,
                               tree)
        if self._incr and self._enc_cache is not None:
            from rocalphago_tpu.features.api import observe_incremental

            # get_move is fully synced by the visits fetch above, so
            # the 6-int stats snapshot costs one tiny transfer
            self._enc_stats = observe_incremental(
                self._enc_stats, self._enc_cache.stats)
        self.last_deadline_hit = ran < planned
        self.deadline_hits += int(self.last_deadline_hit)
        dt = time.monotonic() - t0
        self._clock.note(skey, ran, dt)
        self._move_h.observe(dt)
        if dt > 0:
            self._rate_h.observe(ran / dt)
        self.last_n_sim = ran
        if action >= cfg.num_points or counts[action] == 0:
            return None                              # pass
        return unflatten_idx(action, cfg.size)


def make_mcts_selfplay(cfg: GoConfig, policy_features: tuple,
                       value_features: tuple, policy_apply: Callable,
                       value_apply: Callable, batch: int,
                       max_moves: int, n_sim: int,
                       max_nodes: int | None = None,
                       c_puct: float = 5.0, temperature: float = 1.0,
                       sim_chunk: int = 8,
                       record_visits: bool = False,
                       gumbel: bool = False, m_root: int = 16,
                       gumbel_sample: bool = False,
                       dirichlet_alpha: float = 0.0,
                       noise_frac: float = 0.25, mesh=None,
                       cap_p: float | None = None,
                       cap_cheap: int | None = None,
                       cap_per_row: bool = False,
                       forced_k: float = 0.0):
    """Search-driven self-play: every move of every game comes from a
    fresh on-device search over the batch — PUCT
    (:func:`make_device_mcts`, move sampled from root visit counts by
    ``temperature``) or, with ``gumbel=True``,
    :func:`make_gumbel_mcts` (each ply plays the halving winner;
    ``temperature`` does not apply — see the return-contract note).

    This is the AlphaZero-shaped generation loop the reference never
    had (its RL self-play samples the raw policy; SURVEY.md §3.2) —
    here each ply runs ``n_sim`` lockstep simulations for ALL games in
    one set of compiled programs and then samples the move from root
    visit counts (``∝ visits^(1/temperature)``; argmax at
    ``temperature=0``; forced pass when only pass was visited). Games
    that end are frozen by the engine; the host loop carries only the
    batched :class:`GoState` and per-ply actions.

    ``sim_chunk`` bounds the simulations per compiled program (the
    ~40s TPU worker watchdog). Trees are rebuilt per ply (no subtree
    reuse — the standard trade of slab-array search; priors/values are
    recomputed where a host tree would reuse ~1/A of the subtree).

    Returns ``run(params_p, params_v, rng) -> (final GoState,
    actions i32 [T, B], live bool [T, B])`` — with
    ``record_visits=True``, ``(..., targets [T, B, A])``: the
    search-policy targets an AlphaZero-style trainer
    (``training.zero``) learns from — raw root visit counts (i32)
    under PUCT, the improved policy π' (f32, the Gumbel MuZero
    target) under ``gumbel=True``. Gumbel self-play plays each ply's
    halving winner directly: the per-ply fresh Gumbel draw is the
    exploration, so no visit-count temperature sampling applies.

    ``dirichlet_alpha > 0`` (PUCT mode only) mixes AlphaZero root
    exploration noise into each ply's root priors before the
    simulations: ``p ← (1−ε)·p + ε·Dir(α)`` over the prior-supported
    actions, with ``ε = noise_frac`` (paper values: α=0.03, ε=0.25
    for 19×19). Self-play generation only — serving
    (:class:`DeviceMCTSPlayer`) never adds noise. Gumbel mode
    rejects the knob: the gumbel draw is already the root
    exploration mechanism.

    **Self-play economics** (KataGo, "Accelerating Self-Play
    Learning in Go"; all default OFF, each an independent flag):

    - ``cap_p`` — playout-cap randomization. Each ply draws its sim
      budget from the game rng chain: the full ``n_sim`` with
      probability ``cap_p``, else the cheap ``cap_cheap``
      (default ``n_sim // 4``). The draw is SHARED across the batch
      by default — the games run lockstep, so one full-searched row
      would make the whole batch pay full price; a correlated draw
      converts the cheap plies into real wall-clock
      (``E[sims/ply] = p·full + (1−p)·cheap``). ``cap_per_row=True``
      draws iid per game instead and leans on the per-row budget
      masking in the chunk programs (rows at their cap retire sim
      steps as no-ops) — same E[sims] but chunk count follows the
      batch MAX, so it only pays off once per-row early-exit
      matters more than lockstep (e.g. under cross-game batching).
      With ``record_visits=True`` the run appends a
      ``full bool [T, B]`` mask — only full-searched plies should
      emit policy targets (the trainer masks with it); cheap plies
      still train the value/aux heads.
    - ``forced_k`` — forced playouts + policy-target pruning at the
      root (PUCT only): selection floors each root child at
      ``sqrt(forced_k · prior · n_total)`` visits, and the recorded
      target has the forced visits pruned back out
      (:func:`search.pruned_targets`) so exploration doesn't leak
      into the policy target. Targets become f32 (normalized).

    Env defaults: ``ROCALPHAGO_CAP_P`` / ``ROCALPHAGO_CAP_CHEAP``
    seed ``cap_p`` / ``cap_cheap`` when the caller passes ``None``.
    """
    if gumbel and dirichlet_alpha > 0:
        raise ValueError(
            "dirichlet_alpha is a PUCT-mode knob; gumbel self-play's "
            "root exploration is the gumbel draw itself")
    if cap_p is None:
        cap_p = float(os.environ.get("ROCALPHAGO_CAP_P", "") or 0.0)
    if not 0.0 <= cap_p <= 1.0:
        raise ValueError(f"cap_p must be in [0, 1], got {cap_p}")
    if cap_cheap is None:
        cap_cheap = int(os.environ.get("ROCALPHAGO_CAP_CHEAP", "")
                        or max(1, n_sim // 4))
    cheap = max(1, min(int(cap_cheap), n_sim))
    econ = cap_p > 0 and cheap < n_sim
    if gumbel and forced_k:
        raise ValueError(
            "forced_k is a PUCT-root knob; gumbel search visits "
            "candidates by schedule, not PUCT selection")
    if gumbel:
        search = make_gumbel_mcts(cfg, policy_features,
                                  value_features, policy_apply,
                                  value_apply, n_sim, max_nodes,
                                  m_root=m_root, c_puct=c_puct)
    else:
        search = make_device_mcts(cfg, policy_features,
                                  value_features, policy_apply,
                                  value_apply, n_sim, max_nodes,
                                  c_puct, forced_k=forced_k)
    n = cfg.num_points
    vstep = jax.vmap(functools.partial(step, cfg))

    def sample_weighted(weights, sub):
        """Draw an action per game from non-negative weights
        ``∝ w^(1/temperature)``; exact argmax at temperature 0.
        Shared by the visit-count and π' move rules so the two
        cannot drift."""
        if temperature > 0:
            logits = jnp.where(
                weights > 0, jnp.log(jnp.maximum(weights, 1e-9))
                / temperature, -jnp.inf)
            action = jax.random.categorical(sub, logits, axis=-1)
        else:
            action = jnp.argmax(weights, axis=-1)
        return action.astype(jnp.int32)

    @jax.jit
    def pick_and_step(states: GoState, visits, rng):
        rng, sub = jax.random.split(rng)
        action = sample_weighted(visits.astype(jnp.float32), sub)
        live = ~states.done
        return vstep(states, action), rng, action, live

    @jax.jit
    def step_best(states: GoState, best):
        """Gumbel move rule: play the halving winner — the per-ply
        fresh Gumbel draw already IS the exploration (sampling from
        the policy via the Gumbel-max trick), so no visit-count
        temperature sampling on top."""
        live = ~states.done
        return vstep(states, best), best, live

    @jax.jit
    def add_root_noise(tree: DeviceTree, rng):
        """AlphaZero root exploration: mix Dir(α) into the root
        priors over the prior-supported actions."""
        p0 = tree.prior[:, 0, :]
        valid = p0 > 0
        gam = jnp.where(valid, jax.random.gamma(
            rng, dirichlet_alpha, p0.shape, jnp.float32), 0.0)
        dirichlet = gam / jnp.maximum(
            gam.sum(axis=-1, keepdims=True), 1e-12)
        mixed = jnp.where(
            valid, (1.0 - noise_frac) * p0 + noise_frac * dirichlet,
            0.0)
        return tree._replace(prior=tree.prior.at[:, 0, :].set(mixed))

    def puct_search_noisy(params_p, params_v, states, rng):
        """init → noise → the searcher's own chunk loop (the noisy
        tree is ours alone — hand it over for donation)."""
        tree = search.init(params_p, params_v, states)
        tree = add_root_noise(tree, rng)
        return search.run_chunked(params_p, params_v, states,
                                  sim_chunk, tree=tree, owned=True)

    @jax.jit
    def draw_budget(sub):
        """One Bernoulli(cap_p) per ply: shared across the batch by
        default (lockstep games — see the docstring), iid per row
        with ``cap_per_row``."""
        if cap_per_row:
            full = jax.random.bernoulli(sub, cap_p, (batch,))
        else:
            full = jnp.broadcast_to(
                jax.random.bernoulli(sub, cap_p), (batch,))
        return full, jnp.where(full, n_sim, cheap).astype(jnp.int32)

    def puct_search(params_p, params_v, states, noise_rng, n_ply,
                    budget):
        """The economics PUCT ply: same program sequence as
        :func:`run_chunked` (init → [noise] → donated chunk loop →
        root stats), but with the ply's sim count / per-row budget
        threaded through and the pruned policy target read off the
        final tree when ``forced_k`` is on."""
        tree = search.init(params_p, params_v, states)
        if dirichlet_alpha > 0:
            tree = add_root_noise(tree, noise_rng)
        tree, ran = search.run_sims_chunked(
            params_p, params_v, tree, sim_chunk, n=n_ply,
            budget=budget, owned=True)
        visits, _ = search.root_stats(tree)
        if forced_k:
            target, pruned = search.pruned_targets(tree)
        else:
            target, pruned = visits, None
        return visits, target, pruned, ran

    # per-ply wall time of search self-play (the done-fetch below
    # syncs each ply, so the numbers are real)
    _ply_h = obs_registry.histogram("selfplay_ply_seconds")
    _sims_h = obs_registry.histogram("selfplay_sims_per_move",
                                     edges=obs_registry.COUNT_EDGES)
    _full_g = obs_registry.gauge("selfplay_fullsearch_frac")
    _pruned_c = obs_registry.counter("policy_targets_pruned_total")

    def run(params_p, params_v, rng):
        states = new_states(cfg, batch)
        if mesh is not None:
            # the search shards by placement alone (module docstring):
            # sharding the game batch here shards every per-ply search
            # and the engine steps; params stay replicated
            from rocalphago_tpu.parallel import mesh as meshlib

            states = meshlib.shard_batch(mesh, states)
        actions, lives, visit_seq, full_seq = [], [], [], []
        pruned_acc, full_frac, n_plies = [], 0.0, 0
        for _ in range(max_moves):
            t_ply = time.monotonic()
            if econ:
                # the budget draw is a separate split so the OFF
                # path's rng chain (and everything downstream of it)
                # stays bit-identical
                rng, sub_b = jax.random.split(rng)
                full, budget = draw_budget(sub_b)
                fh = np.asarray(jax.device_get(full))
                n_ply = int(n_sim if fh.any() else cheap)
                budget_arg = budget if cap_per_row else None
                full_frac += float(fh.mean())
                n_plies += 1
            if gumbel:
                rng, sub = jax.random.split(rng)
                if econ:
                    visits, _, best, pi = search.run_chunked(
                        params_p, params_v, states, sub, sim_chunk,
                        n=n_ply, budget=budget_arg)
                    _sims_h.observe(search.last_ran
                                    if search.last_ran is not None
                                    else n_ply)
                else:
                    visits, _, best, pi = search.run_chunked(
                        params_p, params_v, states, sub, sim_chunk)
                if gumbel_sample:
                    # ``gumbel_sample`` move rule (VERDICT r4 #9
                    # experiment): sample the move from the improved
                    # policy π' instead of playing the halving
                    # winner — keeps the π' TRAINING target while
                    # restoring PUCT-style stochastic play (the
                    # round-4 rerun measured play-the-winner
                    # narrowing the game distribution off the value
                    # manifold, results/zero_scale_r4/target_compare)
                    states, rng, action, live = pick_and_step(
                        states, pi, rng)
                else:
                    states, action, live = step_best(states, best)
                target = pi
            elif econ or forced_k:
                sub = None
                if dirichlet_alpha > 0:
                    rng, sub = jax.random.split(rng)
                visits, target, pruned, ran = puct_search(
                    params_p, params_v, states, sub,
                    n_ply if econ else None,
                    budget_arg if econ else None)
                if econ:
                    _sims_h.observe(ran)
                if pruned is not None:
                    pruned_acc.append(pruned.sum())
                # the move is always sampled from the RAW visit
                # counts — pruning reshapes only the recorded target
                states, rng, action, live = pick_and_step(
                    states, visits, rng)
            elif dirichlet_alpha > 0:
                rng, sub = jax.random.split(rng)
                visits, _ = puct_search_noisy(params_p, params_v,
                                              states, sub)
                states, rng, action, live = pick_and_step(
                    states, visits, rng)
                target = visits
            else:
                visits, _ = search.run_chunked(params_p, params_v,
                                               states, sim_chunk)
                states, rng, action, live = pick_and_step(
                    states, visits, rng)
                target = visits
            actions.append(action)
            lives.append(live)
            if record_visits:
                visit_seq.append(target)
                if econ:
                    full_seq.append(full)
            done = bool(jax.device_get(states.done.all()))
            _ply_h.observe(time.monotonic() - t_ply)
            if done:
                break
        if econ and n_plies:
            _full_g.set(full_frac / n_plies)
        if pruned_acc:
            _pruned_c.inc(int(jax.device_get(sum(pruned_acc))))
        n_act = cfg.num_points + 1
        out = (states,
               jnp.stack(actions) if actions
               else jnp.zeros((0, batch), jnp.int32),
               jnp.stack(lives) if lives
               else jnp.zeros((0, batch), bool))
        if record_visits:
            tdtype = (jnp.float32 if (gumbel or forced_k)
                      else jnp.int32)
            out += (jnp.stack(visit_seq) if visit_seq
                    else jnp.zeros((0, batch, n_act), tdtype),)
            if econ:
                out += (jnp.stack(full_seq) if full_seq
                        else jnp.zeros((0, batch), bool),)
        return out

    return run
