"""Host-facing agents over the device nets.

Parity: ``AlphaGo/ai.py`` (``GreedyPolicyPlayer``,
``ProbabilisticPolicyPlayer`` with its lockstep-batch ``get_moves``,
``ValuePlayer``; SURVEY.md §2 "Agents"). These wrap host
``pygo.GameState`` objects for GTP / tournaments / tests; bulk
self-play does NOT go through them — that's the fully on-device loop
in :mod:`rocalphago_tpu.search.selfplay`.

``MCTSPlayer`` lives in :mod:`rocalphago_tpu.search.mcts`.
"""

from __future__ import annotations

import numpy as np

from rocalphago_tpu.models.policy import CNNPolicy
from rocalphago_tpu.models.value import CNNValue


def _sensible_moves(state, move_limit=None):
    if move_limit is not None and state.turns_played >= move_limit:
        return []
    moves = state.get_legal_moves(include_eyes=False)
    return moves if moves else []


class GreedyPolicyPlayer:
    """Plays the policy's argmax move over sensible legal moves."""

    def __init__(self, policy: CNNPolicy, pass_when_offered: bool = False,
                 move_limit: int | None = None, symmetric: bool = False):
        self.policy = policy
        self.pass_when_offered = pass_when_offered
        self.move_limit = move_limit
        self.symmetric = symmetric

    def get_move(self, state):
        return self.get_moves([state])[0]

    def get_moves(self, states):
        out = [None] * len(states)
        idx, live, moves_lists = [], [], []
        for i, st in enumerate(states):
            if self.pass_when_offered and st.history and \
                    st.history[-1] is None and st.turns_played > 100:
                continue
            sensible = _sensible_moves(st, self.move_limit)
            if sensible:
                idx.append(i)
                live.append(st)
                moves_lists.append(sensible)
        if not live:
            return out
        dists = self.policy.batch_eval_state(live, moves_lists,
                                             symmetric=self.symmetric)
        for i, dist in zip(idx, dists):
            if dist:
                out[i] = max(dist, key=lambda mp: mp[1])[0]
        return out


class ProbabilisticPolicyPlayer:
    """Samples moves ∝ p^(1/temperature) over sensible legal moves —
    the reference's lockstep-batch self-play agent."""

    def __init__(self, policy: CNNPolicy, temperature: float = 1.0,
                 seed: int | None = None, move_limit: int | None = 500,
                 greedy_start: int | None = None,
                 symmetric: bool = False):
        self.policy = policy
        self.temperature = float(temperature)
        self.move_limit = move_limit
        self.greedy_start = greedy_start
        self.symmetric = symmetric
        self.rng = np.random.default_rng(seed)

    def get_move(self, state):
        return self.get_moves([state])[0]

    def get_moves(self, states):
        out = [None] * len(states)
        idx, live, moves_lists = [], [], []
        for i, st in enumerate(states):
            sensible = _sensible_moves(st, self.move_limit)
            if sensible:
                idx.append(i)
                live.append(st)
                moves_lists.append(sensible)
        if not live:
            return out
        dists = self.policy.batch_eval_state(live, moves_lists,
                                             symmetric=self.symmetric)
        for k, (i, dist) in enumerate(zip(idx, dists)):
            if not dist:
                continue
            moves = [m for m, _ in dist]
            probs = np.asarray([p for _, p in dist], np.float64)
            greedy = (self.greedy_start is not None
                      and live[k].turns_played >= self.greedy_start)
            if self.temperature != 1.0 and not greedy:
                probs = probs ** (1.0 / self.temperature)
            probs = probs / probs.sum()
            if greedy:
                out[i] = moves[int(np.argmax(probs))]
            else:
                out[i] = moves[self.rng.choice(len(moves), p=probs)]
        return out


def build_player(kind: str, policy_path: str, value_path: str | None = None,
                 rollout_path: str | None = None, temperature: float = 0.67,
                 playouts: int = 100, leaf_batch: int = 8,
                 lmbda: float = 0.5, symmetric: bool = False,
                 device_rollout: bool = False, board: int | None = None):
    """One agent factory for every CLI (GTP, tournament): build a
    ``greedy`` / ``probabilistic`` / ``mcts`` player from saved model
    specs. With ``board``, nets saved at another size are re-boarded
    through :meth:`~rocalphago_tpu.models.nn_util.NeuralNetBase.
    at_board` — FCN checkpoints play any size (the cross-size transfer
    ladder rides this); size-locked legacy heads raise ValueError."""
    from rocalphago_tpu.models.nn_util import NeuralNetBase

    def load(path):
        net = NeuralNetBase.load_model(path)
        if board is not None and net.board != board:
            net = net.at_board(board)
        return net

    policy = load(policy_path)
    if kind == "greedy":
        return GreedyPolicyPlayer(policy, symmetric=symmetric)
    if kind == "probabilistic":
        return ProbabilisticPolicyPlayer(policy, temperature=temperature,
                                         symmetric=symmetric)
    if kind == "mcts":
        from rocalphago_tpu.search.mcts import MCTSPlayer

        if not value_path:
            raise ValueError("mcts player needs a value model")
        value = load(value_path)
        rollout = load(rollout_path) if rollout_path else None
        return MCTSPlayer(value, policy, rollout=rollout, lmbda=lmbda,
                          n_playout=playouts, leaf_batch=leaf_batch,
                          symmetric=symmetric,
                          device_rollout=device_rollout)
    if kind in ("device-mcts", "gumbel-mcts"):
        from rocalphago_tpu.search.device_mcts import DeviceMCTSPlayer

        if not value_path:
            raise ValueError(f"{kind} player needs a value model")
        value = load(value_path)
        return DeviceMCTSPlayer(value, policy, n_sim=playouts,
                                gumbel=(kind == "gumbel-mcts"))
    raise ValueError(f"unknown player kind {kind!r}")


def player_board(player) -> int | None:
    """Fixed board size the player's nets were compiled for, or None
    for size-agnostic players (shared by the GTP boardsize guard and
    the tournament CLI's --board validation). Sees through wrappers
    that expose the wrapped agent as ``primary`` (ResilientPlayer)."""
    board = getattr(player, "board", None)
    if board is None:
        board = getattr(getattr(player, "policy", None), "board", None)
    if board is None and getattr(player, "primary", None) is not None:
        board = player_board(player.primary)
    return board


def reset_player(player, reason: str = "new_game") -> None:
    """Clear any per-game search state (new game starting).

    ``reason`` labels the reset for players that count their
    cache invalidations (``DeviceMCTSPlayer.reset`` →
    ``encode_cache_resets_total{reason=...}``); players with a
    plain ``reset()`` just ignore it."""
    import inspect

    def _reset(fn):
        try:
            sig = inspect.signature(fn)
            if "reason" in sig.parameters:
                return fn(reason=reason)
        except (TypeError, ValueError):
            pass
        return fn()

    mcts = getattr(player, "mcts", None)
    if mcts is not None and hasattr(mcts, "reset"):
        _reset(mcts.reset)
    if hasattr(player, "reset") and callable(player.reset):
        _reset(player.reset)    # e.g. DeviceMCTSPlayer's carried tree
    if hasattr(player, "_tree_history"):
        player._tree_history = None


class ValuePlayer:
    """One-ply lookahead on the value net: for each sensible move,
    evaluate the successor and pick the worst position for the
    opponent (SURVEY.md §2 agents [C-MED])."""

    def __init__(self, value: CNNValue, policy: CNNPolicy | None = None,
                 top_k: int | None = None, move_limit: int | None = None):
        self.value = value
        self.policy = policy      # optional pre-filter to top_k moves
        self.top_k = top_k
        self.move_limit = move_limit

    def get_move(self, state):
        moves = _sensible_moves(state, self.move_limit)
        if not moves:
            return None
        if self.policy is not None and self.top_k:
            dist = self.policy.eval_state(state, moves=moves)
            dist.sort(key=lambda mp: -mp[1])
            moves = [m for m, _ in dist[:self.top_k]]
        succs = []
        for mv in moves:
            nxt = state.copy()
            nxt.do_move(mv)
            succs.append(nxt)
        # value is from the player-to-move's (opponent's) perspective
        vals = self.value.batch_eval_state(succs)
        return moves[int(np.argmin(vals))]

    def get_moves(self, states):
        return [self.get_move(s) for s in states]
