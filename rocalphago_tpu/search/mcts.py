"""APV-MCTS: PUCT tree search with batched device leaf evaluation.

Parity: ``AlphaGo/mcts.py`` (``TreeNode`` with ``_P/_Q/_u/_n_visits``,
``select`` = argmax(Q+u), ``expand``, ``update_recursive``; ``MCTS``
with ``value_fn/policy_fn/rollout_policy_fn``, ``lmbda``, ``c_puct``,
``rollout_limit``, ``playout_depth``, ``n_playout``, ``get_move``,
``update_with_move`` subtree reuse; the empty ``ParallelMCTS`` stub;
SURVEY.md §2 "MCTS", §3.3). Every NN touchpoint is an injected callable
— the reference's test seam — so tree mechanics are testable with plain
lambdas.

TPU-native design (SURVEY.md §7 step 6): the tree lives on host (tiny,
pointer-chasing, branchy — a bad fit for XLA), but *leaf evaluation is
batched*: ``ParallelMCTS`` runs ``leaf_batch`` playouts per wave under
virtual loss, collects the distinct leaves, and evaluates policy priors
and values for all of them in ONE jitted forward per net — replacing
the reference's batch-size-1 evals per playout (its known bottleneck)
and filling in its unimplemented ``ParallelMCTS``. Rollouts for the
λ-mix run lockstep across the wave through the injected batch rollout
callable (host rules, batched NN forward — or fully on device via
:func:`device_rollout_fn`).
"""

from __future__ import annotations

import numpy as np

from rocalphago_tpu.engine import pygo
from rocalphago_tpu.search.clock import MoveClock

PASS_MOVE = pygo.PASS_MOVE


class TreeNode:
    """A node in the MCTS tree, holding the edge statistics of the move
    that led to it: prior ``_P``, mean value ``_Q`` (from the moving
    player's perspective), visit count ``_n_visits``, and the PUCT
    exploration bonus ``_u``."""

    __slots__ = ("_parent", "_children", "_n_visits", "_Q", "_u", "_P",
                 "_vloss")

    def __init__(self, parent: "TreeNode | None", prior_p: float):
        self._parent = parent
        self._children: dict = {}     # move -> TreeNode
        self._n_visits = 0
        self._Q = 0.0
        self._u = prior_p
        self._P = prior_p
        self._vloss = 0               # outstanding virtual losses

    def expand(self, action_priors) -> None:
        """Create children for ``[(move, prior), ...]``."""
        for action, prob in action_priors:
            if action not in self._children:
                self._children[action] = TreeNode(self, prob)

    def select(self, c_puct: float) -> tuple:
        """(move, child) maximizing Q + u."""
        return max(self._children.items(),
                   key=lambda ac: ac[1].get_value(c_puct))

    def get_value(self, c_puct: float) -> float:
        n_parent = self._parent._n_visits if self._parent else 1
        self._u = (c_puct * self._P * np.sqrt(max(n_parent, 1))
                   / (1 + self._n_visits))
        return self._Q + self._u

    def update(self, leaf_value: float) -> None:
        """Fold one evaluation (from this node's edge perspective) into
        the running mean."""
        self._n_visits += 1
        self._Q += (leaf_value - self._Q) / self._n_visits

    def update_recursive(self, leaf_value: float) -> None:
        """Update ancestors bottom-up, flipping the sign per level
        (alternating players)."""
        if self._parent:
            self._parent.update_recursive(-leaf_value)
        self.update(leaf_value)

    # ------------------------------------------------------ virtual loss

    def add_virtual_loss(self, loss: float = 1.0) -> None:
        """Pessimistic in-flight marker that steers later selections in
        the same wave away from this path (AlphaGo's n_vl trick)."""
        self._vloss += 1
        self._n_visits += 1
        self._Q += (-loss - self._Q) / self._n_visits

    def revert_virtual_loss(self, loss: float = 1.0) -> None:
        if self._vloss <= 0:
            return
        self._vloss -= 1
        self._Q = (self._Q * self._n_visits + loss) / max(
            self._n_visits - 1, 1)
        self._n_visits -= 1

    def is_leaf(self) -> bool:
        return not self._children

    def is_root(self) -> bool:
        return self._parent is None


class MCTS:
    """Asynchronous-policy-and-value MCTS (sequential reference form).

    ``policy_fn(state) -> [(move, prob), ...]`` over sensible moves;
    ``value_fn(state) -> float`` in [-1, 1] from the player to move's
    perspective; ``rollout_policy_fn(state) -> [(move, prob), ...]``
    used for playouts. Leaf value = (1−λ)·value + λ·rollout_outcome.
    """

    def __init__(self, value_fn, policy_fn, rollout_policy_fn,
                 lmbda: float = 0.5, c_puct: float = 5.0,
                 rollout_limit: int = 500, playout_depth: int = 20,
                 n_playout: int = 10000, rng=None):
        self._root = TreeNode(None, 1.0)
        self._value = value_fn
        self._policy = policy_fn
        self._rollout = rollout_policy_fn
        self._lmbda = lmbda
        self._c_puct = c_puct
        self._rollout_limit = rollout_limit
        self._L = playout_depth
        self._n_playout = n_playout
        self._rng = rng or np.random.default_rng(0)

    # ---------------------------------------------------------- playouts

    def _descend(self, state, path: list | None = None):
        """Walk from the root to a leaf (≤ playout_depth plies),
        mutating ``state`` along the way. Returns the leaf node;
        ``path`` (if given) collects every node stepped through."""
        node = self._root
        for _ in range(self._L):
            if node.is_leaf():
                break
            move, node = node.select(self._c_puct)
            state.do_move(move)
            if path is not None:
                path.append(node)
        return node

    def _playout(self, state) -> None:
        node = self._descend(state)
        # an internal node hit at the depth cap is already expanded —
        # don't spend a policy forward on it
        if not state.is_end_of_game and node.is_leaf():
            priors = self._policy(state)
            if priors:
                node.expand(priors)
        node.update_recursive(self._leaf_value(state))

    def _leaf_value(self, state) -> float:
        """λ-mixed evaluation from the leaf's player-to-move
        perspective, returned from the *edge* (previous mover's)
        perspective — i.e. negated — ready for ``update_recursive``."""
        if state.is_end_of_game:
            w = state.get_winner()
            v = 0.0 if w == 0 else (1.0 if w == state.current_player
                                    else -1.0)
        else:
            v = 0.0
            if self._lmbda < 1.0:
                v += (1.0 - self._lmbda) * float(self._value(state))
            if self._lmbda > 0.0:
                v += self._lmbda * self._evaluate_rollout(
                    state.copy(), self._rollout_limit)
        return -v

    def _evaluate_rollout(self, state, limit: int) -> float:
        """Play to the end (≤ limit plies) with the rollout policy;
        outcome from the perspective of the player to move at entry."""
        player = state.current_player
        for _ in range(limit):
            if state.is_end_of_game:
                break
            dist = self._rollout(state)
            if not dist:
                state.do_move(PASS_MOVE)
                continue
            probs = np.asarray([p for _, p in dist], np.float64)
            probs /= probs.sum()
            move = dist[self._rng.choice(len(dist), p=probs)][0]
            state.do_move(move)
        w = state.get_winner()
        return 0.0 if w == 0 else (1.0 if w == player else -1.0)

    # ------------------------------------------------------------ driving

    def get_move(self, state, n_playout: int | None = None):
        """Run playouts from ``state`` and return the most-visited
        move (``None`` = pass when the tree has no children).
        ``n_playout`` overrides the configured budget (a game clock
        may ask for fewer)."""
        for _ in range(n_playout if n_playout is not None
                       else self._n_playout):
            self._playout(state.copy())
        if self._root.is_leaf():
            return PASS_MOVE
        return max(self._root._children.items(),
                   key=lambda ac: ac[1]._n_visits)[0]

    def update_with_move(self, last_move) -> None:
        """Re-root at the played move, keeping the subtree (reference
        subtree reuse); unknown move → fresh tree."""
        child = self._root._children.get(last_move)
        if child is not None:
            child._parent = None
            self._root = child
        else:
            self.reset()

    def reset(self) -> None:
        """Discard the tree (e.g. the game position jumped)."""
        self._root = TreeNode(None, 1.0)


class ParallelMCTS(MCTS):
    """Batched-leaf APV-MCTS — the reference's empty stub, implemented.

    Per wave: select ``leaf_batch`` leaves under virtual loss, then one
    batched call each to ``batch_policy_fn(states) -> [priors, ...]``,
    ``batch_value_fn(states) -> [v, ...]`` and (if λ>0)
    ``batch_rollout_fn(states) -> [outcome, ...]`` — so NN cost per
    playout drops by ~leaf_batch× versus the sequential form. All
    callables remain injected (lambda-testable, SURVEY.md §4).
    """

    def __init__(self, batch_value_fn, batch_policy_fn, batch_rollout_fn,
                 lmbda: float = 0.5, c_puct: float = 5.0,
                 rollout_limit: int = 500, playout_depth: int = 20,
                 n_playout: int = 10000, leaf_batch: int = 8, rng=None,
                 batch_policy_value_fn=None):
        super().__init__(batch_value_fn, batch_policy_fn, batch_rollout_fn,
                         lmbda=lmbda, c_puct=c_puct,
                         rollout_limit=rollout_limit,
                         playout_depth=playout_depth, n_playout=n_playout,
                         rng=rng)
        self._leaf_batch = leaf_batch
        # optional fused evaluator: (states, want_priors flags) →
        # (priors list, values) off ONE shared encode per wave
        self._pv = batch_policy_value_fn

    def get_move(self, state, n_playout: int | None = None):
        n = self._n_playout if n_playout is None else n_playout
        waves, rem = divmod(n, self._leaf_batch)
        for _ in range(waves):
            self._wave(state, self._leaf_batch)
        if rem:
            self._wave(state, rem)
        if self._root.is_leaf():
            return PASS_MOVE
        return max(self._root._children.items(),
                   key=lambda ac: ac[1]._n_visits)[0]

    def _wave(self, state, width: int) -> None:
        # descend under virtual loss applied to EVERY node on the path
        # (standard APV-MCTS: upper levels must look worse too, or
        # later descents in the wave re-trace the same line and leaf
        # diversity collapses); duplicate arrivals at the same node
        # (forced when the tree is tiny) share one evaluation
        paths = []                   # per playout: nodes under vloss
        leaves = []                  # per playout: its leaf node
        uniq_idx: dict = {}          # id(node) -> index below
        nodes, leaf_states = [], []
        for _ in range(width):
            st = state.copy()
            path: list = []
            node = self._descend(st, path)
            vpath = path or [node]
            for nd in vpath:
                nd.add_virtual_loss()
            paths.append(vpath)
            leaves.append(node)
            if id(node) not in uniq_idx:
                uniq_idx[id(node)] = len(nodes)
                nodes.append(node)
                leaf_states.append(st)

        live = [i for i, st in enumerate(leaf_states)
                if not st.is_end_of_game]
        need_priors = [i for i in live if nodes[i].is_leaf()]
        priors = [None] * len(nodes)
        values = np.zeros(len(nodes))
        if live:
            live_states = [leaf_states[i] for i in live]
            if self._pv is not None and self._lmbda < 1.0:
                # fused path: one shared encode for priors AND values
                need = set(need_priors)
                dists, vals = self._pv(live_states,
                                       [i in need for i in live])
                for k, i in enumerate(live):
                    if dists[k] is not None:
                        priors[i] = dists[k]
                values[live] += (1.0 - self._lmbda) * np.asarray(
                    vals, np.float64)
            else:
                if need_priors:
                    dists = self._policy(
                        [leaf_states[i] for i in need_priors])
                    for i, pri in zip(need_priors, dists):
                        priors[i] = pri
                if self._lmbda < 1.0:
                    vals = np.asarray(self._value(live_states),
                                      np.float64)
                    values[live] += (1.0 - self._lmbda) * vals
            if self._lmbda > 0.0:
                outs = np.asarray(
                    self._rollout([s.copy() for s in live_states]),
                    np.float64)
                values[live] += self._lmbda * outs
        for i, st in enumerate(leaf_states):
            if st.is_end_of_game:
                w = st.get_winner()
                values[i] = 0.0 if w == 0 else (
                    1.0 if w == st.current_player else -1.0)

        for vpath in paths:
            for nd in vpath:
                nd.revert_virtual_loss()
        for node in leaves:
            i = uniq_idx[id(node)]
            if priors[i]:
                node.expand(priors[i])
            node.update_recursive(-values[i])


# --------------------------------------------------------------- wiring


def device_rollout_fn(rollout_net, rollout_limit: int = 500,
                      temperature: float = 1.0, min_batch: int = 8,
                      seed: int = 0):
    """``batch_rollout`` callable that plays the wave's leaves to
    terminal FULLY on device (the ``mcts.py`` module-docstring promise;
    SURVEY.md §3.3 rebuild note — no host ``do_move`` per ply).

    Bridges the host leaf states into one batched :class:`GoState`
    (history hashing skipped — the net cfg has superko off), runs the
    compiled :func:`selfplay.make_device_rollout` scan once, and maps
    the area-scored winners back to each entry player's perspective.
    Waves are padded up to ``min_batch`` so every call hits the same
    compiled shape (``step`` freezes padded/finished games).

    Scoring uses the *game's* komi, read from the wave's leaf states —
    not the net cfg's default — so rollout outcomes agree with the
    host path's ``get_winner()`` (one compiled program per distinct
    komi, cached; a game's komi never changes mid-search).
    """
    import dataclasses

    import jax
    import jax.numpy as jnp

    from rocalphago_tpu.engine import jaxgo
    from rocalphago_tpu.search.selfplay import make_device_rollout

    base_cfg = rollout_net.cfg
    runs: dict = {}       # komi -> (cfg, compiled rollout)
    key_box = [jax.random.key(seed)]

    def for_komi(komi: float):
        if komi not in runs:
            cfg = dataclasses.replace(base_cfg, komi=komi)
            runs[komi] = (cfg, make_device_rollout(
                cfg, rollout_net.feature_list, rollout_net.module.apply,
                rollout_limit=rollout_limit, temperature=temperature))
        return runs[komi]

    def batch_rollout(states):
        cfg, run = for_komi(float(states[0].komi))
        entry = [s.current_player for s in states]
        dev = [jaxgo.from_pygo(cfg, s, with_history=False,
                               with_labels=False)
               for s in states]
        pad = max(min_batch - len(dev), 0)
        # pad with DONE copies: the rollout while_loop exits when every
        # lane ends, so live padding would cost full wasted rollouts
        done_pad = dev[0]._replace(done=jnp.bool_(True))
        dev.extend([done_pad] * pad)
        batched = jaxgo.seed_labels(
            cfg, jax.tree.map(lambda *xs: jnp.stack(xs), *dev))
        key_box[0], sub = jax.random.split(key_box[0])
        winners = np.asarray(jax.device_get(
            run(rollout_net.params, batched, sub)))
        return [0.0 if w == 0 else (1.0 if w == p else -1.0)
                for w, p in zip(winners[:len(states)], entry)]

    return batch_rollout


def net_backends(policy, value, rollout=None, rollout_limit: int = 500,
                 rng=None, symmetric: bool = False,
                 device_rollout: bool = False, leaf_batch: int = 8):
    """Batch callables for :class:`ParallelMCTS` from the framework
    nets: one jitted forward per net per wave.

    ``rollout`` (a fast policy net — or the SL policy itself, as the
    reference does when no rollout net is trained) drives lockstep
    batched playouts-to-terminal: on host rules by default, or — with
    ``device_rollout=True`` — as one compiled on-device scan per wave
    via :func:`device_rollout_fn` (the TPU-class path). ``symmetric``
    ensembles priors/values over the 8 board symmetries (AlphaGo's
    evaluation-time averaging; 8× eval cost, rollouts excluded).
    """
    rng = rng or np.random.default_rng(0)

    def batch_policy(states):
        sensible = [s.get_legal_moves(include_eyes=False) for s in states]
        return policy.batch_eval_state(states, sensible,
                                       symmetric=symmetric)

    def batch_value(states):
        return value.batch_eval_state(states, symmetric=symmetric)

    # Fused wave evaluation: when the value features are exactly the
    # policy features + the color plane (the AlphaGo 48/49 layout),
    # the expensive 48-plane encode is paid ONCE per wave and shared —
    # the policy forward reads a prefix slice of the value planes.
    # (Symmetric mode keeps the separate paths: the two nets ensemble
    # differently.)
    batch_policy_value = None
    nested = (tuple(value.feature_list[:-1]) == tuple(policy.feature_list)
              and value.feature_list[-1] == "color")
    if nested and not symmetric:
        n_policy_planes = policy.preprocess.output_dim

        def batch_policy_value(states, want_priors):
            planes = value._states_to_planes(states)
            vals = value.values_from_planes(planes)
            priors = [None] * len(states)
            pidx = [i for i, w in enumerate(want_priors) if w]
            if pidx:
                sub = [states[i] for i in pidx]
                sensible = [s.get_legal_moves(include_eyes=False)
                            for s in sub]
                pplanes = planes[np.asarray(pidx)][..., :n_policy_planes]
                for i, d in zip(pidx, policy.dists_from_planes(
                        sub, pplanes, sensible)):
                    priors[i] = d
            return priors, vals

    rollout_net = rollout or policy

    if device_rollout:
        return (batch_value, batch_policy,
                device_rollout_fn(rollout_net,
                                  rollout_limit=rollout_limit,
                                  min_batch=leaf_batch,
                                  seed=int(rng.integers(2**31))),
                batch_policy_value)

    def batch_rollout(states):
        entry_players = [s.current_player for s in states]
        for _ in range(rollout_limit):
            if all(s.is_end_of_game for s in states):
                break
            # evaluate the whole fixed-size batch every ply (finished
            # games get an empty support and are skipped): one
            # compiled shape, not one per distinct live count
            sens = [[] if s.is_end_of_game
                    else s.get_legal_moves(include_eyes=False)
                    for s in states]
            dists = rollout_net.batch_eval_state(states, sens)
            for st, dist in zip(states, dists):
                if st.is_end_of_game:
                    continue
                if not dist:
                    st.do_move(PASS_MOVE)
                    continue
                probs = np.asarray([p for _, p in dist], np.float64)
                probs /= probs.sum()
                st.do_move(dist[rng.choice(len(dist), p=probs)][0])
        outs = []
        for st, player in zip(states, entry_players):
            w = st.get_winner()
            outs.append(0.0 if w == 0 else (1.0 if w == player else -1.0))
        return outs

    return batch_value, batch_policy, batch_rollout, batch_policy_value


class MCTSPlayer:
    """Full-strength agent: APV-MCTS over the policy/value/rollout nets
    (reference ``ai.MCTSPlayer``), batched-leaf by default.

    Subtree reuse is history-aware: the player records the move history
    its root corresponds to, re-roots along the opponent's intervening
    move when the incoming state extends it by exactly one ply, and
    otherwise resets the tree — so a stale tree can never desync from
    the position being searched.

    TIME CONTROL mirrors :class:`~rocalphago_tpu.search.device_mcts.
    DeviceMCTSPlayer`: ``set_move_time(seconds)`` (wired from GTP by
    the engine) caps the next search at ``seconds × measured
    playouts/sec`` (shared :class:`~rocalphago_tpu.search.clock.
    MoveClock`; samples keyed per komi so each komi's compile-
    bearing first search is excluded), floored at one leaf wave.
    ``last_n_playout`` reports what the last search really ran.
    """

    def __init__(self, value, policy, rollout=None, lmbda: float = 0.5,
                 c_puct: float = 5.0, rollout_limit: int = 500,
                 playout_depth: int = 20, n_playout: int = 100,
                 leaf_batch: int = 8, seed: int | None = None,
                 symmetric: bool = False, device_rollout: bool = False):
        self.board = policy.board   # GTP boardsize validation
        rng = np.random.default_rng(seed)
        bv, bp, br, bpv = net_backends(policy, value, rollout,
                                       rollout_limit=rollout_limit,
                                       rng=rng, symmetric=symmetric,
                                       device_rollout=device_rollout,
                                       leaf_batch=leaf_batch)
        self.mcts = ParallelMCTS(bv, bp, br, lmbda=lmbda, c_puct=c_puct,
                                 rollout_limit=rollout_limit,
                                 playout_depth=playout_depth,
                                 n_playout=n_playout,
                                 leaf_batch=leaf_batch, rng=rng,
                                 batch_policy_value_fn=bpv)
        self._tree_history: list | None = None
        # GTP time control (see class docstring): shared clock, rate
        # samples keyed per komi — net_backends compiles one program
        # per distinct komi, and that first run must not feed the EMA
        self._clock = MoveClock()
        self.last_n_playout = None

    def set_move_time(self, seconds) -> None:
        """Per-move wall budget in seconds (None = no clock). The GTP
        engine calls this before every genmove from the game clock."""
        self._clock.set_move_time(seconds)

    def _effective_playouts(self) -> int:
        allowed = self._clock.allowed_units()
        if allowed is None:
            return self.mcts._n_playout
        wave = self.mcts._leaf_batch
        return min(self.mcts._n_playout,
                   max(wave, allowed // wave * wave))

    def _sync_tree(self, history: list) -> None:
        if self._tree_history is None or history == self._tree_history:
            return
        n = len(self._tree_history)
        if len(history) == n + 1 and history[:n] == self._tree_history:
            self.mcts.update_with_move(history[-1])
        else:
            self.mcts.reset()

    def get_move(self, state):
        history = list(state.history)
        self._sync_tree(history)
        sensible = state.get_legal_moves(include_eyes=False)
        if state.is_end_of_game or not sensible:
            self._tree_history = None
            self.mcts.reset()
            return PASS_MOVE
        import time as _time

        eff = self._effective_playouts()
        t0 = _time.monotonic()
        move = self.mcts.get_move(state, n_playout=eff)
        self._clock.note(float(state.komi), eff,
                         _time.monotonic() - t0)
        self.last_n_playout = eff
        self.mcts.update_with_move(move)
        self._tree_history = history + [move]
        return move
