"""Agents & search (reference layer L5): policy players, on-device
batched self-play, and APV-MCTS (SURVEY.md §1 L5, §3.3) — plus the
fully on-device tree search (``device_mcts``), the TPU-first design
the reference's host tree cannot express.

Re-exports are lazy — see :mod:`rocalphago_tpu.utils.lazy`.
"""

from rocalphago_tpu.utils.lazy import make_lazy

_EXPORTS = {
    "DeviceMCTSPlayer": "rocalphago_tpu.search.device_mcts",
    "DeviceTree": "rocalphago_tpu.search.device_mcts",
    "make_device_mcts": "rocalphago_tpu.search.device_mcts",
    "make_gumbel_mcts": "rocalphago_tpu.search.device_mcts",
    "make_mcts_selfplay": "rocalphago_tpu.search.device_mcts",
    "MCTS": "rocalphago_tpu.search.mcts",
    "MCTSPlayer": "rocalphago_tpu.search.mcts",
    "ParallelMCTS": "rocalphago_tpu.search.mcts",
    "TreeNode": "rocalphago_tpu.search.mcts",
    "net_backends": "rocalphago_tpu.search.mcts",
    "GreedyPolicyPlayer": "rocalphago_tpu.search.players",
    "ProbabilisticPolicyPlayer": "rocalphago_tpu.search.players",
    "ValuePlayer": "rocalphago_tpu.search.players",
    "SelfplayResult": "rocalphago_tpu.search.selfplay",
    "make_selfplay": "rocalphago_tpu.search.selfplay",
    "make_selfplay_chunked": "rocalphago_tpu.search.selfplay",
    "play_games": "rocalphago_tpu.search.selfplay",
    "sensible_mask": "rocalphago_tpu.search.selfplay",
}

__getattr__, __dir__, __all__ = make_lazy(__name__, _EXPORTS)
