"""Agents & search (reference layer L5): policy players, on-device
batched self-play, and APV-MCTS (SURVEY.md §1 L5, §3.3)."""

from rocalphago_tpu.search.mcts import (  # noqa: F401
    MCTS,
    MCTSPlayer,
    ParallelMCTS,
    TreeNode,
    net_backends,
)
from rocalphago_tpu.search.players import (  # noqa: F401
    GreedyPolicyPlayer,
    ProbabilisticPolicyPlayer,
    ValuePlayer,
)
from rocalphago_tpu.search.selfplay import (  # noqa: F401
    SelfplayResult,
    make_selfplay,
    make_selfplay_chunked,
    play_games,
    sensible_mask,
)
