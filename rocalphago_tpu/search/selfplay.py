"""On-device batched self-play: the whole game loop under one jit.

This is the rebuild of the reference's only vectorized primitive —
``ProbabilisticPolicyPlayer.get_moves`` stepping ~20 games in lockstep
on host with per-state Python featurization (SURVEY.md §2b
"environment parallelism", §3.2 HOT loops). Here the *entire* loop —
encode planes, policy forward, temperature sampling, rules step —
is a ``lax.scan`` over moves with every operand batched over games, so
thousands of games run per chip with zero host round-trips. This is
the component the ≥200 games/min north-star metric rests on.

Color handling: games in the first half of the batch have net A as
Black, the second half net B, so each scan step runs exactly one
half-batch forward through each net (a `jnp.roll` by B/2 swaps the
halves on odd plies) — no wasted double evaluation.

Move policy matches the reference's self-play players: sample from
softmax(logits/T) restricted to *sensible* moves (legal, not filling
an own true eye — the engine's sensibleness analysis); pass only when
no sensible move exists. Games end by two passes or ``max_moves``
(reference ``move_limit`` ≈ 500); unfinished games are scored as they
stand (area scoring).
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from rocalphago_tpu.engine.jaxgo import (
    GoConfig,
    GoState,
    group_data,
    legal_mask,
    new_states,
    step,
    vgroup_data,
    winner,
)
from rocalphago_tpu.features.incremental import (
    batched_delta_encoder,
    init_caches,
)
from rocalphago_tpu.features.planes import (
    batched_encoder,
    needs_member,
    true_eyes,
)
from rocalphago_tpu.obs import registry as obs_registry
from rocalphago_tpu.runtime import faults
from rocalphago_tpu.runtime.pipeline import ChunkPipeline


def incremental_default() -> bool:
    """Whether the batched self-play ply loop carries the incremental
    encode cache (``features/incremental.py``) — env knob
    ``ROCALPHAGO_ENCODE_INCR``, read at TRACE time like the ladder
    knobs so benchmarks can A/B it per traced program.

    MEASURED DEFAULT off for the BATCHED loop: under ``vmap`` the
    delta path's gating conds lower to selects that execute both
    branches, so its win is confined to cached ladder verdicts
    shortening the batch-lockstep rung loop, against the footprint
    bookkeeping it adds every ply (``bench_encode.py --trajectory
    --traj-batch`` records the A/B; BENCH_RESULTS.md "Incremental
    encode"). The SEQUENTIAL single-state paths
    (``Preprocess.advance``, the ``DeviceMCTSPlayer`` root advance,
    ``bench_encode --trajectory``) default ON instead — there the
    host-branch gating really skips the opening/chase blocks and
    measures ~2× µs/pos on dense 19×19 random tails. Results are
    bit-identical either way (``tests/test_incremental.py``)."""
    from rocalphago_tpu.features import incremental as _incr

    return _incr.enabled(default=False)


def sensible_mask(cfg: GoConfig, state: GoState,
                  gd=None) -> jax.Array:
    """bool [N]: legal board moves that do not fill an own true eye
    (the reference's ``get_legal_moves(include_eyes=False)``).
    Pass a precomputed ``gd`` to share the flood fill."""
    if gd is None:
        gd = group_data(cfg, state.board, with_zxor=cfg.enforce_superko,
                        labels=state.labels)
    legal = legal_mask(cfg, state, gd)[:-1]
    return legal & ~true_eyes(cfg, state, state.turn)


class SelfplayResult(NamedTuple):
    final: GoState       # batched end states
    actions: jax.Array   # int32 [T, B] action per ply (N = pass)
    live: jax.Array      # bool  [T, B] game was live when ply t played
    winners: jax.Array   # int32 [B]    +1 black / -1 white / 0
    num_moves: jax.Array  # int32 [B]   plies actually played


def _half_swap(x: jax.Array, swap: jax.Array) -> jax.Array:
    """Swap batch halves when ``swap`` (scalar bool) — static shapes."""
    half = x.shape[0] // 2
    return lax.cond(swap, lambda a: jnp.roll(a, half, axis=0), lambda a: a,
                    x)


def _make_ply(cfg: GoConfig, features: tuple, apply_a: Callable,
              apply_b: Callable, batch: int, temperature: float,
              incremental: bool = False):
    """Shared scan body for :func:`play_games` and
    :func:`make_selfplay_chunked`: one ply of lockstep two-net
    self-play, parameterized over net params so the chunked runner can
    trace it in a standalone jit. Owns the even-batch invariant: the
    half-batch color split slices at ``batch // 2``.

    ``incremental``: encode each ply through the delta path
    (:func:`~rocalphago_tpu.features.incremental.batched_delta_encoder`)
    with a per-game :class:`EncodeCache` threaded through the scan
    carry — bit-identical planes, cached ladder verdicts across
    successive plies. The ply then takes and returns ``caches``
    (``None`` and pass-through when off, so both runners carry one
    pytree slot either way)."""
    if batch % 2:
        raise ValueError(
            f"batch must be even (half-and-half color split), got {batch}")
    n = cfg.num_points
    vgd = vgroup_data(cfg, with_member=needs_member(features),
                      with_zxor=cfg.enforce_superko)
    enc = (batched_delta_encoder(cfg, features) if incremental
           else batched_encoder(cfg, features))
    vsens = jax.vmap(functools.partial(sensible_mask, cfg))
    vstep = jax.vmap(functools.partial(step, cfg))

    def ply(params_a, params_b, states, caches, rng, t):
        rng, sub = jax.random.split(rng)
        # one loop-free analysis per ply, shared by the encoder, the
        # sensibleness mask and the rules step
        gd = vgd(states)
        if incremental:
            planes, caches = enc(states, caches, gd)
        else:
            planes = enc(states, gd)
        # which half faces net A this ply (see module docstring)
        swap = (t % 2) == 1
        rolled = _half_swap(planes, swap)
        half = batch // 2
        logits_a = apply_a(params_a, rolled[:half])
        logits_b = apply_b(params_b, rolled[half:])
        logits = _half_swap(
            jnp.concatenate([logits_a, logits_b], axis=0), swap)

        sens = vsens(states, gd)                          # bool [B, N]
        neg = jnp.finfo(logits.dtype).min
        masked = jnp.where(sens, logits / temperature, neg)
        board_action = jax.random.categorical(sub, masked, axis=-1)
        must_pass = ~sens.any(axis=-1)
        action = jnp.where(must_pass, n, board_action).astype(jnp.int32)

        live = ~states.done
        new = vstep(states, action, gd)
        return new, caches, rng, action, live

    return ply


def _scan_plies(ply, params_a, params_b, states, caches, rng, ts):
    """Scan ``ply`` over the ply indices ``ts``; returns
    ``(states, caches, rng, actions [T,B], live [T,B])``."""
    def body(carry, t):
        states, caches, rng = carry
        new, caches, rng, action, live = ply(
            params_a, params_b, states, caches, rng, t)
        return (new, caches, rng), (action, live)

    (states, caches, rng), (actions, live) = lax.scan(
        body, (states, caches, rng), ts)
    return states, caches, rng, actions, live


def _finish(cfg: GoConfig, final, actions, live,
            score_on_device: bool, batch: int) -> SelfplayResult:
    """Shared result assembly for both runners."""
    if score_on_device:
        winners = jax.vmap(functools.partial(winner, cfg))(final)
    else:
        # caller scores the final boards on host (:func:`host_winners`);
        # sentinel 2 (impossible winner value) so accidentally reading
        # .winners fails loudly instead of looking like all-draws
        winners = jnp.full((batch,), 2, jnp.int32)
    return SelfplayResult(final, actions, live, winners,
                          live.sum(axis=0, dtype=jnp.int32))


def play_games(cfg: GoConfig, features: tuple,
               apply_a: Callable, params_a,
               apply_b: Callable, params_b,
               rng: jax.Array, batch: int, max_moves: int = 500,
               temperature: float = 1.0,
               score_on_device: bool = True,
               incremental: bool | None = None) -> SelfplayResult:
    """Play ``batch`` lockstep games of net A vs net B.

    First half of the batch: A is Black; second half: B is Black
    (callers average both colors for unbiased win-rates, as the
    reference's RL trainer does). ``apply_*`` map (params, planes
    [B',s,s,F]) → logits [B', N]. Fully jit-compatible; wrap in
    ``jax.jit`` with static ``cfg/features/batch/max_moves``.

    ``incremental`` (default: the ``ROCALPHAGO_ENCODE_INCR`` knob,
    :func:`incremental_default`): thread the delta-encode cache
    through the ply scan — bit-identical results, ladder-chase
    verdicts reused across successive plies.
    """
    if incremental is None:
        incremental = incremental_default()
    states = new_states(cfg, batch)
    caches = init_caches(cfg, batch) if incremental else None
    ply = _make_ply(cfg, features, apply_a, apply_b, batch,
                    temperature, incremental=incremental)
    final, _, _, actions, live = _scan_plies(
        ply, params_a, params_b, states, caches, rng,
        jnp.arange(max_moves))
    return _finish(cfg, final, actions, live, score_on_device, batch)


def make_selfplay(cfg: GoConfig, features: tuple, apply_a: Callable,
                  apply_b: Callable, batch: int, max_moves: int = 500,
                  temperature: float = 1.0,
                  incremental: bool | None = None):
    """Jitted ``(params_a, params_b, rng) -> SelfplayResult`` closure."""

    @jax.jit
    def run(params_a, params_b, rng):
        return play_games(cfg, features, apply_a, params_a, apply_b,
                          params_b, rng, batch, max_moves, temperature,
                          incremental=incremental)

    return run


def make_selfplay_chunked(cfg: GoConfig, features: tuple,
                          apply_a: Callable, apply_b: Callable,
                          batch: int, max_moves: int = 500,
                          chunk: int = 100, temperature: float = 1.0,
                          score_on_device: bool = True,
                          mesh=None,
                          incremental: bool | None = None):
    """Chunked variant of :func:`make_selfplay` for backends that kill
    long-running programs.

    The attached single-chip TPU tunnel's worker crashes on device
    programs past roughly 40s of execution (measured: a 19×19
    batch-16 self-play scan survives 120 plies ≈ 31s and dies at 200);
    a monolithic ``max_moves``-ply scan therefore can't run there.
    This runner jits ONE ``chunk``-ply scan segment and drives it from
    a host loop, carrying the batched :class:`GoState` **device-
    resident** between calls — per-segment runtime stays under the
    watchdog, host↔device traffic is one tiny dispatch per segment,
    and a single compile serves any ``max_moves`` (the segment program
    takes the ply offset as a traced scalar, so odd/even color phases
    share the compile too).

    Returns ``(params_a, params_b, rng) -> SelfplayResult`` with
    bit-identical move selection to :func:`play_games` given the same
    rng (the per-ply ``random.split`` chain is preserved across the
    segment boundary by threading the rng through the carry).

    PIPELINED DISPATCH (``runtime.pipeline``): segments are driven
    through a :class:`ChunkPipeline` (``depth`` in-flight segments,
    default env/1; ``depth=0`` = fully synchronous pacing) and each
    segment program DONATES its input ``GoState`` slab, so the
    device-resident carry never exists twice. The ``stop_when_done``
    done-poll never syncs the fresh dispatch at ANY depth: every
    segment's done-scalar is computed on device at dispatch and the
    host reads it from a RETIRED segment (already materialized). At
    ``depth>=1`` the poll runs one segment behind, so up to ``depth``
    extra segments may be dispatched onto all-done states — a proven
    no-op (the engine freezes finished games; asserted in
    ``tests/test_pipeline.py``) whose recorded rows are replaced by
    the same zero padding the sync path writes. Results are therefore
    bit-identical to the sync path at any depth.

    Pass ``mesh`` (a ``parallel.mesh.make_mesh`` mesh) to shard the
    game batch over the mesh's ``data`` axis — environment parallelism
    ACROSS devices, the multi-chip extension of the reference's
    lockstep ``get_moves`` batching (SURVEY.md §2b): initial states
    are placed batch-split, params replicated, and XLA propagates the
    shardings through the whole scan segment (the odd-ply color-swap
    ``roll`` becomes an ICI collective permute). Results are
    bit-identical to the unsharded runner; ``batch`` must be a
    multiple of 2× the data-axis width (even per-device shares keep
    the color-split halves aligned to devices).
    """
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    import time as _time
    if incremental is None:
        incremental = incremental_default()
    meshlib = None
    if mesh is not None:
        from rocalphago_tpu.parallel import mesh as meshlib

        data_width = mesh.shape[meshlib.DATA_AXIS]
        if batch % (2 * data_width):
            raise ValueError(
                f"batch {batch} must be a multiple of 2x the data-axis "
                f"width ({data_width})")
    ply = _make_ply(cfg, features, apply_a, apply_b, batch,
                    temperature, incremental=incremental)

    def _segment_impl(params_a, params_b, states, caches, rng, offset,
                      length):
        return _scan_plies(ply, params_a, params_b, states, caches,
                           rng, offset + jnp.arange(length))

    # the chunk loop's program: the input GoState slab (and the
    # incremental-encode cache slab riding with it) is DONATED so
    # pipelined dispatch (runtime.pipeline) never holds two copies of
    # the device-resident carry. The loop below owns every states
    # value it passes (fresh/sharded/copied), so donation never eats
    # a caller's buffers; donates_buffers marks the program
    # unretryable (runtime.retries refuses to wrap it — retry the
    # whole runner instead, which re-derives everything).
    segment = functools.partial(
        jax.jit, static_argnames=("length",),
        donate_argnums=(2, 3))(_segment_impl)
    segment.donates_buffers = True

    # tiny per-segment done-reduction, dispatched WITH the segment so
    # the host can later read it without syncing anything fresh
    done_flag = jax.jit(lambda s: s.done.all())
    copy_states = jax.jit(lambda s: jax.tree.map(jnp.copy, s))

    finish = jax.jit(functools.partial(
        _finish, cfg, score_on_device=score_on_device, batch=batch))

    # per-segment host wall time (~real segment time when the
    # pipeline paces the loop — each push waits for the previous
    # segment — pure dispatch latency at depth>=1 only for the first
    # segments) + total plies dispatched
    _seg_h = obs_registry.histogram("selfplay_segment_seconds")
    _plies_c = obs_registry.counter("selfplay_plies_total")

    def run(params_a, params_b, rng,
            initial_states: GoState | None = None,
            deadline: float | None = None,
            stop_when_done: bool = False,
            depth: int | None = None,
            pipeline: ChunkPipeline | None = None) -> SelfplayResult:
        """``initial_states`` (batched, defaults to fresh games) lets
        callers continue play from arbitrary positions — e.g. the
        benchmark's mid-game probe segments (the runner copies them
        once before the first segment: segments donate their input
        slab, and the caller keeps ownership of what it passed).

        ``deadline`` (absolute ``time.time()`` value): stop issuing
        further segments once the clock passes it — the in-flight
        segment always completes (never kill a device program; the
        round-2 tunnel wedge postmortem); the result then has
        ``actions.shape[0] < max_moves`` and possibly-unfinished
        games. ``stop_when_done``: stop early once every game has
        ended (two passes) — the done-scalar is computed on device
        per segment and read from a RETIRED segment (one segment
        behind at ``depth>=1``, already materialized at any depth —
        the host never blocks on the fresh dispatch); rows recorded
        past the all-done segment are replaced by the ZERO padding
        the sync path writes, so the result keeps the full
        ``[max_moves, B]`` shape and stays bit-identical at every
        depth. Callers distinguish a deadline truncation from a
        done-exit via ``final.done.all()``. Both default off, which
        preserves the bit-identical-to-monolithic contract (under
        ``stop_when_done`` the action rows after every game has
        ended are zeros where the monolithic scan would have recorded
        sampled-then-ignored moves; ``live``/``num_moves``/``final``
        are unaffected).

        ``depth``/``pipeline``: the dispatch window (see
        :class:`~rocalphago_tpu.runtime.pipeline.ChunkPipeline`);
        pass ``pipeline`` to share one across calls (bench A/Bs read
        its ``host_gap_frac``)."""
        states = (new_states(cfg, batch) if initial_states is None
                  else initial_states)
        # delta-encode carry: cold per run (the runner owns it — the
        # first segment's encodes all refresh, which IS the
        # from-scratch read; warm reuse accrues across segments)
        caches = init_caches(cfg, batch) if incremental else None
        if mesh is not None:
            states = meshlib.shard_batch(mesh, states)
            if caches is not None:
                caches = meshlib.shard_batch(mesh, caches)
            params_a = meshlib.replicate(mesh, params_a)
            params_b = meshlib.replicate(mesh, params_b)
        elif initial_states is not None:
            # segments donate their input slab; the caller keeps its
            # states, so the first donation must eat OUR copy
            states = copy_states(states)
        pipe = pipeline if pipeline is not None else ChunkPipeline(
            depth, runner="selfplay")
        acts = [jnp.zeros((0, batch), jnp.int32)]   # max_moves=0 parity
        lives = [jnp.zeros((0, batch), bool)]
        plies = 0
        done_plies = None      # plies recorded when all games done

        def _first_done(retired):
            """Earliest retired segment whose done-scalar is True
            (retire order = dispatch order; done is monotonic). Each
            entry is ``(payload=plies, handle=done-scalar)``; the
            handle is materialized — the fetch cannot sync anything
            still in flight."""
            for seg_plies, handle in retired:
                if bool(jax.device_get(handle)):
                    return seg_plies
            return None

        for offset in range(0, max_moves, chunk):
            if deadline is not None and _time.time() > deadline:
                # deliberately NOT zero-padded (unlike the
                # stop_when_done exit): the short actions shape IS the
                # caller's truncation signal, and a deadline stop ends
                # the caller's whole measurement anyway, so the one
                # odd-shape finish compile happens at most once per
                # process — inside the 2x backstop slack
                break
            # exact remainder segment (one extra compile at most) so
            # no ply beyond max_moves ever runs — results stay
            # bit-identical to the monolithic scan
            faults.barrier("selfplay.chunk", offset)
            length = min(chunk, max_moves - offset)
            t0 = _time.monotonic()
            states, caches, rng, actions, live = segment(
                params_a, params_b, states, caches, rng,
                jnp.int32(offset), length)
            acts.append(actions)
            lives.append(live)
            plies = offset + length
            _plies_c.inc(length)
            handle = done_flag(states) if stop_when_done else rng
            retired = pipe.push(handle, payload=plies)
            _seg_h.observe(_time.monotonic() - t0)
            if stop_when_done:
                done_plies = _first_done(retired)
                if done_plies is not None:
                    break
        if stop_when_done:
            # drain both exits: the lagged extras are no-op segments
            # (the result fetch would sync them anyway) and a shared
            # pipeline must not leak this run's done-handles into the
            # next run's retire stream
            retired = pipe.drain()
            if done_plies is None:
                done_plies = _first_done(retired)
        else:
            pipe.finish()
        if done_plies is not None:
            # zero-pad from the first all-done segment (see
            # docstring): rows recorded by lagged extra segments are
            # dropped — those segments stepped frozen games (a no-op
            # on `states`) and the sync path writes zeros here. Fixed
            # output shapes keep the finish program at one compile.
            actions_all = jnp.concatenate(acts)[:done_plies]
            lives_all = jnp.concatenate(lives)[:done_plies]
            pad = max_moves - done_plies
            return finish(
                states,
                jnp.concatenate(
                    [actions_all, jnp.zeros((pad, batch), jnp.int32)]),
                jnp.concatenate(
                    [lives_all, jnp.zeros((pad, batch), bool)]))
        return finish(states, jnp.concatenate(acts),
                      jnp.concatenate(lives))

    def warmup(params_a, params_b):
        """Compile-and-once-execute the EXACT programs a full
        ``run()`` dispatches — the chunk-length segment, the
        remainder segment (when ``max_moves % chunk``), the
        done-scalar reduction and the full-shape finish program — so
        a subsequent timed rep pays zero compiles (the headline
        bench's untimed-warmup discipline, at a couple of segments'
        cost instead of a whole game's; BENCH_r05's compile leak was
        the full-rep warmup eating the budget the timed rep needed).
        Returns the measured post-compile wall seconds of one
        chunk-length segment (the caller's rep-time estimator)."""
        states = new_states(cfg, batch)
        caches = init_caches(cfg, batch) if incremental else None
        rng = jax.random.key(0)
        lengths = [min(chunk, max_moves)]
        rem = max_moves % chunk
        if max_moves > chunk and rem:
            lengths.append(rem)
        seg_s = None
        for length in lengths:
            # compile pass, then one timed pass for the estimator
            states, caches, rng, actions, live = segment(
                params_a, params_b, states, caches, rng,
                jnp.int32(0), length)
            jax.block_until_ready(actions)
            if length == lengths[0]:
                t0 = _time.monotonic()
                states, caches, rng, actions, live = segment(
                    params_a, params_b, states, caches, rng,
                    jnp.int32(0), length)
                jax.block_until_ready(actions)
                seg_s = _time.monotonic() - t0
        jax.device_get(done_flag(states))
        jax.device_get(finish(
            states, jnp.zeros((max_moves, batch), jnp.int32),
            jnp.zeros((max_moves, batch), bool)).winners)
        return seg_s

    # the compiled per-segment program, exposed for benchmarks (flops
    # accounting via .lower().compile().cost_analysis()) — signature
    # (params_a, params_b, states, caches, rng, offset, length=K).
    # NOTE: it donates its `states`/`caches` arguments when executed.
    run.segment = segment
    run.warmup = warmup
    return run


def host_winners(cfg: GoConfig, boards: np.ndarray) -> np.ndarray:
    """Area-score final boards on HOST: int32 [B] (+1/-1/0).

    Equivalent to ``vmap(winner)`` but in numpy (the oracle's
    :func:`pygo.score_board` per board) — benchmarks use it to keep
    whole-board region labeling out of the compiled program (scoring
    happens once per game; a host BFS is microseconds and shrinks the
    XLA graph the experimental TPU backend must handle).
    """
    from rocalphago_tpu.engine.pygo import score_board

    size = cfg.size
    boards = np.asarray(boards, np.int8).reshape(-1, size, size)
    out = np.zeros(len(boards), np.int32)
    for b, board in enumerate(boards):
        black, white = score_board(board, cfg.komi)
        diff = black - white
        out[b] = 0 if diff == 0 else (1 if diff > 0 else -1)
    return out


def make_device_rollout(cfg: GoConfig, features: tuple, apply_fn: Callable,
                        rollout_limit: int = 500,
                        temperature: float = 1.0,
                        with_steps: bool = False):
    """Jitted ``(params, states, rng) -> winners`` rollout-to-terminal
    (``with_steps=True``: ``-> (winners, executed_plies)`` — benchmarks
    must not assume the early-exit loop ran to ``rollout_limit``).

    The MCTS λ-mix's rollout leg, fully on device (SURVEY.md §3.3
    rebuild note): play a *batched* :class:`GoState` — e.g. a wave of
    leaves bridged via :func:`jaxgo.from_pygo` — to the end of the game
    (≤ ``rollout_limit`` further plies) with one rollout net playing
    both colors, then area-score. Finished or padded entries stay
    frozen (``step`` is a no-op on done games). Returns int32 ``[B]``
    winners (+1 black / -1 white / 0 draw); callers translate to the
    entry player's perspective.

    Same ply body as :func:`play_games`, minus the two-net color
    split: rollouts use a single policy, so every ply is exactly one
    full-batch forward. The loop is a ``while_loop`` that EXITS as
    soon as every game in the wave has ended (two passes) — typical
    games finish far before ``rollout_limit``, and a fixed-length
    scan would make every rollout pay the worst case (measured 10×
    on 9×9 with the default limit of 500).
    """
    n = cfg.num_points
    vgd = vgroup_data(cfg, with_member=needs_member(features),
                      with_zxor=cfg.enforce_superko)
    enc = batched_encoder(cfg, features)
    vsens = jax.vmap(functools.partial(sensible_mask, cfg))
    vstep = jax.vmap(functools.partial(step, cfg))

    @jax.jit
    def run(params, states: GoState, rng: jax.Array) -> jax.Array:
        def ply(carry):
            states, rng, t = carry
            rng, sub = jax.random.split(rng)
            gd = vgd(states)
            planes = enc(states, gd)
            logits = apply_fn(params, planes)
            sens = vsens(states, gd)
            neg = jnp.finfo(logits.dtype).min
            masked = jnp.where(sens, logits / temperature, neg)
            action = jax.random.categorical(sub, masked, axis=-1)
            must_pass = ~sens.any(axis=-1)
            action = jnp.where(must_pass, n, action).astype(jnp.int32)
            return vstep(states, action, gd), rng, t + 1

        def cond(carry):
            states, _, t = carry
            return ~states.done.all() & (t < rollout_limit)

        final, _, t = lax.while_loop(cond, ply,
                                     (states, rng, jnp.int32(0)))
        winners = jax.vmap(functools.partial(winner, cfg))(final)
        # with_steps: also report the executed ply count (benchmarks
        # must not assume the loop ran to rollout_limit)
        return (winners, t) if with_steps else winners

    return run
