"""Transposition-keyed NN evaluation cache for the serve fleet.

Fleet traffic is massively repetitive — thousands of sessions walk
the same empty-board openings and shared joseki, MCTS re-reaches
transpositions, canary arms replay the incumbent's positions — yet
every dispatched row pays a full policy+value device eval. The engine
already carries an exact uint32[2] Zobrist hash per position
(``engine/jaxgo.py``, vectorized superko), extended to an *eval
signature* (:func:`rocalphago_tpu.engine.jaxgo.eval_signature`) that
also covers the player to move, simple-ko point, done flag and
per-stone age buckets — everything the feature planes read. KataGo's
NN output cache ("Accelerating Self-Play Learning in Go", PAPERS.md)
is the precedent: redundant evals are the cheapest device work to
eliminate.

:class:`EvalCache` is a bounded, sharded-lock LRU keyed
``(sig_hi, sig_lo, board_size, komi, params_version)`` storing the
EXACT device outputs (host copies). Hits are therefore bit-identical
to a device eval by construction, and hot-swap invalidation is free:
the params version is part of the key, so a swapped net can never be
served a stale entry — and because the evaluator's version registry
REUSES version numbers after retirement, the evaluator explicitly
calls :meth:`evict_version` whenever a version retires.

Collision safety: the signature is 64 bits, so a false hit needs a
same-shard 64-bit collision among live entries — at the default
100k-entry capacity the birthday bound puts the collision
probability among resident entries around ``1e-10``. For paranoia
runs, ``ROCALPHAGO_EVAL_CACHE_VERIFY=1`` stores the raw board bytes
with each entry, compares them on every hit, counts mismatches in
``eval_cache_collisions_total`` and serves the miss path instead —
turning a silent wrong answer into a counted non-event.

Symmetry folding: ``ROCALPHAGO_EVAL_CACHE_SYMMETRY=1`` replaces the
Zobrist key with a CANONICAL exact key — the lexicographically
smallest of the 8 dihedral transforms of the board bytes (plus
age-bucket bytes, remapped ko, turn, done) — and stores priors in
the canonical orientation, remapping them back on hit. This trades
per-batch host transforms for up to 8× more hits. It is OFF by
default and flag-gated because the nets are not exactly
equivariant: a symmetric hit returns the eval of the *transformed*
board, which is only approximately the eval of the original (the
OFF path stays bit-identical).

Thread-safety: entries shard by key hash across
``ROCALPHAGO_EVAL_CACHE_SHARDS`` independent locks. Shard locks
never nest — with each other or with any other serve lock (the
evaluator calls in from its dispatcher thread with no lock held, and
retirement eviction runs after ``BatchingEvaluator._cond`` is
released) — so the cache adds no edges to the lock-order graph.
"""

from __future__ import annotations

import functools
import os
from collections import OrderedDict

import numpy as np

from rocalphago_tpu.analysis import lockcheck
from rocalphago_tpu.obs import registry as obs_registry

#: master switch: ``1`` makes ServePool/MultiSizePool build a cache
ENABLE_ENV = "ROCALPHAGO_EVAL_CACHE"
#: total entry bound across all shards (default 100_000)
CAP_ENV = "ROCALPHAGO_EVAL_CACHE_CAP"
#: lock-shard count (default 8)
SHARDS_ENV = "ROCALPHAGO_EVAL_CACHE_SHARDS"
#: paranoia mode: compare board bytes on hit, count collisions
VERIFY_ENV = "ROCALPHAGO_EVAL_CACHE_VERIFY"
#: fold the 8 dihedral symmetries into a canonical key (approximate —
#: nets are not exactly equivariant; OFF path bit-identical)
SYMMETRY_ENV = "ROCALPHAGO_EVAL_CACHE_SYMMETRY"

DEFAULT_CAPACITY = 100_000
DEFAULT_SHARDS = 8


def cache_enabled() -> bool:
    """The master env switch (explicit ``EvalCache`` args override)."""
    return os.environ.get(ENABLE_ENV, "") not in ("", "0")


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "") not in ("", "0")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    return int(raw) if raw.strip() else default


# ----------------------------------------------------------- symmetry


@functools.lru_cache(maxsize=None)
def dihedral_perms(size: int):
    """``(perms, inverses)``: the 8 dihedral transforms as flat-index
    permutations. ``canon_field = field[perms[t]]`` applies transform
    ``t``; ``field = canon_field[inverses[t]]`` undoes it."""
    idx = np.arange(size * size, dtype=np.int64).reshape(size, size)
    perms, invs = [], []
    for k in range(4):
        for flip in (False, True):
            t = np.rot90(idx, k)
            if flip:
                t = np.fliplr(t)
            p = np.ascontiguousarray(t).reshape(-1)
            inv = np.empty_like(p)
            inv[p] = np.arange(p.size)
            perms.append(p)
            invs.append(inv)
    return tuple(perms), tuple(invs)


def canonical_key(size: int, board: np.ndarray, buckets: np.ndarray,
                  ko: int, turn: int, done: bool):
    """``(core_key, t)``: the symmetry-folded EXACT key of a position
    — the transform ``t`` whose board bytes are lexicographically
    smallest (first such ``t`` on ties) canonicalizes the board, the
    age buckets and the ko point; turn and done are invariant. The
    key is raw bytes, so unlike the Zobrist path it cannot collide.
    """
    perms, invs = dihedral_perms(size)
    best_t, best_cb = 0, board[perms[0]].tobytes()
    for t in range(1, 8):
        cb = board[perms[t]].tobytes()
        if cb < best_cb:
            best_t, best_cb = t, cb
    p, inv = perms[best_t], invs[best_t]
    cko = -1 if ko < 0 else int(inv[ko])
    core = (best_cb, buckets[p].tobytes(), cko, int(turn), bool(done))
    return core, best_t


def canonicalize_priors(priors: np.ndarray, t: int,
                        size: int) -> np.ndarray:
    """Reorder a priors row ``[N+1]`` (pass logit last, invariant)
    into the canonical orientation ``t``."""
    n = size * size
    perms, _ = dihedral_perms(size)
    return np.concatenate([priors[..., :n][..., perms[t]],
                           priors[..., n:]], axis=-1)


def orient_priors(canon_priors: np.ndarray, t: int,
                  size: int) -> np.ndarray:
    """Undo :func:`canonicalize_priors`: canonical-frame priors back
    to the original orientation of a row canonicalized by ``t``."""
    n = size * size
    _, invs = dihedral_perms(size)
    return np.concatenate([canon_priors[..., :n][..., invs[t]],
                           canon_priors[..., n:]], axis=-1)


# -------------------------------------------------------------- cache


class EvalCache:
    """Bounded, sharded-lock LRU of NN eval outputs (module docstring
    for key anatomy / collision math / invalidation).

    Keys are plain tuples whose LAST element is the params version
    (:meth:`evict_version` relies on that layout); values are opaque
    to the cache (the evaluator stores ``(priors_row, value)`` host
    arrays, in canonical orientation under symmetry folding).
    One instance is safely shared across every session of a pool —
    and across the member pools of a ``MultiSizePool``, since the
    board size is part of the key.
    """

    def __init__(self, capacity: int | None = None,
                 shards: int | None = None,
                 verify: bool | None = None,
                 symmetry: bool | None = None):
        self.capacity = (_env_int(CAP_ENV, DEFAULT_CAPACITY)
                         if capacity is None else int(capacity))
        n = (_env_int(SHARDS_ENV, DEFAULT_SHARDS)
             if shards is None else int(shards))
        self.shards = max(1, n)
        self.symmetry = (_env_flag(SYMMETRY_ENV)
                         if symmetry is None else bool(symmetry))
        # symmetry keys are exact bytes — nothing to verify against
        self.verify = (False if self.symmetry else
                       (_env_flag(VERIFY_ENV)
                        if verify is None else bool(verify)))
        self._per_shard = max(1, self.capacity // self.shards)
        self._maps = [OrderedDict() for _ in range(self.shards)]
        self._locks = [lockcheck.make_lock("EvalCache._shard")
                       for _ in range(self.shards)]
        # per-shard event counts, updated under that shard's lock and
        # summed by stats(); registry counters inc outside the locks
        self._hits = [0] * self.shards
        self._misses = [0] * self.shards
        self._evictions = [0] * self.shards
        self._collisions = [0] * self.shards
        self._hits_c = obs_registry.counter("eval_cache_hits_total")
        self._misses_c = obs_registry.counter("eval_cache_misses_total")
        self._evcap_c = obs_registry.counter(
            "eval_cache_evictions_total", reason="capacity")
        self._evver_c = obs_registry.counter(
            "eval_cache_evictions_total", reason="version")
        self._coll_c = obs_registry.counter(
            "eval_cache_collisions_total")
        self._entries_g = obs_registry.gauge("eval_cache_entries")

    def _shard_of(self, key) -> int:
        return hash(key) % self.shards

    def __len__(self) -> int:
        return sum(len(m) for m in self._maps)

    def lookup(self, key, board_bytes: bytes | None = None):
        """The cached value for ``key`` (refreshing LRU recency), or
        None. In verify mode a hit whose stored board bytes differ
        from ``board_bytes`` is a detected hash collision: counted,
        and served as a miss (the subsequent insert overwrites the
        colliding entry)."""
        i = self._shard_of(key)
        with self._locks[i]:
            entry = self._maps[i].get(key)
            if entry is not None:
                if (self.verify and board_bytes is not None
                        and entry[1] is not None
                        and entry[1] != board_bytes):
                    self._collisions[i] += 1
                    self._misses[i] += 1
                    entry = None
                    collided = True
                else:
                    self._maps[i].move_to_end(key)
                    self._hits[i] += 1
                    collided = False
            else:
                self._misses[i] += 1
                collided = False
        if entry is None:
            self._misses_c.inc()
            if collided:
                self._coll_c.inc()
            return None
        self._hits_c.inc()
        return entry[0]

    def insert(self, key, value, board_bytes: bytes | None = None):
        """Store ``value`` (LRU-evicting the shard past its share of
        the capacity). ``board_bytes`` is retained only in verify
        mode."""
        i = self._shard_of(key)
        evicted = 0
        with self._locks[i]:
            m = self._maps[i]
            m[key] = (value, board_bytes if self.verify else None)
            m.move_to_end(key)
            while len(m) > self._per_shard:
                m.popitem(last=False)
                evicted += 1
                self._evictions[i] += 1
        if evicted:
            self._evcap_c.inc(evicted)
        self._entries_g.set(len(self))

    def evict_version(self, version) -> int:
        """Drop every entry of a retired params version — REQUIRED on
        retirement, not just hygiene: the evaluator's registry reuses
        version numbers (``max(versions) + 1``), so a stale entry
        under a recycled number would be served for a different net.
        Returns the number of entries dropped."""
        removed = 0
        for i in range(self.shards):
            with self._locks[i]:
                m = self._maps[i]
                dead = [k for k in m if k[-1] == version]
                for k in dead:
                    del m[k]
                self._evictions[i] += len(dead)
                removed += len(dead)
        if removed:
            self._evver_c.inc(removed)
        self._entries_g.set(len(self))
        return removed

    def clear(self) -> None:
        for i in range(self.shards):
            with self._locks[i]:
                self._maps[i].clear()
        self._entries_g.set(0)

    def stats(self) -> dict:
        """Host-side counters (the probe surface — mirrored literally
        in ``ServePool.stats``; the obs registry carries the same
        numbers as metrics)."""
        hits = sum(self._hits)
        misses = sum(self._misses)
        total = hits + misses
        return {
            "enabled": True,
            "entries": len(self),
            "capacity": self.capacity,
            "hits": hits,
            "misses": misses,
            "evictions": sum(self._evictions),
            "collisions": sum(self._collisions),
            "hit_rate": (round(hits / total, 4) if total else None),
        }


def disabled_stats() -> dict:
    """The ``stats()`` shape when no cache is attached — same keys,
    always present, so the probe schema does not depend on config."""
    return {"enabled": False, "entries": 0, "capacity": 0, "hits": 0,
            "misses": 0, "evictions": 0, "collisions": 0,
            "hit_rate": None}
