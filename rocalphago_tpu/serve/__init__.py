"""Fleet-grade play service: one fused evaluator, many live games.

The path from "one GTP process per game" to heavy-traffic serving is
throughput-by-batching: every active search is blocked on the same
tiny policy+value forward, so pending leaf evaluations from ALL live
games coalesce into one device batch (the economics behind Pgx's
10^4–10^6 steps/s band and KataGo's batched self-play service —
PAPERS.md). The subsystem fuses pieces that already exist:

* :mod:`.evaluator` — the shared :class:`~.evaluator.
  BatchingEvaluator`: one jit-compiled policy+value program at a few
  fixed batch sizes, fed by a queue that coalesces pending leaf-eval
  requests across sessions under a fill-target / max-wait-µs dispatch
  policy, padding to the nearest compiled size;
* :mod:`.sessions` — :class:`~.sessions.ServePool` /
  :class:`~.sessions.SessionPlayer`: N concurrent game sessions
  sharing ONE compiled search (``search/device_mcts.py``'s
  ``prepare_sim``/``apply_sim`` seam) whose leaf evaluations go
  through the shared evaluator instead of each session's own jit
  program;
* :mod:`.admission` — bounded queue + session caps; under overload a
  shed (:class:`~.admission.EvaluatorOverload`) steps the session
  down the existing :class:`~rocalphago_tpu.interface.resilient.
  ResilientPlayer` ladder (reduced sims → raw policy → rules
  fallback) and the :class:`~rocalphago_tpu.runtime.deadline.
  Deadline` SLO guarantees an anytime answer.

Architecture, dispatch policy, knobs and measured numbers:
docs/SERVING.md. Benchmark: ``benchmarks/bench_serve.py``.
"""

from rocalphago_tpu.serve.admission import (  # noqa: F401
    AdmissionController,
    AdmissionError,
    EvaluatorOverload,
)
from rocalphago_tpu.serve.evaluator import BatchingEvaluator  # noqa: F401
from rocalphago_tpu.serve.sessions import (  # noqa: F401
    FleetDriver,
    ServePool,
    ServeSession,
    SessionPlayer,
)
