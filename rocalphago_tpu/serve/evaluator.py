"""The shared batching evaluator: cross-game leaf evaluation.

One dispatcher thread owns ONE jit-compiled policy+value program
(``search.eval_batch`` from :func:`rocalphago_tpu.search.device_mcts.
make_device_mcts`) compiled at a few FIXED batch sizes. Sessions
submit pending leaf states (typically one row per live search per
simulation); the dispatcher coalesces whole requests across sessions
into one device batch, pads to the nearest compiled size (padded rows
replicate row 0 and are sliced off — per-row programs, so real rows
are bit-independent of the padding; pinned by
``tests/test_serve.py``), evaluates, and hands each request back its
slice.

Dispatch policy (docs/SERVING.md):

* **fill target** — dispatch as soon as pending rows reach
  ``min(max_batch, live sessions)``: every live search has at most
  one leaf in flight, so a full convoy is the most that can ever
  arrive and waiting past it is pure stall. With no admission
  controller attached the target is ``max_batch``.
* **max wait** — a partial batch is flushed when its OLDEST request
  has waited ``max_wait_us`` (degraded sessions stop submitting; the
  tail must not stall the fleet). ``ROCALPHAGO_SERVE_MAX_WAIT_US``
  overrides the 500 µs default.
* **bounded queue** — ``submit`` past the admission controller's
  ``queue_rows`` bound sheds (:class:`~rocalphago_tpu.serve.
  admission.EvaluatorOverload`) instead of queueing; the session's
  resilience ladder absorbs it.

A failed batch (injected fault at the ``serve.eval`` barrier, or a
real device error) fails ONLY the requests in that batch — their
futures carry the exception, the dispatcher loop survives, and every
other session keeps being served (the soak test's core claim). The
dispatcher THREAD itself is a supervised unit
(:class:`~rocalphago_tpu.runtime.supervisor.SupervisedThread`): an
exception that escapes the per-batch handler — the ``serve.dispatch``
barrier at the top of the loop is the chaos harness's kill point —
re-enters the loop after a classified backoff (queue, counters and
stop flag all live on the evaluator, so nothing is lost), and a
crash LOOP parks the dispatcher and fails pending requests instead
of hanging its sessions.

Batch sizes default to ``1,8,32,128,256`` (clipped to the admission
session cap); ``ROCALPHAGO_SERVE_BATCH_SIZES`` overrides with a
comma list. Each size is one XLA program, compiled on first use (or
ahead of time via ``ServePool.warm``).

Versioned params (docs/ROLLOUT.md): the evaluator holds a registry
of ``version -> (params_p, params_v)`` pairs with one CURRENT
pointer. :meth:`set_params` installs a new pair and flips the
pointer — params are jit ARGUMENTS at fixed compiled shapes, so a
swap is O(1) and never recompiles. A session pins one version for
the whole genmove (:meth:`acquire`/:meth:`release`), so a search
never mixes two nets; the dispatcher never coalesces requests of
different versions into one batch (it splits at a version edge), so
a device batch is single-version by construction. Non-current
versions retire as soon as the last pin (or queued request) drops.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from rocalphago_tpu.analysis import lockcheck
from rocalphago_tpu.obs import registry as obs_registry
from rocalphago_tpu.runtime import faults, supervisor

MAX_WAIT_ENV = "ROCALPHAGO_SERVE_MAX_WAIT_US"
BATCH_SIZES_ENV = "ROCALPHAGO_SERVE_BATCH_SIZES"

#: batch-occupancy histogram edges (real rows / compiled size)
OCC_EDGES = (0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0)


def default_batch_sizes(cap: int | None = None) -> tuple:
    """The compiled-size ladder: env override or ``1,8,32,64,256``,
    clipped to ``cap`` (the session cap — no point compiling a batch
    no convoy can fill). ``cap`` itself joins the ladder: the full
    convoy — every live session's leaf, the steady-state batch — must
    be a compiled size, not padded up to one (a cap of 48 padded to
    256 would waste 4× the eval)."""
    raw = os.environ.get(BATCH_SIZES_ENV, "")
    sizes = (tuple(int(s) for s in raw.split(",") if s.strip())
             if raw else (1, 8, 32, 64, 256))
    sizes = tuple(sorted(set(s for s in sizes if s > 0)))
    if not sizes:
        raise ValueError(f"no usable batch sizes in {raw!r}")
    if cap is not None and cap >= sizes[0]:
        sizes = tuple(sorted(
            set(s for s in sizes if s <= cap) | {cap}))
    return sizes


class _Pending:
    """A submitted evaluation request: rows in, a future out.
    ``komi`` is None (the pool's pinned komi) or the request's custom
    komi — a float applied to every row, or a per-row sequence."""

    __slots__ = ("states", "rows", "komi", "version", "t_submit",
                 "_event", "_result", "_exc")

    def __init__(self, states, rows: int, komi=None,
                 version: int = 0):
        self.states = states
        self.rows = rows
        self.komi = komi
        self.version = version
        self.t_submit = time.monotonic()
        self._event = threading.Event()
        self._result = None
        self._exc = None

    def _finish(self, result) -> None:
        self._result = result
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()

    def result(self, timeout: float | None = None):
        """Block for the batch containing this request; returns
        ``(priors [rows, A], values [rows])`` or re-raises the
        batch's failure. ``timeout`` (tests) raises TimeoutError."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"evaluation not served within {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._result


class BatchingEvaluator:
    """Coalesce leaf-eval requests from many sessions into fixed-size
    device batches (module docstring has the dispatch policy).

    Parameters
    ----------
    eval_fn : ``(params_p, params_v, states[B]) -> (priors, values)``
        — a jitted per-row program (``search.eval_batch``); one
        compile per distinct padded size.
    params_p, params_v : the weights, bound for the pool's lifetime.
    batch_sizes : compiled-size ladder (default
        :func:`default_batch_sizes`).
    max_wait_us : partial-batch flush age (default env / 500 µs).
    admission : optional :class:`~rocalphago_tpu.serve.admission.
        AdmissionController` — provides the queue bound and the
        live-session fill target.
    start : tests pass False to drive/fill the queue by hand.
    eval_komi_fn : optional ``(params_p, params_v, states[B],
        komi f32 [B]) -> (priors, values)`` (``search.
        eval_batch_komi``) — engaged ONLY for batches that contain a
        custom-komi request; default-komi batches stay on ``eval_fn``
        bit-for-bit. Rows without a custom komi ride the komi program
        at ``default_komi``, which scores identically by
        construction.
    default_komi : the pool's pinned komi (``cfg.komi``) — the fill
        value for non-custom rows in a mixed batch.
    """

    def __init__(self, eval_fn, params_p, params_v,
                 batch_sizes=None, max_wait_us: float | None = None,
                 admission=None, start: bool = True,
                 eval_komi_fn=None, default_komi: float = 0.0,
                 metrics=None, restart_policy=None):
        self._eval_fn = eval_fn
        self._eval_komi_fn = eval_komi_fn
        self.default_komi = float(default_komi)
        # the versioned-params registry (module docstring): pairs are
        # jit arguments, the CURRENT pointer is what unversioned
        # submits resolve to, pins keep a version alive across a swap
        self._params = {0: (params_p, params_v)}  # guarded-by: _cond
        self._current = 0                 # guarded-by: self._cond
        self._pins: dict = {}             # guarded-by: self._cond
        self.swaps = 0                    # guarded-by: self._cond
        cap = admission.max_sessions if admission is not None else None
        self.batch_sizes = (tuple(sorted(batch_sizes)) if batch_sizes
                            else default_batch_sizes(cap))
        self.max_batch = self.batch_sizes[-1]
        if max_wait_us is None:
            raw = os.environ.get(MAX_WAIT_ENV, "")
            max_wait_us = float(raw) if raw else 500.0
        self.max_wait_s = max_wait_us / 1e6
        self.admission = admission
        self._cond = lockcheck.make_condition("BatchingEvaluator._cond")
        self._queue: deque = deque()      # guarded-by: self._cond
        self._pending_rows = 0            # guarded-by: self._cond
        self._stop = False                # guarded-by: self._cond
        # dispatch accounting (stats() + the serve probes)
        self.batches = 0
        self.komi_batches = 0
        self.failures = 0
        self.rows_total = 0
        self.padded_total = 0
        self._occ_h = obs_registry.histogram("serve_batch_occupancy",
                                             edges=OCC_EDGES)
        self._wait_h = obs_registry.histogram(
            "serve_queue_wait_seconds")
        self._rows_c = obs_registry.counter("serve_eval_rows_total")
        self._fail_c = obs_registry.counter(
            "serve_eval_failures_total")
        self._depth_g = obs_registry.gauge("serve_queue_depth")
        self._swap_c = obs_registry.counter("serve_param_swaps_total")
        self._ver_g = obs_registry.gauge("serve_params_version")
        self._ver_g.set(0)
        # resurrect-on-death: the loop's state is all on self, so
        # re-entering it after an escaped exception loses nothing; a
        # crash loop parks and fails the queue (no hanging clients)
        self._thread = supervisor.SupervisedThread(
            self._loop, name="serve:dispatcher", metrics=metrics,
            policy=restart_policy, on_park=self._fail_pending)
        if start:
            self._thread.start()

    # ----------------------------------------------------- versions

    @property
    def params_version(self) -> int:
        """The CURRENT version — what an unpinned submit resolves to."""
        with self._cond:
            return self._current

    def add_version(self, params_p, params_v,
                    version: int | None = None) -> int:
        """Register a pair WITHOUT flipping the current pointer (the
        canary's staging path). The new version arrives pinned once —
        :meth:`release` drops the stage pin (retiring the version
        unless it was promoted current meanwhile)."""
        with self._cond:
            v = (max(self._params) + 1 if version is None
                 else int(version))
            self._params[v] = (params_p, params_v)
            self._pins[v] = self._pins.get(v, 0) + 1
            return v

    def set_params(self, params_p=None, params_v=None,
                   version: int | None = None) -> int:
        """The hot swap: install ``(params_p, params_v)`` — or, with
        params omitted, promote an already-registered ``version`` —
        as the new current pair. Params are arguments to the compiled
        programs at fixed shapes, so this is a pointer flip: no
        recompile, no dropped requests; in-flight pinned searches
        finish on the version they started. Returns the version."""
        with self._cond:
            if params_p is None:
                v = int(version)
                if v not in self._params:
                    raise KeyError(
                        f"params version {v} is not registered "
                        f"(have {sorted(self._params)})")
            else:
                v = (max(self._params) + 1 if version is None
                     else int(version))
                self._params[v] = (params_p, params_v)
            prev = self._current
            self._current = v
            if v != prev:
                self.swaps += 1
            # retire every version that is neither current nor pinned
            # (by a session's genmove, a canary's stage, or a queued
            # request)
            for old in [o for o in self._params
                        if o != v and not self._pins.get(o)]:
                del self._params[old]
            self._cond.notify_all()
        if v != prev:
            self._swap_c.inc()
        self._ver_g.set(v)
        return v

    def acquire(self, version: int | None = None) -> int:
        """Pin a version (None = current) for a whole search: the
        session's per-genmove consistency guarantee. Raises KeyError
        when a requested (e.g. rolled-back canary) version is
        retired — callers fall back to ``acquire(None)``."""
        with self._cond:
            v = self._current if version is None else int(version)
            if v not in self._params:
                raise KeyError(
                    f"params version {v} is retired "
                    f"(current {self._current})")
            self._pins[v] = self._pins.get(v, 0) + 1
            return v

    def release(self, version: int) -> None:
        """Drop one pin; a non-current version with no pins left
        retires immediately (its params become collectable)."""
        with self._cond:
            n = self._pins.get(version, 0) - 1
            if n > 0:
                self._pins[version] = n
            else:
                self._pins.pop(version, None)
            for old in [o for o in self._params
                        if o != self._current
                        and not self._pins.get(o)]:
                del self._params[old]

    def version_params(self, version: int | None = None) -> tuple:
        """The ``(params_p, params_v)`` pair of ``version`` (None =
        current) — the promotion path hands these to the facade nets
        so degraded rungs follow the swap."""
        with self._cond:
            v = self._current if version is None else int(version)
            return self._params[v]

    # ------------------------------------------------------- client

    def submit(self, states, rows: int | None = None,
               komi=None, version: int | None = None) -> _Pending:
        """Enqueue a [rows]-batched GoState for evaluation. Raises
        :class:`~rocalphago_tpu.serve.admission.EvaluatorOverload`
        when the bounded queue is full (the shed path) — the caller's
        resilience ladder owns what happens next. ``komi`` (float, or
        a per-row sequence) scores this request's terminal rows under
        that komi instead of the pool's pinned one; it requires
        ``eval_komi_fn`` and only changes which compiled program the
        containing batch runs, not how it is coalesced. ``version``
        pins the request to a registered params version (None = the
        current pointer at enqueue time); the queued request holds a
        pin until it is served, so a swap cannot retire its net."""
        if rows is None:
            rows = int(states.board.shape[0])
        if rows > self.max_batch:
            raise ValueError(
                f"request of {rows} rows exceeds the largest "
                f"compiled batch ({self.max_batch})")
        if komi is not None and self._eval_komi_fn is None:
            raise ValueError(
                "per-request komi needs an eval_komi_fn "
                "(search.eval_batch_komi)")
        with self._cond:
            if self._stop:
                raise RuntimeError("evaluator is closed")
            v = self._current if version is None else int(version)
            if v not in self._params:
                raise KeyError(
                    f"params version {v} is retired "
                    f"(current {self._current})")
            if self.admission is not None:
                self.admission.admit_rows(self._pending_rows, rows)
            req = _Pending(states, rows, komi, version=v)
            self._pins[v] = self._pins.get(v, 0) + 1
            self._queue.append(req)
            self._pending_rows += rows
            self._cond.notify_all()
        return req

    def evaluate(self, states, rows: int | None = None,
                 timeout: float | None = None, komi=None,
                 version: int | None = None):
        """Blocking submit: ``(priors, values)`` for ``states``."""
        return self.submit(states, rows, komi=komi,
                           version=version).result(timeout)

    def eval_direct(self, states, komi=None,
                    version: int | None = None):
        """Run the compiled eval program directly, bypassing the
        queue — warmup (compile each ladder size ahead of traffic)
        and the degraded paths that must not add queue load. ``komi``
        (f32 [B] array) selects the komi-aware program."""
        pp, pv = self.version_params(version)
        if komi is None:
            return self._eval_fn(pp, pv, states)
        return self._eval_komi_fn(pp, pv, states, komi)

    # ---------------------------------------------------- dispatcher

    def _fill_target(self) -> int:
        live = (self.admission.live()
                if self.admission is not None else 0)
        return min(self.max_batch, live) if live > 0 else \
            self.max_batch

    def _padded_size(self, rows: int) -> int:
        for s in self.batch_sizes:
            if s >= rows:
                return s
        return self.max_batch

    def _loop(self) -> None:
        while True:
            # the dispatcher-kill point: OUTSIDE the per-batch try
            # and before any request is popped, so an injected kill
            # takes the THREAD down with the queue intact — the
            # supervised restart serves the same requests
            faults.barrier("serve.dispatch", iteration=self.batches)
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait(0.1)
                if self._stop and not self._queue:
                    return
                # dispatch policy: fill to target, else flush when
                # the oldest request has aged out (close() can clear
                # the queue under us — re-check it each wake)
                while not self._stop and self._queue:
                    if self._pending_rows >= self._fill_target():
                        break
                    age = time.monotonic() - self._queue[0].t_submit
                    if age >= self.max_wait_s:
                        break
                    self._cond.wait(self.max_wait_s - age)
                take, total = [], 0
                while self._queue and (
                        total + self._queue[0].rows <= self.max_batch):
                    if take and (self._queue[0].version
                                 != take[0].version):
                        # never coalesce across a version edge: one
                        # device batch = one net (swap consistency);
                        # the other version's convoy is next round
                        break
                    req = self._queue.popleft()
                    take.append(req)
                    total += req.rows
                self._pending_rows -= total
                depth = self._pending_rows
            self._depth_g.set(depth)
            if take:
                self._dispatch(take, total)

    def _dispatch(self, take: list, total: int) -> None:
        import jax
        import jax.numpy as jnp

        now = time.monotonic()
        for req in take:
            self._wait_h.observe(now - req.t_submit)
        size = self._padded_size(total)
        self.batches += 1
        try:
            # the soak tests' injection point: a fault here fails
            # exactly this batch's requests, never the dispatcher
            faults.barrier("serve.eval", iteration=self.batches)
            states = take[0].states
            if len(take) > 1:
                states = jax.tree.map(
                    lambda *xs: jnp.concatenate(xs, axis=0),
                    *[r.states for r in take])
            komi = None
            if any(r.komi is not None for r in take):
                # a custom-komi request switches the WHOLE batch to
                # the komi program; default-komi requests ride along
                # at default_komi, which scores identically
                self.komi_batches += 1
                komi = jnp.concatenate([
                    jnp.full((r.rows,), self.default_komi,
                             jnp.float32) if r.komi is None
                    else jnp.broadcast_to(
                        jnp.asarray(r.komi, jnp.float32), (r.rows,))
                    for r in take])
            if size > total:
                # pad rows replicate row 0 (valid states, no NaN
                # hazards) and are sliced off below — per-row
                # programs make real rows independent of them
                pad = size - total
                states = jax.tree.map(
                    lambda x: jnp.concatenate(
                        [x, jnp.broadcast_to(
                            x[:1], (pad,) + x.shape[1:])], axis=0),
                    states)
                if komi is not None:
                    komi = jnp.concatenate(
                        [komi, jnp.broadcast_to(komi[:1], (pad,))])
            priors, values = self.eval_direct(
                states, komi=komi, version=take[0].version)
        except Exception as e:  # noqa: BLE001 — fail the batch, not
            #                     the dispatcher (classified by the
            #                     sessions' resilience ladders)
            self.failures += 1
            self._fail_c.inc()
            for req in take:
                req._fail(e)
                self.release(req.version)
            return
        self.rows_total += total
        self.padded_total += size
        self._rows_c.inc(total)
        self._occ_h.observe(total / size)
        obs_registry.counter("serve_eval_batches_total",
                             size=str(size)).inc()
        offset = 0
        for req in take:
            req._finish((priors[offset:offset + req.rows],
                         values[offset:offset + req.rows]))
            offset += req.rows
            self.release(req.version)

    def _fail_pending(self) -> None:
        """Parked-dispatcher cleanup: fail everything queued so no
        session blocks forever on a dead dispatcher."""
        with self._cond:
            leftovers = list(self._queue)
            self._queue.clear()
            self._pending_rows = 0
        err = self._thread.error
        for req in leftovers:
            req._fail(RuntimeError(
                f"evaluator dispatcher parked"
                f"{f' ({type(err).__name__}: {err})' if err else ''}"))
            self.release(req.version)

    # ------------------------------------------------------ lifecycle

    def drain_once(self) -> None:
        """Tests (``start=False``): run one dispatch round inline."""
        with self._cond:
            take, total = [], 0
            while self._queue and (
                    total + self._queue[0].rows <= self.max_batch):
                if take and (self._queue[0].version
                             != take[0].version):
                    break  # single-version batches (see _loop)
                req = self._queue.popleft()
                take.append(req)
                total += req.rows
            self._pending_rows -= total
        if take:
            self._dispatch(take, total)

    def close(self) -> None:
        """Stop the dispatcher; pending requests fail (closed)."""
        with self._cond:
            self._stop = True
            leftovers = list(self._queue)
            self._queue.clear()
            self._pending_rows = 0
            self._cond.notify_all()
        for req in leftovers:
            req._fail(RuntimeError("evaluator closed"))
            self.release(req.version)
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    # ---------------------------------------------------------- stats

    def stats(self) -> dict:
        """Probe snapshot (`rocalphago-health`'s ``serve`` block)."""
        with self._cond:
            depth = self._pending_rows
            version = self._current
            swaps = self.swaps
        return {
            "batches": self.batches,
            "komi_batches": self.komi_batches,
            "rows": self.rows_total,
            "failures": self.failures,
            "queue_depth": depth,
            "params_version": version,
            "swaps": swaps,
            "batch_occupancy": (
                round(self.rows_total / self.padded_total, 4)
                if self.padded_total else None),
            "batch_sizes": list(self.batch_sizes),
            "max_wait_us": round(self.max_wait_s * 1e6, 1),
        }
