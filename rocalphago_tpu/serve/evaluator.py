"""The shared batching evaluator: cross-game leaf evaluation.

One dispatcher thread owns ONE jit-compiled policy+value program
(``search.eval_batch`` from :func:`rocalphago_tpu.search.device_mcts.
make_device_mcts`) compiled at a few FIXED batch sizes. Sessions
submit pending leaf states (typically one row per live search per
simulation); the dispatcher coalesces whole requests across sessions
into one device batch, pads to the nearest compiled size (padded rows
replicate row 0 and are sliced off — per-row programs, so real rows
are bit-independent of the padding; pinned by
``tests/test_serve.py``), evaluates, and hands each request back its
slice.

Dispatch policy (docs/SERVING.md):

* **fill target** — dispatch as soon as pending rows reach
  ``min(max_batch, live sessions)``: every live search has at most
  one leaf in flight, so a full convoy is the most that can ever
  arrive and waiting past it is pure stall. With no admission
  controller attached the target is ``max_batch``.
* **max wait** — a partial batch is flushed when its OLDEST request
  has waited ``max_wait_us`` (degraded sessions stop submitting; the
  tail must not stall the fleet). ``ROCALPHAGO_SERVE_MAX_WAIT_US``
  overrides the 500 µs default.
* **bounded queue** — ``submit`` past the admission controller's
  ``queue_rows`` bound sheds (:class:`~rocalphago_tpu.serve.
  admission.EvaluatorOverload`) instead of queueing; the session's
  resilience ladder absorbs it.

A failed batch (injected fault at the ``serve.eval`` barrier, or a
real device error) fails ONLY the requests in that batch — their
futures carry the exception, the dispatcher loop survives, and every
other session keeps being served (the soak test's core claim). The
dispatcher THREAD itself is a supervised unit
(:class:`~rocalphago_tpu.runtime.supervisor.SupervisedThread`): an
exception that escapes the per-batch handler — the ``serve.dispatch``
barrier at the top of the loop is the chaos harness's kill point —
re-enters the loop after a classified backoff (queue, counters and
stop flag all live on the evaluator, so nothing is lost), and a
crash LOOP parks the dispatcher and fails pending requests instead
of hanging its sessions.

Batch sizes default to ``1,8,32,128,256`` (clipped to the admission
session cap); ``ROCALPHAGO_SERVE_BATCH_SIZES`` overrides with a
comma list. Each size is one XLA program, compiled on first use (or
ahead of time via ``ServePool.warm``).

Versioned params (docs/ROLLOUT.md): the evaluator holds a registry
of ``version -> (params_p, params_v)`` pairs with one CURRENT
pointer. :meth:`set_params` installs a new pair and flips the
pointer — params are jit ARGUMENTS at fixed compiled shapes, so a
swap is O(1) and never recompiles. A session pins one version for
the whole genmove (:meth:`acquire`/:meth:`release`), so a search
never mixes two nets; the dispatcher never coalesces requests of
different versions into one batch (it splits at a version edge), so
a device batch is single-version by construction. Non-current
versions retire as soon as the last pin (or queued request) drops.

Transposition cache (docs/SERVING.md "Evaluation cache"): with an
:class:`~rocalphago_tpu.serve.evalcache.EvalCache` attached, the
dispatcher keys every coalesced row by its eval signature (device
arrays riding each request via ``keys=``, or computed by ``key_fn``
for requests without them), serves hits from the cache, collapses
duplicate-key misses to ONE device row (in-batch dedup — convoyed
fleets walking shared openings stop paying per-session evals), pads
only the UNIQUE rows to a compiled size, and fans results back out.
Hits and dedup fan-outs are host copies of exact device outputs, so
the cached path is bit-identical to the uncached one (pinned by
``tests/test_serve.py``); a batch of pure hits skips the device
entirely. Version retirement evicts that version's entries — the
registry reuses version numbers, so this is correctness, not
hygiene. The gather/pad work on the cached path is EAGER jax (no
tracked jit entry), so ``jax_compiles_total`` stays flat.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from rocalphago_tpu.analysis import lockcheck
from rocalphago_tpu.obs import registry as obs_registry
from rocalphago_tpu.runtime import faults, supervisor

MAX_WAIT_ENV = "ROCALPHAGO_SERVE_MAX_WAIT_US"
BATCH_SIZES_ENV = "ROCALPHAGO_SERVE_BATCH_SIZES"

#: batch-occupancy histogram edges (real rows / compiled size)
OCC_EDGES = (0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0)


def default_batch_sizes(cap: int | None = None) -> tuple:
    """The compiled-size ladder: env override or ``1,8,32,64,256``,
    clipped to ``cap`` (the session cap — no point compiling a batch
    no convoy can fill). ``cap`` itself joins the ladder: the full
    convoy — every live session's leaf, the steady-state batch — must
    be a compiled size, not padded up to one (a cap of 48 padded to
    256 would waste 4× the eval)."""
    raw = os.environ.get(BATCH_SIZES_ENV, "")
    sizes = (tuple(int(s) for s in raw.split(",") if s.strip())
             if raw else (1, 8, 32, 64, 256))
    sizes = tuple(sorted(set(s for s in sizes if s > 0)))
    if not sizes:
        raise ValueError(f"no usable batch sizes in {raw!r}")
    if cap is not None and cap >= sizes[0]:
        sizes = tuple(sorted(
            set(s for s in sizes if s <= cap) | {cap}))
    return sizes


class _Pending:
    """A submitted evaluation request: rows in, a future out.
    ``komi`` is None (the pool's pinned komi) or the request's custom
    komi — a float applied to every row, or a per-row sequence.
    ``keys`` is None or the rows' eval signatures (uint32 [rows, 2],
    device or host) — the transposition-cache keys the searcher
    already computed on device (``SimStep.eval_keys``)."""

    __slots__ = ("states", "rows", "komi", "version", "keys",
                 "t_submit", "_event", "_result", "_exc")

    def __init__(self, states, rows: int, komi=None,
                 version: int = 0, keys=None):
        self.states = states
        self.rows = rows
        self.komi = komi
        self.version = version
        self.keys = keys
        self.t_submit = time.monotonic()
        self._event = threading.Event()
        self._result = None
        self._exc = None

    def _finish(self, result) -> None:
        self._result = result
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()

    def result(self, timeout: float | None = None):
        """Block for the batch containing this request; returns
        ``(priors [rows, A], values [rows])`` or re-raises the
        batch's failure. ``timeout`` (tests) raises TimeoutError."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"evaluation not served within {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._result


class BatchingEvaluator:
    """Coalesce leaf-eval requests from many sessions into fixed-size
    device batches (module docstring has the dispatch policy).

    Parameters
    ----------
    eval_fn : ``(params_p, params_v, states[B]) -> (priors, values)``
        — a jitted per-row program (``search.eval_batch``); one
        compile per distinct padded size.
    params_p, params_v : the weights, bound for the pool's lifetime.
    batch_sizes : compiled-size ladder (default
        :func:`default_batch_sizes`).
    max_wait_us : partial-batch flush age (default env / 500 µs).
    admission : optional :class:`~rocalphago_tpu.serve.admission.
        AdmissionController` — provides the queue bound and the
        live-session fill target.
    start : tests pass False to drive/fill the queue by hand.
    eval_komi_fn : optional ``(params_p, params_v, states[B],
        komi f32 [B]) -> (priors, values)`` (``search.
        eval_batch_komi``) — engaged ONLY for batches that contain a
        custom-komi request; default-komi batches stay on ``eval_fn``
        bit-for-bit. Rows without a custom komi ride the komi program
        at ``default_komi``, which scores identically by
        construction.
    default_komi : the pool's pinned komi (``cfg.komi``) — the fill
        value for non-custom rows in a mixed batch.
    cache : optional :class:`~rocalphago_tpu.serve.evalcache.
        EvalCache` — enables the transposition-cache + in-batch-dedup
        dispatch path (module docstring). None keeps the plain path
        byte-for-byte.
    key_fn : ``(states[B]) -> uint32 [B, 2]`` (``search.eval_key``) —
        computes eval signatures for requests that arrive without
        ``keys``. Required with a non-symmetry ``cache``.
    board : the pool's board size — part of every cache key, so one
        cache is shareable across a ``MultiSizePool``'s members.
    """

    def __init__(self, eval_fn, params_p, params_v,
                 batch_sizes=None, max_wait_us: float | None = None,
                 admission=None, start: bool = True,
                 eval_komi_fn=None, default_komi: float = 0.0,
                 metrics=None, restart_policy=None, cache=None,
                 key_fn=None, board: int = 0):
        self._eval_fn = eval_fn
        self._eval_komi_fn = eval_komi_fn
        self.default_komi = float(default_komi)
        self.cache = cache
        self._key_fn = key_fn
        self.board = int(board)
        if cache is not None and key_fn is None \
                and not cache.symmetry:
            raise ValueError(
                "an EvalCache needs key_fn (search.eval_key) to key "
                "requests that arrive without precomputed keys")
        # the versioned-params registry (module docstring): pairs are
        # jit arguments, the CURRENT pointer is what unversioned
        # submits resolve to, pins keep a version alive across a swap
        self._params = {0: (params_p, params_v)}  # guarded-by: _cond
        self._current = 0                 # guarded-by: self._cond
        self._pins: dict = {}             # guarded-by: self._cond
        self.swaps = 0                    # guarded-by: self._cond
        cap = admission.max_sessions if admission is not None else None
        self.batch_sizes = (tuple(sorted(batch_sizes)) if batch_sizes
                            else default_batch_sizes(cap))
        self.max_batch = self.batch_sizes[-1]
        if max_wait_us is None:
            raw = os.environ.get(MAX_WAIT_ENV, "")
            max_wait_us = float(raw) if raw else 500.0
        self.max_wait_s = max_wait_us / 1e6
        self.admission = admission
        self._cond = lockcheck.make_condition("BatchingEvaluator._cond")
        self._queue: deque = deque()      # guarded-by: self._cond
        self._pending_rows = 0            # guarded-by: self._cond
        self._stop = False                # guarded-by: self._cond
        # dispatch accounting (stats() + the serve probes)
        self.batches = 0
        self.komi_batches = 0
        self.failures = 0
        self.rows_total = 0
        # occupancy honesty under dedup: rows_total counts LOGICAL
        # rows served, unique_rows_total the rows that actually hit
        # the device (equal on the plain path), dedup_rows_saved the
        # duplicate miss rows collapsed away; batch_occupancy = unique
        # / padded, so dedup cannot inflate it past 1
        self.unique_rows_total = 0
        self.dedup_rows_saved_total = 0
        self.padded_total = 0
        self._uniq_c = obs_registry.counter("serve_unique_rows_total")
        self._dedup_c = obs_registry.counter(
            "serve_dedup_rows_saved_total")
        self._occ_h = obs_registry.histogram("serve_batch_occupancy",
                                             edges=OCC_EDGES)
        self._wait_h = obs_registry.histogram(
            "serve_queue_wait_seconds")
        self._rows_c = obs_registry.counter("serve_eval_rows_total")
        self._fail_c = obs_registry.counter(
            "serve_eval_failures_total")
        self._depth_g = obs_registry.gauge("serve_queue_depth")
        self._swap_c = obs_registry.counter("serve_param_swaps_total")
        self._ver_g = obs_registry.gauge("serve_params_version")
        self._ver_g.set(0)
        # resurrect-on-death: the loop's state is all on self, so
        # re-entering it after an escaped exception loses nothing; a
        # crash loop parks and fails the queue (no hanging clients)
        self._thread = supervisor.SupervisedThread(
            self._loop, name="serve:dispatcher", metrics=metrics,
            policy=restart_policy, on_park=self._fail_pending)
        if start:
            self._thread.start()

    # ----------------------------------------------------- versions

    @property
    def params_version(self) -> int:
        """The CURRENT version — what an unpinned submit resolves to."""
        with self._cond:
            return self._current

    def add_version(self, params_p, params_v,
                    version: int | None = None) -> int:
        """Register a pair WITHOUT flipping the current pointer (the
        canary's staging path). The new version arrives pinned once —
        :meth:`release` drops the stage pin (retiring the version
        unless it was promoted current meanwhile)."""
        with self._cond:
            v = (max(self._params) + 1 if version is None
                 else int(version))
            self._params[v] = (params_p, params_v)
            self._pins[v] = self._pins.get(v, 0) + 1
            return v

    def set_params(self, params_p=None, params_v=None,
                   version: int | None = None) -> int:
        """The hot swap: install ``(params_p, params_v)`` — or, with
        params omitted, promote an already-registered ``version`` —
        as the new current pair. Params are arguments to the compiled
        programs at fixed shapes, so this is a pointer flip: no
        recompile, no dropped requests; in-flight pinned searches
        finish on the version they started. Returns the version."""
        with self._cond:
            if params_p is None:
                v = int(version)
                if v not in self._params:
                    raise KeyError(
                        f"params version {v} is not registered "
                        f"(have {sorted(self._params)})")
            else:
                v = (max(self._params) + 1 if version is None
                     else int(version))
                self._params[v] = (params_p, params_v)
            prev = self._current
            self._current = v
            if v != prev:
                self.swaps += 1
            # retire every version that is neither current nor pinned
            # (by a session's genmove, a canary's stage, or a queued
            # request)
            dead = [o for o in self._params
                    if o != v and not self._pins.get(o)]
            for old in dead:
                del self._params[old]
            self._cond.notify_all()
        # cache eviction AFTER dropping _cond: shard locks must never
        # nest under the dispatcher condition (lock-order graph)
        self._evict_retired(dead)
        if v != prev:
            self._swap_c.inc()
        self._ver_g.set(v)
        return v

    def acquire(self, version: int | None = None) -> int:
        """Pin a version (None = current) for a whole search: the
        session's per-genmove consistency guarantee. Raises KeyError
        when a requested (e.g. rolled-back canary) version is
        retired — callers fall back to ``acquire(None)``."""
        with self._cond:
            v = self._current if version is None else int(version)
            if v not in self._params:
                raise KeyError(
                    f"params version {v} is retired "
                    f"(current {self._current})")
            self._pins[v] = self._pins.get(v, 0) + 1
            return v

    def release(self, version: int) -> None:
        """Drop one pin; a non-current version with no pins left
        retires immediately (its params become collectable, its cache
        entries evict — version numbers are REUSED, so a recycled
        number must never see a stale entry)."""
        with self._cond:
            n = self._pins.get(version, 0) - 1
            if n > 0:
                self._pins[version] = n
            else:
                self._pins.pop(version, None)
            dead = [o for o in self._params
                    if o != self._current
                    and not self._pins.get(o)]
            for old in dead:
                del self._params[old]
        self._evict_retired(dead)

    def _evict_retired(self, versions) -> None:
        """Cache-side half of retirement — called with NO lock held."""
        if self.cache is not None:
            for v in versions:
                self.cache.evict_version(v)

    def version_params(self, version: int | None = None) -> tuple:
        """The ``(params_p, params_v)`` pair of ``version`` (None =
        current) — the promotion path hands these to the facade nets
        so degraded rungs follow the swap."""
        with self._cond:
            v = self._current if version is None else int(version)
            return self._params[v]

    # ------------------------------------------------------- client

    def submit(self, states, rows: int | None = None,
               komi=None, version: int | None = None,
               keys=None) -> _Pending:
        """Enqueue a [rows]-batched GoState for evaluation. Raises
        :class:`~rocalphago_tpu.serve.admission.EvaluatorOverload`
        when the bounded queue is full (the shed path) — the caller's
        resilience ladder owns what happens next. ``komi`` (float, or
        a per-row sequence) scores this request's terminal rows under
        that komi instead of the pool's pinned one; it requires
        ``eval_komi_fn`` and only changes which compiled program the
        containing batch runs, not how it is coalesced. ``version``
        pins the request to a registered params version (None = the
        current pointer at enqueue time); the queued request holds a
        pin until it is served, so a swap cannot retire its net.
        ``keys`` rides the rows' precomputed eval signatures to the
        transposition cache (ignored without one attached)."""
        if rows is None:
            rows = int(states.board.shape[0])
        if rows > self.max_batch:
            raise ValueError(
                f"request of {rows} rows exceeds the largest "
                f"compiled batch ({self.max_batch})")
        if komi is not None and self._eval_komi_fn is None:
            raise ValueError(
                "per-request komi needs an eval_komi_fn "
                "(search.eval_batch_komi)")
        with self._cond:
            if self._stop:
                raise RuntimeError("evaluator is closed")
            v = self._current if version is None else int(version)
            if v not in self._params:
                raise KeyError(
                    f"params version {v} is retired "
                    f"(current {self._current})")
            if self.admission is not None:
                self.admission.admit_rows(self._pending_rows, rows)
            req = _Pending(states, rows, komi, version=v, keys=keys)
            self._pins[v] = self._pins.get(v, 0) + 1
            self._queue.append(req)
            self._pending_rows += rows
            self._cond.notify_all()
        return req

    def evaluate(self, states, rows: int | None = None,
                 timeout: float | None = None, komi=None,
                 version: int | None = None, keys=None):
        """Blocking submit: ``(priors, values)`` for ``states``."""
        return self.submit(states, rows, komi=komi, version=version,
                           keys=keys).result(timeout)

    def eval_direct(self, states, komi=None,
                    version: int | None = None):
        """Run the compiled eval program directly, bypassing the
        queue — warmup (compile each ladder size ahead of traffic)
        and the degraded paths that must not add queue load. ``komi``
        (f32 [B] array) selects the komi-aware program."""
        pp, pv = self.version_params(version)
        if komi is None:
            return self._eval_fn(pp, pv, states)
        return self._eval_komi_fn(pp, pv, states, komi)

    # ---------------------------------------------------- dispatcher

    def _fill_target(self) -> int:
        live = (self.admission.live()
                if self.admission is not None else 0)
        return min(self.max_batch, live) if live > 0 else \
            self.max_batch

    def _padded_size(self, rows: int) -> int:
        for s in self.batch_sizes:
            if s >= rows:
                return s
        return self.max_batch

    def _loop(self) -> None:
        while True:
            # the dispatcher-kill point: OUTSIDE the per-batch try
            # and before any request is popped, so an injected kill
            # takes the THREAD down with the queue intact — the
            # supervised restart serves the same requests
            faults.barrier("serve.dispatch", iteration=self.batches)
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait(0.1)
                if self._stop and not self._queue:
                    return
                # dispatch policy: fill to target, else flush when
                # the oldest request has aged out (close() can clear
                # the queue under us — re-check it each wake)
                while not self._stop and self._queue:
                    if self._pending_rows >= self._fill_target():
                        break
                    age = time.monotonic() - self._queue[0].t_submit
                    if age >= self.max_wait_s:
                        break
                    self._cond.wait(self.max_wait_s - age)
                take, total = [], 0
                while self._queue and (
                        total + self._queue[0].rows <= self.max_batch):
                    if take and (self._queue[0].version
                                 != take[0].version):
                        # never coalesce across a version edge: one
                        # device batch = one net (swap consistency);
                        # the other version's convoy is next round
                        break
                    req = self._queue.popleft()
                    take.append(req)
                    total += req.rows
                self._pending_rows -= total
                depth = self._pending_rows
            self._depth_g.set(depth)
            if take:
                self._dispatch(take, total)

    def _dispatch(self, take: list, total: int) -> None:
        import jax
        import jax.numpy as jnp

        now = time.monotonic()
        for req in take:
            self._wait_h.observe(now - req.t_submit)
        size = self._padded_size(total)
        self.batches += 1
        try:
            # the soak tests' injection point: a fault here fails
            # exactly this batch's requests, never the dispatcher
            faults.barrier("serve.eval", iteration=self.batches)
            states = take[0].states
            if len(take) > 1:
                states = jax.tree.map(
                    lambda *xs: jnp.concatenate(xs, axis=0),
                    *[r.states for r in take])
            komi = None
            if any(r.komi is not None for r in take):
                # a custom-komi request switches the WHOLE batch to
                # the komi program; default-komi requests ride along
                # at default_komi, which scores identically
                self.komi_batches += 1
                komi = jnp.concatenate([
                    jnp.full((r.rows,), self.default_komi,
                             jnp.float32) if r.komi is None
                    else jnp.broadcast_to(
                        jnp.asarray(r.komi, jnp.float32), (r.rows,))
                    for r in take])
            if self.cache is not None:
                priors, values, devrows, size = self._eval_cached(
                    states, komi, take, total)
            else:
                if size > total:
                    # pad rows replicate row 0 (valid states, no NaN
                    # hazards) and are sliced off below — per-row
                    # programs make real rows independent of them
                    pad = size - total
                    states = jax.tree.map(
                        lambda x: jnp.concatenate(
                            [x, jnp.broadcast_to(
                                x[:1], (pad,) + x.shape[1:])],
                            axis=0),
                        states)
                    if komi is not None:
                        komi = jnp.concatenate(
                            [komi, jnp.broadcast_to(komi[:1],
                                                    (pad,))])
                priors, values = self.eval_direct(
                    states, komi=komi, version=take[0].version)
                devrows = total
        except Exception as e:  # noqa: BLE001 — fail the batch, not
            #                     the dispatcher (classified by the
            #                     sessions' resilience ladders)
            self.failures += 1
            self._fail_c.inc()
            for req in take:
                req._fail(e)
                self.release(req.version)
            return
        self.rows_total += total
        self.unique_rows_total += devrows
        self.padded_total += size
        self._rows_c.inc(total)
        if devrows:
            self._uniq_c.inc(devrows)
        if size:
            self._occ_h.observe(devrows / size)
            obs_registry.counter("serve_eval_batches_total",
                                 size=str(size)).inc()
        offset = 0
        for req in take:
            req._finish((priors[offset:offset + req.rows],
                         values[offset:offset + req.rows]))
            offset += req.rows
            self.release(req.version)

    # ------------------------------------------------- cached dispatch

    def _row_keys(self, states, take: list, total: int,
                  komi_rows: list, version: int):
        """Cache key + (symmetry) orientation per coalesced row.

        Zobrist mode: signatures come from the requests' precomputed
        device keys (one host transfer) or ``key_fn`` on the
        coalesced states; key = ``(sig_hi, sig_lo, board, komi,
        version)``. Symmetry mode: exact canonical byte keys from the
        host copies of the rows' plane-relevant fields.
        """
        import jax
        import numpy as np

        from rocalphago_tpu.serve import evalcache

        if not self.cache.symmetry:
            if all(r.keys is not None for r in take):
                sig = np.concatenate(
                    [np.asarray(jax.device_get(r.keys)).reshape(
                        r.rows, 2) for r in take], axis=0)
            else:
                sig = np.asarray(jax.device_get(
                    self._key_fn(states))).reshape(total, 2)
            keys = [(int(s[0]), int(s[1]), self.board, komi_rows[i],
                     version) for i, s in enumerate(sig)]
            return keys, None
        board_h, ages_h, steps_h, ko_h, turn_h, done_h = \
            jax.device_get((states.board, states.stone_ages,
                            states.step_count, states.ko, states.turn,
                            states.done))
        board_h = np.asarray(board_h)
        # the same age BUCKET the turns_since planes one-hot; -1
        # marks empty points so the byte key covers exactly what the
        # nets can see
        buckets = np.clip(
            np.asarray(steps_h).reshape(-1, 1) - 1
            - np.asarray(ages_h), 0, 7).astype(np.int8)
        buckets[board_h == 0] = -1
        keys, perms = [], []
        for i in range(total):
            core, t = evalcache.canonical_key(
                self.board, board_h[i], buckets[i], int(ko_h[i]),
                int(turn_h[i]), bool(done_h[i]))
            keys.append(core + (self.board, komi_rows[i], version))
            perms.append(t)
        return keys, perms

    def _eval_cached(self, states, komi, take: list, total: int):
        """The transposition-cache dispatch path: lookup → in-batch
        dedup of the misses → one padded device eval of the UNIQUE
        rows (skipped entirely when everything hits) → fan-out +
        insert. Returns ``(priors [total, A], values [total], unique
        device rows, padded size)`` with outputs as host arrays —
        bit-identical to the plain path because every returned row IS
        a device output row (fresh or cached). The gather/pad of the
        missed rows happens on HOST (one ``device_get`` of the
        coalesced states, then numpy takes) — eager per-shape device
        gathers would compile a throwaway kernel per (leaf, miss
        count) pair and make the cold path pay seconds of XLA; the
        host path costs nothing to warm, and the only device program
        is ``eval_direct`` at an already-compiled ladder size, so
        ``jax_compiles_total`` stays flat.
        """
        import jax
        import jax.numpy as jnp
        import numpy as np

        from rocalphago_tpu.serve import evalcache

        cache = self.cache
        # the cache path's fault barrier (soak: io_error@serve.cache
        # must fail only this batch, never the dispatcher)
        faults.barrier("serve.cache", iteration=self.batches)
        version = take[0].version
        if komi is None:
            komi_rows = [self.default_komi] * total
        else:
            komi_rows = [float(k) for k in
                         np.asarray(jax.device_get(komi))]
        keys, perms = self._row_keys(states, take, total, komi_rows,
                                     version)
        boards_b = None
        if cache.verify:
            bh = np.asarray(jax.device_get(states.board))
            boards_b = [bh[i].tobytes() for i in range(total)]
        out_p: list = [None] * total
        out_v = np.zeros(total, np.float32)
        miss_idx: list = []        # first occurrence of each missed key
        dup_of: list = [None] * total
        first_miss: dict = {}
        for i, key in enumerate(keys):
            hit = cache.lookup(
                key, board_bytes=boards_b[i] if boards_b else None)
            if hit is not None:
                p, v = hit
                if perms is not None:
                    p = evalcache.orient_priors(p, perms[i],
                                                self.board)
                out_p[i] = p
                out_v[i] = v
                continue
            j = first_miss.get(key)
            if j is None:
                first_miss[key] = i
                miss_idx.append(i)
            else:
                dup_of[i] = j
        unique = len(miss_idx)
        padded = 0
        if unique:
            padded = self._padded_size(unique)
            # combined gather+pad in one numpy take per leaf: the
            # index vector is pre-padded to the compiled size with
            # the first missed row (the sliced-off replicate rows the
            # plain path also pads with)
            idx = np.full(padded, miss_idx[0], np.int32)
            idx[:unique] = miss_idx
            states_h = jax.device_get(states)
            # the re-asarray matters: the jit signature cache keys on
            # Python input types, so numpy leaves would grow
            # eval_batch's cache (a counted "compile") even though
            # XLA reuses the executable — one transfer keeps
            # jax_compiles_total honest AND flat
            ustates = jax.tree.map(
                lambda x: jnp.asarray(np.asarray(x)[idx]), states_h)
            ukomi = (jnp.asarray(
                np.asarray(komi_rows, np.float32)[idx])
                if komi is not None else None)
            priors_d, values_d = self.eval_direct(
                ustates, komi=ukomi, version=version)
            pr, va = jax.device_get((priors_d, values_d))
            pr = np.asarray(pr)[:unique]
            va = np.asarray(va, np.float32)[:unique]
            for r, i in enumerate(miss_idx):
                out_p[i] = pr[r]
                out_v[i] = va[r]
                store = pr[r]
                if perms is not None:
                    store = evalcache.canonicalize_priors(
                        store, perms[i], self.board)
                cache.insert(
                    keys[i], (store, va[r]),
                    board_bytes=boards_b[i] if boards_b else None)
        saved = 0
        for i, j in enumerate(dup_of):
            if j is not None:
                out_p[i] = out_p[j]
                out_v[i] = out_v[j]
                saved += 1
        if saved:
            self.dedup_rows_saved_total += saved
            self._dedup_c.inc(saved)
        return np.stack(out_p), out_v, unique, padded

    def _fail_pending(self) -> None:
        """Parked-dispatcher cleanup: fail everything queued so no
        session blocks forever on a dead dispatcher."""
        with self._cond:
            leftovers = list(self._queue)
            self._queue.clear()
            self._pending_rows = 0
        err = self._thread.error
        for req in leftovers:
            req._fail(RuntimeError(
                f"evaluator dispatcher parked"
                f"{f' ({type(err).__name__}: {err})' if err else ''}"))
            self.release(req.version)

    # ------------------------------------------------------ lifecycle

    def drain_once(self) -> None:
        """Tests (``start=False``): run one dispatch round inline."""
        with self._cond:
            take, total = [], 0
            while self._queue and (
                    total + self._queue[0].rows <= self.max_batch):
                if take and (self._queue[0].version
                             != take[0].version):
                    break  # single-version batches (see _loop)
                req = self._queue.popleft()
                take.append(req)
                total += req.rows
            self._pending_rows -= total
        if take:
            self._dispatch(take, total)

    def close(self) -> None:
        """Stop the dispatcher; pending requests fail (closed)."""
        with self._cond:
            self._stop = True
            leftovers = list(self._queue)
            self._queue.clear()
            self._pending_rows = 0
            self._cond.notify_all()
        for req in leftovers:
            req._fail(RuntimeError("evaluator closed"))
            self.release(req.version)
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    # ---------------------------------------------------------- stats

    def stats(self) -> dict:
        """Probe snapshot (`rocalphago-health`'s ``serve`` block)."""
        with self._cond:
            depth = self._pending_rows
            version = self._current
            swaps = self.swaps
        from rocalphago_tpu.serve import evalcache
        return {
            "batches": self.batches,
            "komi_batches": self.komi_batches,
            "rows": self.rows_total,
            "unique_rows": self.unique_rows_total,
            "dedup_saved": self.dedup_rows_saved_total,
            "failures": self.failures,
            "queue_depth": depth,
            "params_version": version,
            "swaps": swaps,
            # unique device rows / padded rows: dedup cannot inflate
            # occupancy past 1 (the plain path has unique == rows)
            "batch_occupancy": (
                round(self.unique_rows_total / self.padded_total, 4)
                if self.padded_total else None),
            "batch_sizes": list(self.batch_sizes),
            "max_wait_us": round(self.max_wait_s * 1e6, 1),
            "cache": (self.cache.stats() if self.cache is not None
                      else evalcache.disabled_stats()),
        }
