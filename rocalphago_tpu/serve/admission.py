"""Admission control for the serving pool: bounded queue, session cap.

A serving process protects itself at two boundaries:

* **sessions** — :meth:`AdmissionController.admit_session` refuses to
  open a game past ``max_sessions`` (:class:`AdmissionError`; the
  front end replies "try another replica" — the LB reads the live
  count off the ``rocalphago-health`` probe);
* **evaluation rows** — the shared evaluator's queue is bounded at
  ``queue_rows`` pending leaf rows. A submit past the bound is SHED:
  :class:`EvaluatorOverload` is raised back into the submitting
  session, whose :class:`~rocalphago_tpu.interface.resilient.
  ResilientPlayer` ladder steps it down (reason ``overload`` →
  reduced-sims retry → raw policy move → rules fallback) — per-session
  load-shedding instead of unbounded queueing, so a burst degrades
  the burst's games gracefully rather than blowing every session's
  latency SLO.

Both decisions are counted (``serve_sheds_total{kind=}``,
``serve_sessions_live``) so the probes and the load balancer see
pressure before users do.
"""

from __future__ import annotations

import os

from rocalphago_tpu.analysis import lockcheck
from rocalphago_tpu.obs import registry as obs_registry

#: default cap on concurrently open sessions (env override)
MAX_SESSIONS_ENV = "ROCALPHAGO_SERVE_MAX_SESSIONS"
#: default bound on pending evaluation rows (env override)
QUEUE_ROWS_ENV = "ROCALPHAGO_SERVE_QUEUE"


class AdmissionError(RuntimeError):
    """Session admission refused: the pool is at ``max_sessions``."""


class EvaluatorOverload(OSError):
    """The evaluator's bounded queue is full; this submit was shed.

    An ``OSError`` so :func:`rocalphago_tpu.runtime.retries.
    is_transient` classifies it transient (load passes; a cheaper
    retry is safe), with ``degradation_reason`` naming the ladder's
    reason code so sheds are visible as ``overload`` — not folded
    into generic transient flake — in the health probe and metrics.
    """

    #: read by ``ResilientPlayer._classify``
    degradation_reason = "overload"


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    return int(raw) if raw else default


class AdmissionController:
    """Thread-safe counters + bounds shared by pool and evaluator."""

    def __init__(self, max_sessions: int | None = None,
                 queue_rows: int | None = None,
                 board: int | None = None):
        self.max_sessions = (_env_int(MAX_SESSIONS_ENV, 256)
                             if max_sessions is None else max_sessions)
        self.queue_rows = (_env_int(QUEUE_ROWS_ENV, 1024)
                           if queue_rows is None else queue_rows)
        self._lock = lockcheck.make_lock("AdmissionController._lock")
        self.live_sessions = 0            # guarded-by: self._lock
        self.session_rejects = 0          # guarded-by: self._lock
        self.queue_sheds = 0              # guarded-by: self._lock
        # ``board`` labels the gauges/counters per pool in a multi-
        # size process (serve_sessions_live{board=}); a plain pool
        # stays on the unlabelled series it always emitted
        labels = {} if board is None else {"board": str(board)}
        self._live_g = obs_registry.gauge("serve_sessions_live",
                                          **labels)
        self._shed_queue_c = obs_registry.counter(
            "serve_sheds_total", kind="queue_full", **labels)
        self._shed_sess_c = obs_registry.counter(
            "serve_sheds_total", kind="session_reject", **labels)

    # ------------------------------------------------------- sessions

    def admit_session(self) -> None:
        with self._lock:
            if self.live_sessions >= self.max_sessions:
                self.session_rejects += 1
                self._shed_sess_c.inc()
                raise AdmissionError(
                    f"pool at capacity ({self.live_sessions}/"
                    f"{self.max_sessions} sessions)")
            self.live_sessions += 1
            self._live_g.set(self.live_sessions)

    def release_session(self) -> None:
        with self._lock:
            self.live_sessions = max(0, self.live_sessions - 1)
            self._live_g.set(self.live_sessions)

    def live(self) -> int:
        """Locked read of the live-session count (the evaluator's
        fill target polls this once per dispatch round)."""
        with self._lock:
            return self.live_sessions

    # ---------------------------------------------------- eval queue

    def admit_rows(self, pending_rows: int, rows: int) -> None:
        """Raise :class:`EvaluatorOverload` (counted) when accepting
        ``rows`` more pending evaluation rows would cross the bound.
        Called under the evaluator's queue lock — pure check + count,
        never blocks."""
        if pending_rows + rows > self.queue_rows:
            with self._lock:
                self.queue_sheds += 1
            self._shed_queue_c.inc()
            raise EvaluatorOverload(
                f"evaluator queue full ({pending_rows} pending + "
                f"{rows} > {self.queue_rows} rows)")

    def stats(self) -> dict:
        with self._lock:
            return {
                "live_sessions": self.live_sessions,
                "max_sessions": self.max_sessions,
                "queue_rows": self.queue_rows,
                "session_rejects": self.session_rejects,
                "queue_sheds": self.queue_sheds,
            }
