"""Session manager: N concurrent games over one compiled search.

:class:`ServePool` owns what is expensive and shared — ONE device
searcher (:func:`rocalphago_tpu.search.device_mcts.make_device_mcts`:
``prepare_sim``/``apply_sim``/``assemble_tree`` compiled once for
every session), ONE :class:`~rocalphago_tpu.serve.evaluator.
BatchingEvaluator` holding the weights, and ONE
:class:`~rocalphago_tpu.serve.admission.AdmissionController`.
:meth:`ServePool.open_session` hands out :class:`ServeSession`\\ s —
cheap per-game handles whose :class:`SessionPlayer` carries only its
own search tree.

A session's ``get_move`` is the device search driven per simulation
through the shared evaluator: ``prepare_sim`` (select + expand, batch
1) → ``evaluator.evaluate`` (the leaf coalesced with every other live
game's leaf into one device batch) → ``apply_sim`` (write + backup).
The split path is the fused in-search path by construction
(``device_mcts.SimStep``), so visits/priors cannot drift between a
pooled session and a standalone ``DeviceMCTSPlayer``.

Resilience: sessions are wrapped in the existing
:class:`~rocalphago_tpu.interface.resilient.ResilientPlayer` ladder —
an evaluator shed (:class:`~rocalphago_tpu.serve.admission.
EvaluatorOverload`, reason ``overload``) steps the session down to a
reduced-sims retry, then the raw policy net, then the rules fallback;
a hung session is abandoned by the ladder's watchdog without
touching the evaluator (other sessions keep being served — the soak
test in ``tests/test_serve.py``). The per-genmove SLO
(``slo_s`` / ``ROCALPHAGO_SERVE_SLO_MS``, or the GTP clock via
``set_move_time``) arms a :class:`~rocalphago_tpu.runtime.deadline.
Deadline` checked between simulations with a one-simulation anytime
floor — an overloaded pool serves shallower searches, never late
errors.

Komi: the pool config's komi is the pinned DEFAULT — default-komi
sessions run the exact compiled program they always did. A session
may carry its own komi (``open_session(komi=...)``, re-threaded live
by GTP ``komi`` via :meth:`ServeSession.set_komi`): komi rides the
request as DATA, and the evaluator rescored such batches through
``search.eval_batch_komi`` — one compiled program per batch size
serving every komi value, so a new komi is a new argument, not a
recompile. Rows at the default komi score identically on either
program (the rescore shifts the terminal margin by exactly ``0.0``).
"""

from __future__ import annotations

import os
import time

from rocalphago_tpu.analysis import lockcheck
from rocalphago_tpu.obs import registry as obs_registry
from rocalphago_tpu.runtime.deadline import Deadline
from rocalphago_tpu.serve.admission import AdmissionController
from rocalphago_tpu.serve.evaluator import BatchingEvaluator

SLO_ENV = "ROCALPHAGO_SERVE_SLO_MS"


def _default_slo_s() -> float | None:
    raw = os.environ.get(SLO_ENV, "")
    return float(raw) / 1e3 if raw else None


class SessionPlayer:
    """Per-session search agent over the pool's shared programs.

    The ``get_move(pygo.GameState) -> move | None`` surface every
    wrapper in this stack expects (GTP engine, ResilientPlayer,
    tournament), plus the hooks the resilience ladder uses:
    ``n_sim``/``sim_limit`` (reduced-budget rung), ``policy`` (raw
    policy rung over the SAME net), and the deadline stats the
    health probe reads (``last_n_sim``, ``deadline_hits``,
    ``last_deadline_hit``).
    """

    def __init__(self, pool: "ServePool"):
        self.pool = pool
        self.policy = pool.policy
        self.board = pool.board
        self._cfg = pool.cfg
        self.komi: float | None = None    # None = the pool's pinned
        #   komi; a float rescales terminal leaf values per request
        self.sim_limit: int | None = None
        self.last_n_sim = None
        self.deadline_hits = 0
        self.last_deadline_hit = False
        self.genmoves = 0
        self._move_time: float | None = None
        #: canary arm hook: a session pinned to a STAGED params
        #: version searches on it every genmove; None follows the
        #: pool's current pointer. A rolled-back (retired) pin falls
        #: back to current — the game continues on the incumbent.
        self.pinned_version: int | None = None
        self.last_version: int | None = None
        import jax.numpy as jnp

        # the free-PUCT root_actions row, built once
        self._free = jnp.full((1,), -1, jnp.int32)

    @property
    def n_sim(self) -> int:
        return self.pool.n_sim

    def set_move_time(self, seconds) -> None:
        """GTP clock hook: per-move wall budget (None = no clock).
        The tighter of this and the pool SLO arms the deadline."""
        self._move_time = (None if seconds is None
                           else max(float(seconds), 0.0))

    def reset(self, reason: str = "new_game") -> None:
        """New game: sessions carry no cross-move state (trees are
        rebuilt per move — the shared-evaluator path's simplicity
        trade; subtree reuse is the standalone player's economy)."""

    def _budget_s(self) -> float | None:
        slo = self.pool.slo_s
        if self._move_time is None:
            return slo
        return self._move_time if slo is None else \
            min(self._move_time, slo)

    def _komi(self) -> float | None:
        """The komi to ride this session's requests: None (the
        pinned program) unless a custom komi differs from the pool
        default — equal values stay on the default path bit-for-bit."""
        k = self.komi
        if k is None or float(k) == float(self._cfg.komi):
            return None
        return float(k)

    def get_move(self, state):
        import jax
        import numpy as np

        from rocalphago_tpu.engine import jaxgo as _jaxgo
        from rocalphago_tpu.utils.coords import unflatten_idx

        pool = self.pool
        search = pool.search
        t0 = time.monotonic()
        self.genmoves += 1
        root = _jaxgo.from_pygo(self._cfg, state)
        roots = jax.tree.map(lambda x: x[None], root)
        eff = self.n_sim
        if self.sim_limit is not None:
            eff = max(1, min(eff, self.sim_limit))
        # the SLO/clock deadline enforces between simulations with a
        # one-simulation floor; the compile-bearing cold pool is
        # exempt (warm() — no honest wall budget spans a compile)
        deadline = Deadline.after(self._budget_s())
        enforce = not deadline.unlimited and pool.warmed
        komi = self._komi()
        # one params version per genmove: pinned for the WHOLE search
        # so a hot swap mid-search cannot mix nets within one tree; a
        # retired (rolled-back) pin falls back to the current pointer
        try:
            ver = pool.evaluator.acquire(self.pinned_version)
        except KeyError:
            self.pinned_version = None
            ver = pool.evaluator.acquire(None)
        self.last_version = ver
        try:
            # root priors through the shared evaluator, like every
            # leaf; with a transposition cache attached, the root's
            # eval signature rides along (leaf rows carry theirs via
            # SimStep.eval_keys — computed on device either way)
            keys0 = (search.eval_key(roots)
                     if pool.evaluator.cache is not None else None)
            priors0, _ = pool.evaluator.evaluate(roots, komi=komi,
                                                 version=ver,
                                                 keys=keys0)
            tree = search.assemble_tree(roots, priors0)
            # steady state is ONE device call per simulation
            # (advance_sim: apply + next prepare fused); the deadline
            # is checked between simulations, one-sim anytime floor
            ctx = search.prepare_sim(tree, self._free)
            ran = 0
            while True:
                priors, values = pool.evaluator.evaluate(
                    ctx.eval_states, komi=komi, version=ver,
                    keys=ctx.eval_keys)
                ran += 1
                if ran >= eff or (enforce and deadline.expired()):
                    tree = search.apply_sim(tree, ctx, priors, values)
                    break
                tree, ctx = search.advance_sim(tree, ctx, priors,
                                               values, self._free)
        finally:
            pool.evaluator.release(ver)
        visits, _ = search.root_stats(tree)
        counts = np.asarray(jax.device_get(visits))[0]
        action = int(counts.argmax())
        self.last_deadline_hit = ran < eff
        self.deadline_hits += int(self.last_deadline_hit)
        self.last_n_sim = ran
        pool.note_genmove(time.monotonic() - t0, ran)
        if action >= self._cfg.num_points or counts[action] == 0:
            return None                              # pass
        return unflatten_idx(action, self._cfg.size)


class FleetDriver:
    """Throughput drive: advance many sessions' searches in lockstep
    rounds, one convoy of cross-game leaves per simulation.

    The thread-per-session path (:class:`SessionPlayer` under the
    ladder) is the latency/robustness mode — every game its own
    thread, failures isolated per session. On a host whose per-row
    thread-handoff cost rivals the eval itself (one busy CPU core,
    hundreds of sessions) the same searches can instead be DRIVEN by
    one loop: the driver stacks the live games' independent per-game
    tree slabs on the batch axis the device search already has,
    requests every simulation's leaf rows from the shared evaluator
    as one submit (coalesced + padded exactly like any other
    client's), and steps all trees with one ``advance_sim`` call per
    round. Same trees, same eval program, same answers — only the
    host-side drive differs: per-row dispatch cost amortizes over
    the fleet instead of repeating per session.

    One driver call = one genmove for EVERY session it drives; games
    join/leave between calls (the fleet re-stacks each round). The
    pool SLO still applies — the deadline is checked between
    simulation convoys with a one-convoy anytime floor, truncating
    every driven search together.
    """

    def __init__(self, pool: "ServePool", sessions):
        self.pool = pool
        self.sessions = list(sessions)
        self.last_n_sim = None
        self.deadline_hits = 0

    def _komi_rows(self, n: int):
        """Per-row komi for a fleet convoy: None unless some driven
        session carries a custom komi (then one float per session,
        pool default where unset)."""
        default = float(self.pool.cfg.komi)
        if len(self.sessions) != n:
            return None
        ks = [getattr(getattr(s, "raw", s), "komi", None)
              for s in self.sessions]
        if all(k is None or float(k) == default for k in ks):
            return None
        return [default if k is None else float(k) for k in ks]

    def genmove_all(self, states) -> list:
        """One move for each of ``states`` (aligned with the driven
        sessions): list of ``(x, y)`` / None (pass)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from rocalphago_tpu.engine import jaxgo as _jaxgo
        from rocalphago_tpu.utils.coords import unflatten_idx

        pool = self.pool
        search = pool.search
        cfg = pool.cfg
        n = len(states)
        t0 = time.monotonic()
        roots = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[_jaxgo.from_pygo(cfg, st) for st in states])
        deadline = Deadline.after(pool.slo_s)
        enforce = not deadline.unlimited and pool.warmed
        komi = self._komi_rows(n)
        # the whole lockstep round searches ONE pinned version — the
        # same per-genmove consistency a threaded session gets
        ver = pool.evaluator.acquire(None)
        try:
            keys0 = (search.eval_key(roots)
                     if pool.evaluator.cache is not None else None)
            priors0, _ = pool.evaluator.evaluate(roots, rows=n,
                                                 komi=komi,
                                                 version=ver,
                                                 keys=keys0)
            tree = search.assemble_tree(roots, priors0)
            free = jnp.full((n,), -1, jnp.int32)
            ctx = search.prepare_sim(tree, free)
            ran = 0
            while True:
                priors, values = pool.evaluator.evaluate(
                    ctx.eval_states, rows=n, komi=komi, version=ver,
                    keys=ctx.eval_keys)
                ran += 1
                if ran >= pool.n_sim or (enforce
                                         and deadline.expired()):
                    tree = search.apply_sim(tree, ctx, priors, values)
                    break
                tree, ctx = search.advance_sim(tree, ctx, priors,
                                               values, free)
        finally:
            pool.evaluator.release(ver)
        visits, _ = search.root_stats(tree)
        counts = np.asarray(jax.device_get(visits))
        self.last_n_sim = ran
        self.deadline_hits += int(ran < pool.n_sim)
        dt = time.monotonic() - t0
        for _ in range(n):
            pool.note_genmove(dt, ran)
        moves = []
        for i in range(n):
            action = int(counts[i].argmax())
            if action >= cfg.num_points or counts[i][action] == 0:
                moves.append(None)
            else:
                moves.append(unflatten_idx(action, cfg.size))
        return moves

    def warm(self) -> None:
        """Compile the driver's fleet-size programs (batch = fleet)
        plus the evaluator sizes the convoys pad to."""
        import jax
        import jax.numpy as jnp

        from rocalphago_tpu.engine.jaxgo import new_states

        pool = self.pool
        n = len(self.sessions)
        roots = new_states(pool.cfg, n)
        priors, _ = pool.evaluator.evaluate(roots, rows=n)
        tree = pool.search.assemble_tree(roots, priors)
        free = jnp.full((n,), -1, jnp.int32)
        ctx = pool.search.prepare_sim(tree, free)
        pr, va = pool.evaluator.evaluate(ctx.eval_states, rows=n)
        tree, ctx = pool.search.advance_sim(tree, ctx, pr, va, free)
        pr, va = pool.evaluator.evaluate(ctx.eval_states, rows=n)
        tree = pool.search.apply_sim(tree, ctx, pr, va)
        jax.block_until_ready(pool.search.root_stats(tree)[0])
        pool.warmed = True


class ServeSession:
    """One live game's handle: the (ladder-wrapped) player plus the
    admission slot, released by :meth:`close`."""

    def __init__(self, pool: "ServePool", sid: int, player, raw):
        self.pool = pool
        self.id = sid
        self.player = player        # what callers serve moves from
        self.raw = raw              # the unwrapped SessionPlayer
        self._closed = False

    def get_move(self, state):
        return self.player.get_move(state)

    @property
    def komi(self) -> float | None:
        """This session's komi (None = the pool's pinned default)."""
        return self.raw.komi

    def set_komi(self, komi: float | None) -> None:
        """Re-thread this session's komi (the GTP ``komi`` command
        lands here): takes effect on the next genmove, no rebuild —
        komi is data to the evaluator, not part of any compiled
        shape. None restores the pool default."""
        self.raw.komi = None if komi is None else float(komi)

    @property
    def params_version(self) -> int | None:
        """The version this session's LAST genmove searched on."""
        return self.raw.last_version

    def pin_version(self, version: int | None) -> None:
        """Pin future genmoves to a staged params version (the canary
        arm assignment); None rejoins the pool's current pointer."""
        self.raw.pinned_version = (None if version is None
                                   else int(version))

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self.pool._release(self.id)

    def __enter__(self) -> "ServeSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class ServePool:
    """The serving subsystem's root object (module docstring).

    Parameters mirror :class:`~rocalphago_tpu.search.device_mcts.
    DeviceMCTSPlayer` where they overlap (``n_sim``, ``max_nodes``,
    ``c_puct``); serving knobs: ``max_sessions`` / ``queue_rows``
    (admission), ``batch_sizes`` / ``max_wait_us`` (dispatch),
    ``slo_s`` (per-genmove deadline; env ``ROCALPHAGO_SERVE_SLO_MS``),
    ``hang_timeout_s`` + ``metrics`` (threaded into each session's
    resilience ladder); ``eval_cache`` (an
    :class:`~rocalphago_tpu.serve.evalcache.EvalCache` to share, None
    to follow ``ROCALPHAGO_EVAL_CACHE``, ``False`` to force-disable
    regardless of the env — refused either way under
    ``enforce_superko``, where NN output is not a pure function of
    the eval signature).
    """

    def __init__(self, value_net, policy_net, n_sim: int = 64,
                 max_nodes: int | None = None, c_puct: float = 5.0,
                 max_sessions: int | None = None,
                 queue_rows: int | None = None,
                 batch_sizes=None, max_wait_us: float | None = None,
                 slo_s: float | None = None,
                 hang_timeout_s: float | None = None, metrics=None,
                 searcher=None, label_board: bool = False,
                 eval_cache=None):
        from rocalphago_tpu.search.device_mcts import make_device_mcts
        from rocalphago_tpu.serve import evalcache

        self.policy = policy_net
        self.value = value_net
        self.cfg = policy_net.cfg
        self.board = policy_net.board
        self.n_sim = n_sim
        self.slo_s = _default_slo_s() if slo_s is None else slo_s
        self.hang_timeout_s = hang_timeout_s
        self.metrics = metrics
        # ``searcher``: share one compiled search across pools (the
        # bench sweep re-pools per session count; jit caches live on
        # the searcher's closures, so injecting it dodges recompiles)
        self.search = searcher if searcher is not None else \
            make_device_mcts(
                self.cfg, policy_net.feature_list,
                value_net.feature_list, policy_net.module.apply,
                value_net.module.apply, n_sim=n_sim,
                max_nodes=max_nodes, c_puct=c_puct)
        # label_board: a pool inside a MultiSizePool labels its
        # admission metrics per size (serve_sessions_live{board=});
        # a standalone pool keeps the unlabelled series
        self.admission = AdmissionController(
            max_sessions, queue_rows,
            board=self.board if label_board else None)
        # transposition cache: explicit instance, or built from the
        # env master switch. Under enforce_superko the NN output is
        # NOT a pure function of the eval signature (the sensible-
        # move mask reads the hash HISTORY), so caching is refused —
        # stats()["cache"]["enabled"] shows the outcome either way.
        cache = eval_cache
        if cache is None and evalcache.cache_enabled():
            cache = evalcache.EvalCache()
        if cache is False:      # explicit opt-out, overrides the env
            cache = None        # switch (the bench A/B's OFF arm)
        if self.cfg.enforce_superko:
            cache = None
        self.eval_cache = cache
        self.evaluator = BatchingEvaluator(
            self.search.eval_batch, policy_net.params, value_net.params,
            batch_sizes=batch_sizes, max_wait_us=max_wait_us,
            admission=self.admission,
            eval_komi_fn=getattr(self.search, "eval_batch_komi", None),
            default_komi=self.cfg.komi, cache=cache,
            key_fn=getattr(self.search, "eval_key", None),
            board=self.board)
        self.warmed = False
        self._lock = lockcheck.make_lock("ServePool._lock")
        self._sessions: dict = {}         # guarded-by: self._lock
        self._next_id = 0                 # guarded-by: self._lock
        self._move_h = obs_registry.histogram("serve_genmove_seconds")
        self._sims_c = obs_registry.counter("serve_session_sims_total")

    # ------------------------------------------------------- sessions

    def open_session(self, resilient: bool = True,
                     reduced_sims: int | None = None,
                     komi: float | None = None) -> ServeSession:
        """Admit one game (:class:`~rocalphago_tpu.serve.admission.
        AdmissionError` at capacity). ``resilient=False`` returns the
        raw player — benchmarks measuring the search alone. ``komi``
        gives THIS session its own komi (module docstring); None is
        the pool's pinned default."""
        self.admission.admit_session()
        raw = SessionPlayer(self)
        raw.komi = None if komi is None else float(komi)
        player = raw
        if resilient:
            from rocalphago_tpu.interface.resilient import (
                ResilientPlayer,
            )

            player = ResilientPlayer(
                raw, metrics=self.metrics, reduced_sims=reduced_sims,
                hang_timeout_s=self.hang_timeout_s)
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            sess = ServeSession(self, sid, player, raw)
            self._sessions[sid] = sess
        return sess

    def _release(self, sid: int) -> None:
        with self._lock:
            if self._sessions.pop(sid, None) is None:
                return
        self.admission.release_session()

    def note_genmove(self, dt: float, sims: int) -> None:
        self._move_h.observe(dt)
        self._sims_c.inc(sims)

    def driver(self, sessions) -> FleetDriver:
        """The lockstep throughput drive over ``sessions`` (see
        :class:`FleetDriver`)."""
        return FleetDriver(self, sessions)

    # -------------------------------------------------------- rollout

    @property
    def params_version(self) -> int:
        return self.evaluator.params_version

    def set_params(self, params_p=None, params_v=None,
                   version: int | None = None) -> int:
        """Hot-swap the pool's net: install ``(params_p, params_v)``
        (or promote a staged ``version``) as the current pair — a
        pointer flip at the evaluator's fixed compiled shapes, live
        sessions keep playing, in-flight genmoves finish on the
        version they pinned. The facade nets follow so the degraded
        rungs (raw policy fallback) serve the same weights."""
        v = self.evaluator.set_params(params_p, params_v,
                                      version=version)
        pp, pv = self.evaluator.version_params(v)
        self.policy.params = pp
        self.value.params = pv
        return v

    def stage_params(self, params_p, params_v,
                     version: int | None = None) -> int:
        """Register a candidate pair WITHOUT flipping current (the
        canary's arm): sessions reach it only via
        :meth:`ServeSession.pin_version`."""
        return self.evaluator.add_version(params_p, params_v,
                                          version=version)

    def promote_version(self, version: int) -> int:
        """Full rollout of a staged version: flip current to it and
        drop the stage pin."""
        v = self.set_params(version=version)
        self.evaluator.release(v)
        return v

    def discard_version(self, version: int) -> None:
        """Roll a staged version back: drop the stage pin so it
        retires once in-flight pinned searches finish; sessions
        pinned to it fall back to current on their next genmove."""
        self.evaluator.release(version)

    # --------------------------------------------------------- warmup

    def warm(self, sizes=None) -> None:
        """Compile ahead of traffic: the per-session programs
        (prepare/apply/assemble/root_stats at batch 1) and the
        evaluator's ladder of padded sizes — so the first live
        genmove never pays XLA, and SLO enforcement (armed only on a
        warm pool) is honest from the first served move."""
        import jax

        from rocalphago_tpu.engine.jaxgo import new_states

        for size in (sizes or self.evaluator.batch_sizes):
            out = self.evaluator.eval_direct(
                new_states(self.cfg, size))
            jax.block_until_ready(out[0])
        roots = new_states(self.cfg, 1)
        if self.eval_cache is not None and \
                hasattr(self.search, "eval_key"):
            # the cached genmove path signs the root on device —
            # compile it here so jax_compiles_total stays flat from
            # the first served move (fleet-size signing compiles in
            # FleetDriver.warm via its keyless evaluate call)
            jax.block_until_ready(self.search.eval_key(roots))
        priors, _ = self.evaluator.eval_direct(roots)
        tree = self.search.assemble_tree(roots, priors)
        import jax.numpy as jnp

        free = jnp.full((1,), -1, jnp.int32)
        ctx = self.search.prepare_sim(tree, free)
        pr, va = self.evaluator.eval_direct(ctx.eval_states)
        tree, ctx = self.search.advance_sim(tree, ctx, pr, va, free)
        pr, va = self.evaluator.eval_direct(ctx.eval_states)
        tree = self.search.apply_sim(tree, ctx, pr, va)
        jax.block_until_ready(self.search.root_stats(tree)[0])
        self.warmed = True

    # ------------------------------------------------------ lifecycle

    def close(self) -> None:
        with self._lock:
            sessions = list(self._sessions.values())
        for sess in sessions:
            sess.close()
        self.evaluator.close()

    def __enter__(self) -> "ServePool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---------------------------------------------------------- stats

    def stats(self) -> dict:
        """The probes' ``serve`` block (schema: docs/SERVING.md):
        live sessions, queue depth, batch occupancy, sheds — the
        fields a load balancer keys health on."""
        adm = self.admission.stats()
        ev = self.evaluator.stats()
        cs = ev["cache"]
        return {
            "sessions": {
                "live": adm["live_sessions"],
                "max": adm["max_sessions"],
                "rejects": adm["session_rejects"],
            },
            "queue": {
                "depth": ev["queue_depth"],
                "rows_bound": adm["queue_rows"],
                "sheds": adm["queue_sheds"],
            },
            "evaluator": {
                "batches": ev["batches"],
                "komi_batches": ev["komi_batches"],
                "rows": ev["rows"],
                "unique_rows": ev["unique_rows"],
                "dedup_saved": ev["dedup_saved"],
                "failures": ev["failures"],
                "batch_occupancy": ev["batch_occupancy"],
                "batch_sizes": ev["batch_sizes"],
                "max_wait_us": ev["max_wait_us"],
            },
            "cache": {
                "enabled": cs["enabled"],
                "entries": cs["entries"],
                "capacity": cs["capacity"],
                "hits": cs["hits"],
                "misses": cs["misses"],
                "evictions": cs["evictions"],
                "collisions": cs["collisions"],
                "hit_rate": cs["hit_rate"],
            },
            "params": {
                "version": ev["params_version"],
                "swaps": ev["swaps"],
            },
            "board": self.board,
            "komi_default": float(self.cfg.komi),
            "slo_ms": (None if self.slo_s is None
                       else round(self.slo_s * 1e3, 3)),
            "n_sim": self.n_sim,
            "warmed": self.warmed,
        }
