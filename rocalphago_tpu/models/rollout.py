"""Fast rollout policy.

Parity: the reference's rollout slot (SURVEY.md §2 "Rollout policy",
[C-LOW] — upstream lacks a trained rollout net; its ``MCTS`` accepts any
``rollout_policy_fn``, and BASELINE's north star names "rollout-policy
convnets", so the rebuild ships one). A deliberately tiny convnet —
one 3×3 conv over the cheap feature subset + 1×1 head + per-position
bias — whose batched forward is a few MXU tiles, so thousands of
vectorized rollout steps per second per chip are feasible.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from rocalphago_tpu.models.nn_util import (
    NeuralNetBase,
    PointHead,
    PointPolicyEval,
    neuralnet,
)

# Cheap planes only: no candidate-simulation or ladder features, so the
# rollout encoder costs a fraction of the full 48-plane pass.
ROLLOUT_FEATURES = ("board", "ones", "turns_since", "liberties")


class RolloutNet(nn.Module):
    """One 3×3 conv → 1×1-conv point head → logits ``[B, N]``
    (``head="bias"`` restores the legacy per-position bias)."""

    board: int = 19
    input_planes: int = 20
    filters: int = 32
    head: str = "fcn"
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(self.filters, (3, 3), padding="SAME",
                            dtype=self.dtype, name="conv1")(x))
        return PointHead(head=self.head, dtype=self.dtype,
                         name="head")(x)


@neuralnet
class CNNRollout(PointPolicyEval, NeuralNetBase):
    """Fast policy for MCTS rollouts (same eval API as CNNPolicy, via
    the shared :class:`PointPolicyEval` mixin)."""

    def __init__(self, feature_list=ROLLOUT_FEATURES, **kwargs):
        kwargs.setdefault("head", "fcn")   # recorded in saved specs
        super().__init__(feature_list, **kwargs)

    @staticmethod
    def create_network(board: int = 19, input_planes: int = 20,
                       filters: int = 32,
                       head: str = "fcn") -> RolloutNet:
        return RolloutNet(board=board, input_planes=input_planes,
                          filters=filters, head=head)

    @classmethod
    def migrate_spec(cls, spec: dict) -> dict:
        """Pre-``head``-kwarg rollout specs carried the per-position
        bias param — load them as the legacy head."""
        spec.setdefault("kwargs", {}).setdefault("head", "bias")
        return spec

    def size_generic(self) -> bool:
        return self.module.head == "fcn"
