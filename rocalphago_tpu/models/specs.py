"""CLI to create model JSON specs (+ fresh weights).

The reference keeps model architecture in a JSON spec created ad hoc in
user code before training (SURVEY.md §2 "NN base / registry"); this
small CLI makes that a one-liner:

    python -m rocalphago_tpu.models.specs policy --out models/policy.json
    python -m rocalphago_tpu.models.specs value --out models/value.json
    python -m rocalphago_tpu.models.specs rollout --out models/rollout.json
"""

from __future__ import annotations

import argparse
import sys

from rocalphago_tpu.features import (
    DEFAULT_FEATURES,
    VALUE_FEATURES,
    default_features,
    value_features,
)
from rocalphago_tpu.models.policy import CNNPolicy
from rocalphago_tpu.models.rollout import ROLLOUT_FEATURES, CNNRollout
from rocalphago_tpu.models.value import CNNValue


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Write a model JSON spec with fresh weights")
    ap.add_argument("kind", choices=("policy", "value", "rollout"))
    ap.add_argument("--out", required=True, help="spec path (.json)")
    ap.add_argument("--board", type=int, default=19)
    ap.add_argument("--layers", type=int, default=12,
                    help="conv trunk depth (policy/value only; the "
                         "rollout net is fixed at one conv layer)")
    ap.add_argument("--filters", type=int, default=None,
                    help="filters per conv layer (default 128; "
                         "rollout default 32)")
    ap.add_argument("--features", nargs="*", default=None,
                    help=f"feature names (policy default: the AlphaGo "
                         f"48-plane set {', '.join(DEFAULT_FEATURES)}; "
                         f"value default adds the 'color' plane (49); "
                         f"rollout default: {', '.join(ROLLOUT_FEATURES)}. "
                         f"ROCALPHAGO_LADDER_PLANES=off drops the two "
                         f"ladder planes from the policy/value defaults "
                         f"— the ladder-free configuration)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--head", default=None,
                    help="head variant: 'fcn' (size-generic params — "
                         "the default; one checkpoint applies at any "
                         "board, see docs/MULTISIZE.md) or the legacy "
                         "size-locked head ('dense' for value, 'bias' "
                         "for policy/rollout). The value default also "
                         "honors ROCALPHAGO_VALUE_HEAD")
    ap.add_argument("--trunk-pool", type=int, default=0,
                    help="number of KataGo-style global-pooling bias "
                         "blocks interleaved in the conv trunk "
                         "(policy/value only; default 0 = the plain "
                         "AlphaGo trunk). Pair with "
                         "ROCALPHAGO_LADDER_PLANES=off so the net can "
                         "see whole-board ladder state without the "
                         "handcrafted planes")
    a = ap.parse_args(argv)

    if a.kind == "policy":
        features = tuple(a.features) if a.features else default_features()
        net = CNNPolicy(features, board=a.board, layers=a.layers,
                        filters_per_layer=a.filters or 128, seed=a.seed,
                        **({"head": a.head} if a.head else {}),
                        **({"trunk_pool": a.trunk_pool}
                           if a.trunk_pool else {}))
    elif a.kind == "value":
        features = tuple(a.features) if a.features else value_features()
        net = CNNValue(features, board=a.board, layers=a.layers,
                       filters_per_layer=a.filters or 128, seed=a.seed,
                       **({"head": a.head} if a.head else {}),
                       **({"trunk_pool": a.trunk_pool}
                          if a.trunk_pool else {}))
    else:
        features = tuple(a.features) if a.features else ROLLOUT_FEATURES
        net = CNNRollout(features, board=a.board,
                         filters=a.filters or 32, seed=a.seed,
                         **({"head": a.head} if a.head else {}))
    net.save_model(a.out)
    print(f"wrote {a.out} ({type(net).__name__}, board={a.board}, "
          f"head={net.module.head}, "
          f"{net.preprocess.output_dim} planes)")
    return net


if __name__ == "__main__":
    main(sys.argv[1:])
