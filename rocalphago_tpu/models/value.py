"""Value network: position → expected outcome in [-1, 1].

Parity: ``AlphaGo/models/value.py::CNNValue`` (same conv trunk as the
policy + 1×1 conv + ``Dense(256, relu)`` + ``Dense(1, tanh)``;
``eval_state``; SURVEY.md §2 "Value net"). NHWC bfloat16 trunk, float32
head, scalar per position.

Head variants (``head=`` kwarg, recorded in saved specs):

* ``"fcn"`` (default) — fully convolutional: 1×1 conv → global
  mean+max spatial pooling → small dense head. No parameter shape
  depends on H×W, so ONE checkpoint applies at 9×9/13×13/19×19
  unchanged (the transfer result of "Transfer of Fully Convolutional
  Policy-Value Networks", PAPERS.md) — the contract
  ``rocalphago_tpu/multisize`` serves and ``training/curriculum.py``
  trains across.
* ``"dense"`` — the legacy size-locked head (flatten H×W into
  ``Dense(dense_units)``). ``ROCALPHAGO_VALUE_HEAD=dense`` restores it
  as the default for new nets; specs saved before the head kwarg
  existed load as this via :meth:`CNNValue.migrate_spec`.

Auxiliary heads (``aux_heads=("ownership", "score")``, KataGo's
"Accelerating Self-Play Learning in Go"): extra prediction heads
sharing the trunk — per-point terminal ownership (tanh ``[B, N]``)
and final score margin (scalar) — trained against the engine's
terminal labels (:func:`rocalphago_tpu.ops.labels.terminal_labels`)
as regularizers that feed territory signal back into the shared
trunk. Default ``()``: the param tree, the value output, and every
compiled program are unchanged. With heads on, the main ``__call__``
still returns only the value (XLA dead-code-eliminates the aux
compute from search programs); training asks for ``with_aux=True``.
Both aux heads are size-generic (1×1 conv / pooled dense), so the
FCN multi-size contract survives.
"""

from __future__ import annotations

import functools
import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from rocalphago_tpu.features import VALUE_FEATURES
from rocalphago_tpu.models.nn_util import ConvTrunk, NeuralNetBase, neuralnet

#: legacy escape hatch: set to ``dense`` to build new value nets with
#: the size-locked flattened head (pre-multisize behavior)
VALUE_HEAD_ENV = "ROCALPHAGO_VALUE_HEAD"


def default_value_head() -> str:
    """The head new value nets build with: ``fcn`` unless
    ``ROCALPHAGO_VALUE_HEAD`` overrides."""
    head = os.environ.get(VALUE_HEAD_ENV, "") or "fcn"
    if head not in ("fcn", "dense"):
        raise ValueError(
            f"{VALUE_HEAD_ENV}={head!r}: expected 'fcn' or 'dense'")
    return head


class ValueNet(nn.Module):
    """Conv trunk → value head → tanh scalar ``[B]``.

    ``head="fcn"``: 1×1 conv (``head_filters`` channels) → global
    mean+max pooling over the board axes → ``Dense(dense_units)`` →
    ``Dense(1)``; every parameter shape is board-size-free.
    ``head="dense"``: the legacy 1-channel 1×1 conv flattened over
    H×W into ``Dense(dense_units)`` (size-locked)."""

    board: int = 19
    input_planes: int = 49
    layers: int = 12
    filters_per_layer: int = 128
    filter_width_1: int = 5
    filter_width_K: int = 3
    dense_units: int = 256
    head: str = "fcn"
    head_filters: int = 32
    aux_heads: tuple = ()
    trunk_pool: int = 0
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, with_aux: bool = False):
        t = ConvTrunk(layers=self.layers,
                      filters_per_layer=self.filters_per_layer,
                      filter_width_1=self.filter_width_1,
                      filter_width_K=self.filter_width_K,
                      global_pool=self.trunk_pool,
                      dtype=self.dtype, name="trunk")(x)
        aux = {}
        if "ownership" in self.aux_heads:
            # per-point ownership off the TRUNK (pre-pooling — the
            # head pooling destroys the spatial signal this head
            # exists to supervise); computed whether or not the
            # caller wants it so the params exist at init — XLA
            # removes it from programs that only use the value
            o = nn.Conv(1, (1, 1), padding="SAME", dtype=self.dtype,
                        name="own_conv")(t)
            aux["ownership"] = jnp.tanh(
                o.reshape((o.shape[0], -1)).astype(jnp.float32))
        if self.head == "dense":
            x = nn.Conv(1, (1, 1), padding="SAME", dtype=self.dtype,
                        name="head_conv")(t)
            x = x.reshape((x.shape[0], -1))
        else:
            x = nn.relu(nn.Conv(self.head_filters, (1, 1),
                                padding="SAME", dtype=self.dtype,
                                name="head_conv")(t))
            # mean+max over the board axes: mean carries territory
            # balance, max carries "is there a winning region
            # anywhere" — both invariant to H×W
            x = jnp.concatenate(
                [x.mean(axis=(1, 2)), x.max(axis=(1, 2))], axis=-1)
        x = nn.relu(nn.Dense(self.dense_units, dtype=self.dtype,
                             name="dense1")(x))
        if "score" in self.aux_heads:
            # score margin from the shared penultimate features,
            # unsquashed (a regression target in board points)
            s = nn.Dense(1, dtype=self.dtype, name="score_dense")(x)
            aux["score"] = s[:, 0].astype(jnp.float32)
        v = nn.Dense(1, dtype=self.dtype, name="dense2")(x)
        value = jnp.tanh(v[:, 0].astype(jnp.float32))
        return (value, aux) if with_aux else value


@neuralnet
class CNNValue(NeuralNetBase):
    """Scalar position evaluator.

    Defaults to the 49-plane ``VALUE_FEATURES`` input (the 48 policy
    planes + the player-color plane): komi breaks color symmetry, so
    the color plane is what lets the net value a position differently
    from its color-swapped mirror.
    """

    def __init__(self, feature_list=VALUE_FEATURES, **kwargs):
        # resolve the head NOW so every saved spec records it
        # explicitly (specs without it predate the kwarg and load as
        # the legacy dense head via migrate_spec)
        kwargs.setdefault("head", default_value_head())
        super().__init__(feature_list, **kwargs)

    @staticmethod
    def create_network(board: int = 19, input_planes: int = 49,
                       layers: int = 12, filters_per_layer: int = 128,
                       filter_width_1: int = 5, filter_width_K: int = 3,
                       dense_units: int = 256, head: str = "fcn",
                       head_filters: int = 32,
                       aux_heads=(), trunk_pool: int = 0) -> ValueNet:
        allowed = {"ownership", "score"}
        if not set(aux_heads) <= allowed:
            raise ValueError(
                f"unknown aux heads {sorted(set(aux_heads) - allowed)}"
                f"; supported: {sorted(allowed)}")
        return ValueNet(board=board, input_planes=input_planes,
                        layers=layers,
                        filters_per_layer=filters_per_layer,
                        filter_width_1=filter_width_1,
                        filter_width_K=filter_width_K,
                        dense_units=dense_units, head=head,
                        head_filters=head_filters,
                        aux_heads=tuple(aux_heads),
                        trunk_pool=trunk_pool)

    @classmethod
    def migrate_spec(cls, spec: dict) -> dict:
        """Checkpoint migration: value specs written before the
        ``head`` kwarg existed were trained with the size-locked
        flattened head — load them as such."""
        spec.setdefault("kwargs", {}).setdefault("head", "dense")
        return spec

    def size_generic(self) -> bool:
        return self.module.head == "fcn"

    def _symmetric_spec(self):
        """The scalar value needs no inverse mapping — plain mean."""
        return None, None

    def eval_state(self, state, symmetric: bool = False) -> float:
        """Expected outcome of one state from the player to move's
        perspective, in [-1, 1]."""
        return float(self.batch_eval_state([state], symmetric)[0])

    def batch_eval_state(self, states,
                         symmetric: bool = False) -> np.ndarray:
        planes = self._states_to_planes(self._as_state_list(states))
        return self.values_from_planes(planes, symmetric=symmetric)

    def values_from_planes(self, planes,
                           symmetric: bool = False) -> np.ndarray:
        """Forward from already-encoded planes (encode-sharing seam;
        see ``PointPolicyEval.dists_from_planes``)."""
        planes, b = self._pad_bucket(planes)  # stable compiled shapes
        fwd = self.forward_symmetric if symmetric else self.forward
        return np.asarray(fwd(planes))[:b]

    def forward_aux(self, planes):
        """Jitted apply returning ``(value [B], {head: pred})`` —
        the training-side entry for the auxiliary heads (the plain
        :meth:`forward` keeps the search-side value-only contract)."""
        if getattr(self, "_apply_aux", None) is None:
            self._apply_aux = jax.jit(functools.partial(
                self.module.apply, with_aux=True))
        return self._apply_aux(self.params, planes)


def with_aux_heads(net: CNNValue,
                   aux_heads=("ownership", "score"),
                   seed: int = 0) -> CNNValue:
    """A copy of ``net`` with auxiliary heads grafted on: trunk and
    value-head params are the TRAINED ones (by value, not reference),
    the new heads initialize fresh from ``seed``. The upgrade path for
    a checkpoint that predates the aux heads — the value output is
    bit-identical to ``net``'s, only the aux predictions start
    untrained."""
    kwargs = dict(net.spec_kwargs)
    kwargs["aux_heads"] = tuple(aux_heads)
    grown = CNNValue(net.feature_list, board=net.board, seed=seed,
                     **kwargs)

    def merge(new, old):
        if isinstance(new, dict):
            return {k: merge(v, old[k]) if k in old else v
                    for k, v in new.items()}
        return old

    grown.params = jax.tree.map(
        jnp.asarray,
        merge(serialization.to_state_dict(grown.params),
              serialization.to_state_dict(net.params)))
    return grown
