"""Value network: position → expected outcome in [-1, 1].

Parity: ``AlphaGo/models/value.py::CNNValue`` (same conv trunk as the
policy + 1×1 conv + ``Dense(256, relu)`` + ``Dense(1, tanh)``;
``eval_state``; SURVEY.md §2 "Value net"). NHWC bfloat16 trunk, float32
head, scalar per position.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from rocalphago_tpu.features import VALUE_FEATURES
from rocalphago_tpu.models.nn_util import ConvTrunk, NeuralNetBase, neuralnet


class ValueNet(nn.Module):
    """Conv trunk → 1×1 conv → Dense(256) → tanh scalar ``[B]``."""

    board: int = 19
    input_planes: int = 49
    layers: int = 12
    filters_per_layer: int = 128
    filter_width_1: int = 5
    filter_width_K: int = 3
    dense_units: int = 256
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = ConvTrunk(layers=self.layers,
                      filters_per_layer=self.filters_per_layer,
                      filter_width_1=self.filter_width_1,
                      filter_width_K=self.filter_width_K,
                      dtype=self.dtype, name="trunk")(x)
        x = nn.Conv(1, (1, 1), padding="SAME", dtype=self.dtype,
                    name="head_conv")(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.dense_units, dtype=self.dtype,
                             name="dense1")(x))
        v = nn.Dense(1, dtype=self.dtype, name="dense2")(x)
        return jnp.tanh(v[:, 0].astype(jnp.float32))


@neuralnet
class CNNValue(NeuralNetBase):
    """Scalar position evaluator.

    Defaults to the 49-plane ``VALUE_FEATURES`` input (the 48 policy
    planes + the player-color plane): komi breaks color symmetry, so
    the color plane is what lets the net value a position differently
    from its color-swapped mirror.
    """

    def __init__(self, feature_list=VALUE_FEATURES, **kwargs):
        super().__init__(feature_list, **kwargs)

    @staticmethod
    def create_network(board: int = 19, input_planes: int = 49,
                       layers: int = 12, filters_per_layer: int = 128,
                       filter_width_1: int = 5, filter_width_K: int = 3,
                       dense_units: int = 256) -> ValueNet:
        return ValueNet(board=board, input_planes=input_planes,
                        layers=layers,
                        filters_per_layer=filters_per_layer,
                        filter_width_1=filter_width_1,
                        filter_width_K=filter_width_K,
                        dense_units=dense_units)

    def _symmetric_spec(self):
        """The scalar value needs no inverse mapping — plain mean."""
        return None, None

    def eval_state(self, state, symmetric: bool = False) -> float:
        """Expected outcome of one state from the player to move's
        perspective, in [-1, 1]."""
        return float(self.batch_eval_state([state], symmetric)[0])

    def batch_eval_state(self, states,
                         symmetric: bool = False) -> np.ndarray:
        planes = self._states_to_planes(self._as_state_list(states))
        return self.values_from_planes(planes, symmetric=symmetric)

    def values_from_planes(self, planes,
                           symmetric: bool = False) -> np.ndarray:
        """Forward from already-encoded planes (encode-sharing seam;
        see ``PointPolicyEval.dists_from_planes``)."""
        planes, b = self._pad_bucket(planes)  # stable compiled shapes
        fwd = self.forward_symmetric if symmetric else self.forward
        return np.asarray(fwd(planes))[:b]
