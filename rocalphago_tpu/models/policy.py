"""SL/RL policy network.

Parity: ``AlphaGo/models/policy.py::CNNPolicy`` (``create_network`` with
``layers=12, filters_per_layer=128..192, filter_width_1=5,
filter_width_K=3``, conv trunk + 1×1 conv + per-position bias + softmax
over board points; ``eval_state`` / ``batch_eval_state`` /
``_select_moves_and_normalize``; SURVEY.md §2 "SL policy net").

TPU-native design: NHWC bfloat16 convs (MXU-friendly), logits returned
(softmax fused into the loss / sampling site), per-position bias as a
plain ``[N]`` parameter. The output space is the ``size²`` board
points; pass is handled at the agent layer, as in the reference.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from rocalphago_tpu.engine import jaxgo, pygo
from rocalphago_tpu.models.nn_util import (
    NeuralNetBase,
    legal_moves_mask_host,
    masked_probs,
    neuralnet,
)


class PolicyNet(nn.Module):
    """Conv trunk → 1×1 conv → per-position bias → logits ``[B, N]``."""

    board: int = 19
    input_planes: int = 48
    layers: int = 12
    filters_per_layer: int = 128
    filter_width_1: int = 5
    filter_width_K: int = 3
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(self.dtype)
        for i in range(self.layers - 1):
            w = self.filter_width_1 if i == 0 else self.filter_width_K
            x = nn.Conv(self.filters_per_layer, (w, w), padding="SAME",
                        dtype=self.dtype, name=f"conv{i + 1}")(x)
            x = nn.relu(x)
        x = nn.Conv(1, (1, 1), padding="SAME", dtype=self.dtype,
                    name=f"conv{self.layers}")(x)
        n = self.board * self.board
        logits = x.reshape((x.shape[0], n)).astype(jnp.float32)
        bias = self.param("position_bias", nn.initializers.zeros, (n,))
        return logits + bias


@neuralnet
class CNNPolicy(NeuralNetBase):
    """Move-probability network over board points."""

    @staticmethod
    def create_network(board: int = 19, input_planes: int = 48,
                       layers: int = 12, filters_per_layer: int = 128,
                       filter_width_1: int = 5,
                       filter_width_K: int = 3) -> PolicyNet:
        return PolicyNet(board=board, input_planes=input_planes,
                         layers=layers,
                         filters_per_layer=filters_per_layer,
                         filter_width_1=filter_width_1,
                         filter_width_K=filter_width_K)

    # -------------------------------------------------- host-facing eval

    def eval_state(self, state, moves=None):
        """Distribution over legal moves of one state →
        ``[((x, y), prob), ...]`` (the reference's
        ``_select_moves_and_normalize`` semantics). ``moves`` optionally
        restricts the support."""
        return self.batch_eval_state([state], [moves] if moves else None)[0]

    def batch_eval_state(self, states, moves_lists=None):
        """Lockstep evaluation of many states (one device call)."""
        states = self._as_state_list(states)
        planes = self._states_to_planes(states)
        logits = np.asarray(self.forward(planes))
        out = []
        for i, state in enumerate(states):
            size = state.size if isinstance(state, pygo.GameState) \
                else self.board
            legal = self._legal_for(state)
            if moves_lists is not None and moves_lists[i] is not None:
                allowed = np.zeros_like(legal)
                for (x, y) in moves_lists[i]:
                    allowed[x * size + y] = True
                legal = legal & allowed
            probs = np.asarray(masked_probs(
                logits[i][None], jnp.asarray(legal[None])))[0]
            out.append([((p // size, p % size), float(probs[p]))
                        for p in np.flatnonzero(legal)])
        return out

    def _legal_for(self, state) -> np.ndarray:
        if isinstance(state, pygo.GameState):
            return legal_moves_mask_host(state)
        mask = np.asarray(jaxgo.legal_mask(self.cfg, state))
        return mask[:-1]
