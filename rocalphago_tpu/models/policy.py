"""SL/RL policy network.

Parity: ``AlphaGo/models/policy.py::CNNPolicy`` (``create_network`` with
``layers=12, filters_per_layer=128..192, filter_width_1=5,
filter_width_K=3``, conv trunk + 1×1 conv + per-position bias + softmax
over board points; ``eval_state`` / ``batch_eval_state`` /
``_select_moves_and_normalize``; SURVEY.md §2 "SL policy net").

TPU-native design: NHWC bfloat16 convs (MXU-friendly), logits returned
(softmax fused into the loss / sampling site), per-position bias as a
plain ``[N]`` parameter. The output space is the ``size²`` board
points; pass is handled at the agent layer, as in the reference.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from rocalphago_tpu.features import DEFAULT_FEATURES
from rocalphago_tpu.models.nn_util import (
    ConvTrunk,
    NeuralNetBase,
    PointHead,
    PointPolicyEval,
    neuralnet,
)


class PolicyNet(nn.Module):
    """Conv trunk → point head → logits ``[B, N]``.

    ``head="fcn"`` (default): pure 1×1-conv head — no parameter shape
    depends on the board, so one checkpoint applies at any size.
    ``head="bias"``: the legacy per-position learned bias (size-
    locked); pre-multisize specs load as this."""

    board: int = 19
    input_planes: int = 48
    layers: int = 12
    filters_per_layer: int = 128
    filter_width_1: int = 5
    filter_width_K: int = 3
    head: str = "fcn"
    trunk_pool: int = 0
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = ConvTrunk(layers=self.layers,
                      filters_per_layer=self.filters_per_layer,
                      filter_width_1=self.filter_width_1,
                      filter_width_K=self.filter_width_K,
                      global_pool=self.trunk_pool,
                      dtype=self.dtype, name="trunk")(x)
        return PointHead(head=self.head, dtype=self.dtype,
                         name="head")(x)


@neuralnet
class CNNPolicy(PointPolicyEval, NeuralNetBase):
    """Move-probability network over board points. Host-facing
    evaluation (``eval_state`` / ``batch_eval_state`` / symmetry
    ensembling) comes from :class:`PointPolicyEval`, shared with the
    rollout net."""

    def __init__(self, feature_list=DEFAULT_FEATURES, **kwargs):
        kwargs.setdefault("head", "fcn")   # recorded in saved specs
        super().__init__(feature_list, **kwargs)

    @staticmethod
    def create_network(board: int = 19, input_planes: int = 48,
                       layers: int = 12, filters_per_layer: int = 128,
                       filter_width_1: int = 5,
                       filter_width_K: int = 3,
                       head: str = "fcn",
                       trunk_pool: int = 0) -> PolicyNet:
        return PolicyNet(board=board, input_planes=input_planes,
                         layers=layers,
                         filters_per_layer=filters_per_layer,
                         filter_width_1=filter_width_1,
                         filter_width_K=filter_width_K, head=head,
                         trunk_pool=trunk_pool)

    @classmethod
    def migrate_spec(cls, spec: dict) -> dict:
        """Policy specs written before the ``head`` kwarg carried the
        per-position bias param — load them as the legacy head."""
        spec.setdefault("kwargs", {}).setdefault("head", "bias")
        return spec

    def size_generic(self) -> bool:
        return self.module.head == "fcn"
