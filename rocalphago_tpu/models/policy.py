"""SL/RL policy network.

Parity: ``AlphaGo/models/policy.py::CNNPolicy`` (``create_network`` with
``layers=12, filters_per_layer=128..192, filter_width_1=5,
filter_width_K=3``, conv trunk + 1×1 conv + per-position bias + softmax
over board points; ``eval_state`` / ``batch_eval_state`` /
``_select_moves_and_normalize``; SURVEY.md §2 "SL policy net").

TPU-native design: NHWC bfloat16 convs (MXU-friendly), logits returned
(softmax fused into the loss / sampling site), per-position bias as a
plain ``[N]`` parameter. The output space is the ``size²`` board
points; pass is handled at the agent layer, as in the reference.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from rocalphago_tpu.engine import jaxgo, pygo
from rocalphago_tpu.models.nn_util import (
    ConvTrunk,
    NeuralNetBase,
    PointHead,
    legal_moves_mask_host,
    masked_probs,
    neuralnet,
)


class PolicyNet(nn.Module):
    """Conv trunk → 1×1 conv → per-position bias → logits ``[B, N]``."""

    board: int = 19
    input_planes: int = 48
    layers: int = 12
    filters_per_layer: int = 128
    filter_width_1: int = 5
    filter_width_K: int = 3
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = ConvTrunk(layers=self.layers,
                      filters_per_layer=self.filters_per_layer,
                      filter_width_1=self.filter_width_1,
                      filter_width_K=self.filter_width_K,
                      dtype=self.dtype, name="trunk")(x)
        return PointHead(board=self.board, dtype=self.dtype,
                         name="head")(x)


@neuralnet
class CNNPolicy(NeuralNetBase):
    """Move-probability network over board points."""

    @staticmethod
    def create_network(board: int = 19, input_planes: int = 48,
                       layers: int = 12, filters_per_layer: int = 128,
                       filter_width_1: int = 5,
                       filter_width_K: int = 3) -> PolicyNet:
        return PolicyNet(board=board, input_planes=input_planes,
                         layers=layers,
                         filters_per_layer=filters_per_layer,
                         filter_width_1=filter_width_1,
                         filter_width_K=filter_width_K)

    # ------------------------------------------------ symmetry ensemble

    def _symmetric_spec(self):
        """Inverse-map the point probabilities of each transform, then
        return ``log p̄`` — which behaves as logits under the masked
        softmax (renormalizing over the legal support recovers the
        averaged distribution)."""
        from rocalphago_tpu.training.symmetries import (
            inverse_transform_planes,
        )

        s = self.board

        def per_transform(logits, t):
            probs = jax.nn.softmax(logits, axis=-1)
            grids = probs.reshape(-1, s, s, 1)
            inv = jax.vmap(
                lambda g: inverse_transform_planes(g, t))(grids)
            return inv.reshape(-1, s * s)

        return per_transform, lambda mean: jnp.log(mean + 1e-30)

    # -------------------------------------------------- host-facing eval

    def eval_state(self, state, moves=None):
        """Distribution over legal moves of one state →
        ``[((x, y), prob), ...]`` (the reference's
        ``_select_moves_and_normalize`` semantics). ``moves`` optionally
        restricts the support (an empty list means "no moves");
        it must contain only legal moves — entries are NOT re-checked
        against the rules."""
        return self.batch_eval_state(
            [state], [moves] if moves is not None else None)[0]

    def batch_eval_state(self, states, moves_lists=None,
                         symmetric: bool = False):
        """Lockstep evaluation of many states: one forward and one
        masked-softmax device call for the whole batch.

        ``moves_lists[i]``, when given, becomes the support for state
        ``i`` verbatim (callers pass pre-computed legal/sensible
        subsets; re-deriving legality here would double the host cost
        of the search hot path). ``symmetric`` ensembles the forward
        over the 8 board symmetries (8× device work)."""
        states = self._as_state_list(states)
        planes = self._states_to_planes(states)
        logits = self.forward_symmetric(planes) if symmetric \
            else self.forward(planes)
        sizes, legal_rows = [], []
        for i, state in enumerate(states):
            size = state.size if isinstance(state, pygo.GameState) \
                else self.board
            if moves_lists is not None and moves_lists[i] is not None:
                # callers pass a subset of legal moves; building the
                # mask from it directly skips the per-point legality
                # scan (the expensive host computation)
                legal = np.zeros((size * size,), bool)
                for (x, y) in moves_lists[i]:
                    legal[x * size + y] = True
            else:
                legal = self._legal_for(state)
            sizes.append(size)
            legal_rows.append(legal)
        legal_b = np.stack(legal_rows)
        probs = np.asarray(masked_probs(logits, jnp.asarray(legal_b)))
        out = []
        for i, size in enumerate(sizes):
            out.append([((int(p) // size, int(p) % size),
                         float(probs[i, p]))
                        for p in np.flatnonzero(legal_b[i])])
        return out

    def _legal_for(self, state) -> np.ndarray:
        if isinstance(state, pygo.GameState):
            return legal_moves_mask_host(state)
        mask = np.asarray(jaxgo.legal_mask(self.cfg, state))
        return mask[:-1]
