"""Model base: JSON spec ⇄ network contract, registry, save/load.

Parity: ``AlphaGo/models/nn_util.py`` (``NeuralNetBase`` with JSON model
spec + HDF5 weights, the ``@neuralnet`` subclass registry, and the
per-position ``Bias`` Keras layer; SURVEY.md §2 "NN base / registry").
TPU-native differences:

* networks are Flax modules; parameters live in a pytree, serialized
  with Flax msgpack (``*.flax.msgpack``) instead of Keras HDF5 — but
  the load-bearing idea is kept: a small JSON spec records the class
  name, the **feature list** (the feature⇄network contract the GTP
  server needs to rebuild the encoder), and the architecture kwargs;
* the per-position learned bias is a parameter of the Flax modules
  (see ``policy.PolicyNet``), not a custom layer class;
* ``forward`` is a jitted apply (the reference compiled a raw
  ``K.function`` to bypass Keras predict overhead — ``jax.jit`` is the
  equivalent and better);
* evaluation is batched and device-resident; host-facing ``eval_state``
  accepts either a host ``pygo.GameState`` or a device ``GoState``.
"""

from __future__ import annotations

import functools
import json
import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from rocalphago_tpu.engine import jaxgo, pygo
from rocalphago_tpu.features import DEFAULT_FEATURES, Preprocess
from rocalphago_tpu.runtime.atomic import (
    atomic_write_bytes,
    atomic_write_json,
)

NEURALNETS: dict[str, type] = {}

# Model-spec format version, bumped whenever the flax param-tree layout
# changes (e.g. a trunk refactor renames conv1.. → trunk/*): loading a
# spec written under another format fails with a clear message instead
# of a deep deserialization error. Specs without the field predate the
# versioning and are assumed current.
SPEC_FORMAT = 2


class GlobalPoolBias(nn.Module):
    """KataGo-style global-pooling bias block ("Accelerating Self-Play
    Learning in Go", PAPERS.md): a 1×1 conv projects the trunk to
    ``pool_filters`` channels, their board-wide mean and max are
    concatenated (``2·pool_filters`` scalars — no spatial shape, so
    the block is size-generic like :class:`PointHead`), and a dense
    layer maps them back to one bias per trunk channel, broadcast over
    the board and added to the activations. This is what lets a net
    WITHOUT the handcrafted ladder planes see whole-board state (a
    running ladder is a global pattern a local conv stack cannot
    summarize) — the ladder-free configuration's architectural half."""

    pool_filters: int = 32
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        g = nn.Conv(self.pool_filters, (1, 1), padding="SAME",
                    dtype=self.dtype, name="pool_conv")(x)
        g = nn.relu(g)
        pooled = jnp.concatenate(
            [g.mean(axis=(1, 2)), g.max(axis=(1, 2))], axis=-1)
        bias = nn.Dense(x.shape[-1], dtype=self.dtype,
                        name="pool_dense")(pooled)
        return x + bias[:, None, None, :]


class ConvTrunk(nn.Module):
    """The AlphaGo conv trunk shared by policy and value nets: a
    width-``filter_width_1`` first layer then ``layers-2`` more of
    width ``filter_width_K``, ReLU, SAME padding (reference
    ``create_network`` trunk).

    ``global_pool=g > 0`` interleaves ``g`` :class:`GlobalPoolBias`
    blocks at evenly spaced depths (named ``gpool1..gpoolG``) — the
    ladder-free configuration's trunk. ``global_pool=0`` (default) is
    the exact pre-existing trunk: no extra modules, same param tree,
    bit-identical output."""

    layers: int = 12
    filters_per_layer: int = 128
    filter_width_1: int = 5
    filter_width_K: int = 3
    global_pool: int = 0
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(self.dtype)
        convs = self.layers - 1
        # conv index (1-based) -> pooling block ordinal after it
        pool_after = {(j + 1) * convs // (self.global_pool + 1): j + 1
                      for j in range(self.global_pool)}
        for i in range(convs):
            w = self.filter_width_1 if i == 0 else self.filter_width_K
            x = nn.Conv(self.filters_per_layer, (w, w), padding="SAME",
                        dtype=self.dtype, name=f"conv{i + 1}")(x)
            x = nn.relu(x)
            j = pool_after.get(i + 1)
            if j is not None:
                x = GlobalPoolBias(dtype=self.dtype,
                                   name=f"gpool{j}")(x)
        return x


class PointHead(nn.Module):
    """1×1 conv → flatten → float32 logits ``[B, N]`` over board
    points. ``N`` comes from the input's H×W at trace time, never from
    a stored board size.

    ``head="bias"`` (legacy) adds the reference's per-position learned
    bias (its custom Keras ``Bias`` layer, as a plain ``[N]``
    parameter) — which locks the checkpoint to one board size.
    ``head="fcn"`` (default) omits it, leaving only the conv's own
    channel bias, so the params apply at any H×W. A FRESH net is
    bit-identical either way: the position bias initializes to
    zeros."""

    head: str = "fcn"
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        n = x.shape[1] * x.shape[2]
        x = nn.Conv(1, (1, 1), padding="SAME", dtype=self.dtype,
                    name="conv")(x)
        logits = x.reshape((x.shape[0], n)).astype(jnp.float32)
        if self.head == "bias":
            bias = self.param("position_bias",
                              nn.initializers.zeros, (n,))
            logits = logits + bias
        return logits


def neuralnet(cls):
    """Class decorator registering a network for spec-based loading."""
    NEURALNETS[cls.__name__] = cls
    return cls


class NeuralNetBase:
    """Holds (module, params, preprocess) and the spec (de)serializer.

    Subclasses define ``create_network(**kwargs) -> flax.linen.Module``
    and evaluation helpers. ``self.spec_kwargs`` is everything needed to
    rebuild the module from JSON.
    """

    module = None  # flax module, set by subclass __init__

    def __init__(self, feature_list=DEFAULT_FEATURES, *, board: int = 19,
                 init_weights: bool = True, seed: int = 0, **kwargs):
        self.cfg = jaxgo.GoConfig(size=board)
        self.preprocess = Preprocess(feature_list, cfg=self.cfg)
        self.feature_list = tuple(feature_list)
        self.board = board
        self.spec_kwargs = dict(kwargs)
        self.module = self.create_network(
            board=board, input_planes=self.preprocess.output_dim, **kwargs)
        self.params = None
        if init_weights:
            dummy = jnp.zeros(
                (1, board, board, self.preprocess.output_dim), jnp.float32)
            self.params = self.module.init(jax.random.key(seed), dummy)
        self._apply = jax.jit(self.module.apply)

    # ------------------------------------------------------------- forward

    def forward(self, planes: jax.Array) -> jax.Array:
        """Jitted apply on encoded planes ``[B, s, s, F]``."""
        return self._apply(self.params, planes)

    def forward_symmetric(self, planes: jax.Array) -> jax.Array:
        """Dihedral-ensembled forward (the AlphaGo paper's
        evaluation-time symmetry averaging): run all 8 transforms,
        map each output back, average. Subclasses define the mapping
        via ``_symmetric_spec``."""
        if getattr(self, "_apply_sym", None) is None:
            per_transform, finalize = self._symmetric_spec()
            self._apply_sym = jax.jit(make_symmetric_forward(
                self.module.apply, per_transform, finalize))
        return self._apply_sym(self.params, planes)

    def _symmetric_spec(self):
        """(per_transform(out, t), finalize(mean)) for
        :func:`make_symmetric_forward`; override per output type."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support symmetry "
            "ensembling")

    def _states_to_planes(self, states) -> jax.Array:
        """Host ``pygo.GameState`` list / single device ``GoState`` /
        batched ``GoState`` / list of either → ``[B, s, s, F]``."""
        if isinstance(states, jaxgo.GoState):
            if states.board.ndim == 2:  # already batched
                return self.preprocess.states_to_tensor(states)
            return self.preprocess.state_to_tensor(states)
        if isinstance(states, pygo.GameState):
            states = [states]
        # host BFS labeling skipped per state; one compiled batched
        # fill reseeds the whole wave (hot path: MCTS leaf evaluation)
        any_pygo = any(not isinstance(s, jaxgo.GoState) for s in states)
        dev = [s if isinstance(s, jaxgo.GoState)
               else jaxgo.from_pygo(self.cfg, s, with_labels=False)
               for s in states]
        batched = jax.tree.map(lambda *xs: jnp.stack(xs), *dev)
        if any_pygo:
            batched = jaxgo.seed_labels(self.cfg, batched)
        return self.preprocess.states_to_tensor(batched)

    @staticmethod
    def _pad_bucket(planes: jax.Array, min_bucket: int = 8):
        """Pad the batch axis up to the next power-of-two bucket.

        Host-facing eval batch sizes vary call to call (MCTS waves
        dedup to different leaf counts, game batches shrink as games
        finish); without bucketing every first-seen size costs a full
        XLA compile of the forward — 20–40s on TPU. Returns
        ``(padded_planes, real_batch)``; callers slice outputs back to
        ``real_batch``."""
        b = planes.shape[0]
        bucket = min_bucket
        while bucket < b:
            bucket *= 2
        if bucket == b:
            return planes, b
        pad = jnp.zeros((bucket - b,) + planes.shape[1:], planes.dtype)
        return jnp.concatenate([planes, pad]), b

    @staticmethod
    def _as_state_list(states):
        """Normalize eval inputs to a list of single-game states
        (splits a batched ``GoState`` into per-game views)."""
        if isinstance(states, pygo.GameState):
            return [states]
        if isinstance(states, jaxgo.GoState):
            if states.board.ndim == 1:
                return [states]
            b = states.board.shape[0]
            return [jax.tree.map(lambda x: x[i], states) for i in range(b)]
        return list(states)

    # ------------------------------------------------------ spec save/load

    def save_model(self, json_file: str, weights_file: str | None = None):
        """Write the JSON spec (+ weights beside it unless given)."""
        spec = {
            "class": type(self).__name__,
            "format": SPEC_FORMAT,
            "feature_list": list(self.feature_list),
            "board": self.board,
            "kwargs": self.spec_kwargs,
        }
        if weights_file is None:
            weights_file = os.path.splitext(json_file)[0] + ".flax.msgpack"
        spec["weights_file"] = os.path.relpath(
            weights_file, os.path.dirname(json_file) or ".")
        # weights first, spec second: a crash between the two leaves a
        # stale-but-loadable spec, never a spec pointing at a missing
        # or half-written weights file
        self.save_weights(weights_file)
        atomic_write_json(json_file, spec)

    def save_weights(self, weights_file: str):
        # atomic tmp+fsync+rename: concurrent readers (multi-host
        # opponent pools waiting on snapshot visibility) and post-crash
        # resumes must never see a half-written msgpack
        atomic_write_bytes(weights_file,
                           serialization.to_bytes(self.params))

    def load_weights(self, weights_file: str):
        with open(weights_file, "rb") as f:
            data = f.read()
        try:
            self.params = serialization.from_bytes(self.params, data)
        except (ValueError, KeyError) as e:
            # surface pytree mismatches with the likely causes instead
            # of a bare msgpack error; don't over-claim which one it is
            raise ValueError(
                f"{weights_file} does not match this architecture's "
                "parameter tree: the file may belong to a different "
                "network class/size, be corrupt or truncated, or have "
                "been exported under an older param-tree layout "
                f"(current model-spec format {SPEC_FORMAT}). "
                f"Underlying error: {e}") from e

    @staticmethod
    def load_model(json_file: str) -> "NeuralNetBase":
        """Rebuild any registered network from its JSON spec."""
        with open(json_file) as f:
            spec = json.load(f)
        fmt = spec.get("format", SPEC_FORMAT)
        if fmt != SPEC_FORMAT:
            raise ValueError(
                f"{json_file} is model-spec format {fmt}, this build "
                f"reads format {SPEC_FORMAT}: its weights use an "
                "incompatible parameter-tree layout — re-export the "
                "model with the matching framework version")
        cls = NEURALNETS.get(spec.get("class"))
        if cls is None:
            raise ValueError(
                f"unknown network class {spec.get('class')!r}; "
                f"registered: {sorted(NEURALNETS)}")
        spec = cls.migrate_spec(spec)
        net = cls(tuple(spec["feature_list"]), board=int(spec["board"]),
                  **spec.get("kwargs", {}))
        weights = spec.get("weights_file")
        if weights:
            path = os.path.join(os.path.dirname(json_file) or ".", weights)
            net.load_weights(path)
        return net

    @classmethod
    def migrate_spec(cls, spec: dict) -> dict:
        """Hook for same-format checkpoint migration: adjust an older
        spec (in place is fine) before the network is rebuilt —
        e.g. value/policy specs written before the ``head`` kwarg
        existed load with the legacy size-locked head. Default:
        identity."""
        return spec

    # ---------------------------------------------------- multi-size

    def size_generic(self) -> bool:
        """Whether this net's PARAM tree holds no size-locked shapes,
        i.e. one pytree applies at any board size. Subclasses with an
        FCN head override; the conservative default is False."""
        return False

    def at_board(self, board: int) -> "NeuralNetBase":
        """A facade of this net at another board size SHARING this
        net's params (by reference, no copy): same class, features and
        architecture kwargs, fresh ``GoConfig``/``Preprocess``/jitted
        apply at ``board``. The multi-size seam: a
        :class:`~rocalphago_tpu.multisize.MultiSizePool` builds one
        facade per active size over one FCN checkpoint, and the
        curriculum hands params from one stage's facade to the next.

        Params stay SHARED — assigning ``facade.params`` later
        rebinds only that facade; callers that train through a facade
        must copy the updated tree back themselves."""
        if board == self.board:
            return self
        if not self.size_generic():
            raise ValueError(
                f"{type(self).__name__} at board {self.board} has "
                "size-locked params (legacy dense/bias head) and "
                f"cannot be re-sized to {board} — rebuild or retrain "
                "with the FCN head (see docs/MULTISIZE.md)")
        clone = type(self)(self.feature_list, board=board,
                           init_weights=False, **self.spec_kwargs)
        clone.params = self.params
        return clone

    @staticmethod
    def create_network(**kwargs):
        raise NotImplementedError


def make_symmetric_forward(apply_fn, per_transform=None, finalize=None):
    """``(params, planes [B,s,s,F]) -> ensembled output``: transform
    the batch by each of the 8 dihedral group elements, apply the net,
    map each output back with ``per_transform(out, t)``, average, then
    ``finalize(mean)``."""
    from rocalphago_tpu.training.symmetries import transform_planes

    def sym(params, planes):
        def one(t):
            tp = jax.vmap(lambda x: transform_planes(x, t))(planes)
            out = apply_fn(params, tp)
            return per_transform(out, t) if per_transform else out

        mean = jax.vmap(one)(jnp.arange(8)).mean(axis=0)
        return finalize(mean) if finalize else mean

    return sym


@functools.partial(jax.jit, static_argnames=("temperature_is_one",))
def masked_probs(logits: jax.Array, legal: jax.Array,
                 temperature: jax.Array | float = 1.0,
                 temperature_is_one: bool = False) -> jax.Array:
    """Softmax over legal board points only, with optional temperature
    (probability exponentiation ``p^(1/T)`` as in the reference's
    ``ProbabilisticPolicyPlayer``). ``legal`` is bool ``[B, N]`` over
    board points; all-illegal rows return zeros."""
    neg = jnp.finfo(logits.dtype).min
    masked = jnp.where(legal, logits, neg)
    if not temperature_is_one:
        masked = masked / temperature
    p = jax.nn.softmax(masked, axis=-1)
    p = jnp.where(legal, p, 0.0)
    denom = p.sum(axis=-1, keepdims=True)
    return jnp.where(denom > 0, p / jnp.maximum(denom, 1e-30), 0.0)


def legal_moves_mask_host(state: pygo.GameState) -> np.ndarray:
    """Bool [N] legality over board points for a host GameState
    (sensible moves excluded at the agent layer, not here)."""
    n = state.size * state.size
    mask = np.zeros((n,), bool)
    for (x, y) in state.get_legal_moves(include_eyes=True):
        mask[x * state.size + y] = True
    return mask


class PointPolicyEval:
    """Host-facing evaluation for nets whose output is logits over
    board points — shared by ``CNNPolicy`` and ``CNNRollout`` (the
    reference's ``eval_state`` / ``batch_eval_state`` /
    ``_select_moves_and_normalize`` surface). Mixed into a
    :class:`NeuralNetBase` subclass."""

    def _symmetric_spec(self):
        """Inverse-map the point probabilities of each transform, then
        return ``log p̄`` — which behaves as logits under the masked
        softmax (renormalizing over the legal support recovers the
        averaged distribution)."""
        from rocalphago_tpu.training.symmetries import (
            inverse_transform_planes,
        )

        s = self.board

        def per_transform(logits, t):
            probs = jax.nn.softmax(logits, axis=-1)
            grids = probs.reshape(-1, s, s, 1)
            inv = jax.vmap(
                lambda g: inverse_transform_planes(g, t))(grids)
            return inv.reshape(-1, s * s)

        return per_transform, lambda mean: jnp.log(mean + 1e-30)

    def eval_state(self, state, moves=None):
        """Distribution over legal moves of one state →
        ``[((x, y), prob), ...]`` (the reference's
        ``_select_moves_and_normalize`` semantics). ``moves`` optionally
        restricts the support (an empty list means "no moves");
        it must contain only legal moves — entries are NOT re-checked
        against the rules."""
        return self.batch_eval_state(
            [state], [moves] if moves is not None else None)[0]

    def batch_eval_state(self, states, moves_lists=None,
                         symmetric: bool = False):
        """Lockstep evaluation of many states: one forward and one
        masked-softmax device call for the whole batch.

        ``moves_lists[i]``, when given, becomes the support for state
        ``i`` verbatim (callers pass pre-computed legal/sensible
        subsets; re-deriving legality here would double the host cost
        of the search hot path). ``symmetric`` ensembles the forward
        over the 8 board symmetries (8× device work)."""
        states = self._as_state_list(states)
        return self.dists_from_planes(
            states, self._states_to_planes(states), moves_lists,
            symmetric=symmetric)

    def dists_from_planes(self, states, planes, moves_lists=None,
                          symmetric: bool = False):
        """As :meth:`batch_eval_state`, from already-encoded ``planes``
        — the seam that lets a caller encode ONCE and share the planes
        between nets (the MCTS wave's policy/value fusion: the 48-plane
        encode dominates wave cost, so paying it twice halves sims/s)."""
        planes, b = self._pad_bucket(planes)
        logits = self.forward_symmetric(planes) if symmetric \
            else self.forward(planes)
        sizes, legal_rows = [], []
        for i, state in enumerate(states):
            size = state.size if isinstance(state, pygo.GameState) \
                else self.board
            if moves_lists is not None and moves_lists[i] is not None:
                # callers pass a subset of legal moves; building the
                # mask from it directly skips the per-point legality
                # scan (the expensive host computation)
                legal = np.zeros((size * size,), bool)
                for (x, y) in moves_lists[i]:
                    legal[x * size + y] = True
            else:
                legal = self._legal_for(state)
            sizes.append(size)
            legal_rows.append(legal)
        legal_b = np.stack(legal_rows)
        if logits.shape[0] > b:      # padded rows: all-illegal → zeros
            legal_b = np.concatenate(
                [legal_b, np.zeros((logits.shape[0] - b,
                                    legal_b.shape[1]), bool)])
        probs = np.asarray(masked_probs(logits, jnp.asarray(legal_b)))
        out = []
        for i, size in enumerate(sizes):
            out.append([((int(p) // size, int(p) % size),
                         float(probs[i, p]))
                        for p in np.flatnonzero(legal_b[i])])
        return out

    def _legal_for(self, state) -> np.ndarray:
        if isinstance(state, pygo.GameState):
            return legal_moves_mask_host(state)
        mask = np.asarray(jaxgo.legal_mask(self.cfg, state))
        return mask[:-1]
