"""Neural networks (policy / value / rollout) + the JSON model-spec
registry. Parity: the reference's ``AlphaGo/models/`` (SURVEY.md §1 L3).
"""

from rocalphago_tpu.models.nn_util import (  # noqa: F401
    NEURALNETS,
    NeuralNetBase,
    masked_probs,
    neuralnet,
)
from rocalphago_tpu.models.policy import CNNPolicy, PolicyNet  # noqa: F401
from rocalphago_tpu.models.rollout import (  # noqa: F401
    ROLLOUT_FEATURES,
    CNNRollout,
    RolloutNet,
)
from rocalphago_tpu.models.value import CNNValue, ValueNet  # noqa: F401
