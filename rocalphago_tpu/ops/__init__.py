"""Pallas TPU kernels for the framework's hot primitives (opt-in;
the XLA formulations remain the defaults — see ops.labels)."""

from rocalphago_tpu.ops.chase import pallas_chase
from rocalphago_tpu.ops.labels import pallas_labels

__all__ = ["pallas_chase", "pallas_labels"]
