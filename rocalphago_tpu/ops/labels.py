"""Pallas TPU kernel: batched connected-component labeling.

The engine's hottest primitive is the whole-board flood fill behind
``jaxgo.compute_labels`` (group analysis for stepping, legality,
features, scoring). The XLA formulation is a convergence
``while_loop`` of min-propagation sweeps; this kernel is the
TPU-native alternative: one grid cell per board, the whole fixpoint
iteration running over a VMEM-resident board with zero HBM round
trips between sweeps.

Design notes (see ``/opt/skills/guides/pallas_guide.md``):

* the board is tiny (≤ 25×25), so each program holds it entirely in
  VMEM; the grid parallelizes over the batch;
* min-propagation uses pad + static-slice shifts — pure VPU vector
  ops; there are NO gathers (TPU vector units have no efficient
  arbitrary gather, so the pointer-jumping trick the XLA path uses is
  deliberately omitted here);
* the loop is a ``fori_loop`` with a STATIC trip count chosen so the
  result is provably exact: each sweep propagates the min label one
  step along group connectivity, the longest possible propagation
  chain is N-1 (a serpentine group filling the board), and the bound
  rounds up from there. No convergence check is needed — extra sweeps
  are idempotent.

The kernel is exact but OPT-IN: the default engine path stays on the
XLA ``while_loop`` (early exit usually wins on sparse boards, and the
attached TPU backend is experimental). ``benchmarks/bench_labels.py``
compares both; flipping the engine over is a one-line change in
``jaxgo.compute_labels`` if measurements favor the kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _sweeps_for(num_points: int) -> int:
    """Static sweep count that PROVES convergence: min labels advance
    ≥1 connectivity step per sweep and the longest chain is N-1."""
    return num_points


def _label_kernel(board_ref, out_ref, *, size: int, sweeps: int):
    n = size * size
    board = board_ref[...].reshape(size, size)
    stone = board != 0
    sentinel = jnp.int32(n)
    init = jnp.where(
        stone, jnp.arange(n, dtype=jnp.int32).reshape(size, size),
        sentinel)

    def shifted(x, dx, dy, fill):
        p = jnp.pad(x, 1, constant_values=fill)
        return p[1 + dx:1 + dx + size, 1 + dy:1 + dy + size]

    links = [(shifted(board, dx, dy, 0) == board) & stone
             for dx, dy in ((1, 0), (-1, 0), (0, 1), (0, -1))]

    def sweep(_, lab):
        for link, (dx, dy) in zip(links, ((1, 0), (-1, 0), (0, 1),
                                          (0, -1))):
            nb = shifted(lab, dx, dy, sentinel)
            lab = jnp.minimum(lab, jnp.where(link, nb, sentinel))
        return lab

    lab = jax.lax.fori_loop(0, sweeps, sweep, init)
    out_ref[...] = lab.reshape(1, -1)


@functools.partial(jax.jit, static_argnames=("size", "interpret"))
def pallas_labels(boards: jax.Array, size: int,
                  interpret: bool = False) -> jax.Array:
    """Connected-component root (min flat index) per point for a BATCH
    of boards: int8 ``[B, N]`` → int32 ``[B, N]`` (``N`` = sentinel
    for empty points). Semantics identical to
    ``jaxgo.compute_labels`` vmapped over the batch.

    ``interpret=True`` runs the kernel in the Pallas interpreter — the
    CI path on CPU-only hosts (tests/test_ops.py differential-checks
    it against the XLA implementation).
    """
    batch, n = boards.shape
    if n != size * size:
        raise ValueError(f"boards have {n} points, size² is {size * size}")
    kernel = functools.partial(_label_kernel, size=size,
                               sweeps=_sweeps_for(n))
    return pl.pallas_call(
        kernel,
        grid=(batch,),
        in_specs=[pl.BlockSpec((1, n), lambda b: (b, 0))],
        out_specs=pl.BlockSpec((1, n), lambda b: (b, 0)),
        out_shape=jax.ShapeDtypeStruct((batch, n), jnp.int32),
        interpret=interpret,
    )(boards)
