"""Region labelling ops: the Pallas TPU labeling kernel, plus the
terminal ownership/score labeller built on the same flood-fill
(:func:`terminal_labels` — the auxiliary-target source for the
KataGo-style ownership/score heads in ``models/value.py``).

Pallas TPU kernel: batched connected-component labeling.

The engine's hottest primitive is the whole-board flood fill behind
``jaxgo.compute_labels`` (group analysis for stepping, legality,
features, scoring). The XLA formulation is a convergence
``while_loop`` of min-propagation sweeps; this kernel is the
TPU-native alternative: 8 boards per grid cell, the whole fixpoint
iteration running over VMEM-resident boards with zero HBM round
trips between sweeps.

Design notes:

* the board is tiny (≤ 25×25), so each program holds it entirely in
  VMEM; the grid parallelizes over the batch, 8 boards per cell;
* min-propagation uses pad + static-slice shifts — pure VPU vector
  ops; there are NO gathers (TPU vector units have no efficient
  arbitrary gather, so the pointer-jumping trick the XLA path uses is
  deliberately omitted here);
* the loop is a ``while_loop`` with an in-kernel convergence check
  capped at a STATIC sweep bound that proves exactness: each sweep
  propagates the min label ≥1 step along group connectivity and the
  longest possible chain is N-1 (a serpentine group filling the
  board). The early exit is per grid cell — a hard board stalls only
  its own 8-board block, unlike the XLA path's batch-global fixpoint.

The kernel is exact but OPT-IN: the default engine path stays on the
XLA ``while_loop`` (early exit usually wins on sparse boards, and the
attached TPU backend is experimental). ``benchmarks/bench_labels.py``
compares both; flipping the engine over is a one-line change in
``jaxgo.compute_labels`` if measurements favor the kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def terminal_labels(cfg, state):
    """Auxiliary training targets from one TERMINAL position:
    ``(ownership int8 [N], score float32)``, black-positive.

    Ownership is the area-scoring verdict per point: a stone's own
    color, and for empty points the color of the single-color region
    they sit in (+1 black, -1 white, 0 contested/neutral — dame and
    seki-shared regions). Score is ``black − white`` with the komi
    inside white, so ``sign(score) == jaxgo.winner`` by construction
    — the parity the tests pin. Same flood-fill machinery as
    :func:`jaxgo.area_scores` run on the empty graph; one game's
    labels (vmap over a batch at the call site, e.g. the zero loop's
    game-end labelling).
    """
    from rocalphago_tpu.engine.jaxgo import (BLACK, WHITE,
                                             compute_labels,
                                             neighbors_for)

    n = cfg.num_points
    nbrs = neighbors_for(cfg.size)
    board = state.board
    empty = board == 0

    # label empty regions: treat empty as the "color" (area_scores'
    # exact construction, kept in step with it by the parity test)
    region = compute_labels(
        cfg, jnp.where(empty, jnp.int8(9), jnp.int8(0)))
    board_pad = jnp.concatenate(
        [board, jnp.zeros((1,), board.dtype)])
    nbr_color = board_pad[nbrs]
    touches_b_pt = empty & (nbr_color == BLACK).any(axis=1)
    touches_w_pt = empty & (nbr_color == WHITE).any(axis=1)
    touches_b = jnp.zeros((n + 1,), jnp.bool_).at[region].max(
        touches_b_pt)
    touches_w = jnp.zeros((n + 1,), jnp.bool_).at[region].max(
        touches_w_pt)

    terr_b = empty & touches_b[region] & ~touches_w[region]
    terr_w = empty & touches_w[region] & ~touches_b[region]
    ownership = (board.astype(jnp.int8)
                 + terr_b.astype(jnp.int8) - terr_w.astype(jnp.int8))
    black = (board == BLACK).sum() + terr_b.sum()
    white = (board == WHITE).sum() + terr_w.sum()
    score = (black.astype(jnp.float32)
             - white.astype(jnp.float32) - cfg.komi)
    return ownership, score


def _sweeps_for(num_points: int) -> int:
    """Static sweep count that PROVES convergence: min labels advance
    ≥1 connectivity step per sweep and the longest chain is N-1."""
    return num_points


# Boards packed per grid cell. NOT a tiling requirement (the block's
# trailing dims are the full (size, size) board, which Mosaic accepts
# as-is); packing amortizes per-cell launch overhead — measured 1.6×
# over one board per cell on a real v5e chip at batch 256.
_BOARDS_PER_CELL = 8


def _label_kernel(board_ref, out_ref, *, size: int, sweeps: int):
    n = size * size
    # (bpc, size, size); no reshapes in-kernel, and widen int8 → int32
    # immediately — Mosaic lacks sub-word vector compares on this target
    board = board_ref[...].astype(jnp.int32)
    stone = board != 0
    sentinel = jnp.int32(n)
    iota = (jax.lax.broadcasted_iota(jnp.int32, (1, size, size), 1) * size
            + jax.lax.broadcasted_iota(jnp.int32, (1, size, size), 2))
    init = jnp.where(stone, iota, sentinel)

    def shifted(x, dx, dy, fill):
        p = jnp.pad(x, ((0, 0), (1, 1), (1, 1)), constant_values=fill)
        return p[:, 1 + dx:1 + dx + size, 1 + dy:1 + dy + size]

    links = [(shifted(board, dx, dy, 0) == board) & stone
             for dx, dy in ((1, 0), (-1, 0), (0, 1), (0, -1))]

    def sweep(lab):
        for link, (dx, dy) in zip(links, ((1, 0), (-1, 0), (0, 1),
                                          (0, -1))):
            nb = shifted(lab, dx, dy, sentinel)
            lab = jnp.minimum(lab, jnp.where(link, nb, sentinel))
        return lab

    # Fixpoint with an in-kernel convergence check: the ``sweeps``
    # static bound guarantees exactness, the early exit makes sparse
    # boards (the common case) converge in ~size sweeps instead of N.
    # The check is per grid cell — a hard board only stalls its own
    # 8-board block, not the whole batch the way the XLA path's
    # batch-global while_loop does.
    def cond(state):
        i, lab, changed = state
        return changed & (i < sweeps)

    def body(state):
        i, lab, _ = state
        new = sweep(lab)
        return i + 1, new, jnp.any(new != lab)

    _, lab, _ = jax.lax.while_loop(
        cond, body, (jnp.int32(0), init, jnp.bool_(True)))
    out_ref[...] = lab


@functools.partial(jax.jit, static_argnames=("size", "interpret"))
def pallas_labels(boards: jax.Array, size: int,
                  interpret: bool = False) -> jax.Array:
    """Connected-component root (min flat index) per point for a BATCH
    of boards: int8 ``[B, N]`` → int32 ``[B, N]`` (``N`` = sentinel
    for empty points). Semantics identical to
    ``jaxgo.compute_labels`` vmapped over the batch.

    ``interpret=True`` runs the kernel in the Pallas interpreter — the
    CI path on CPU-only hosts (tests/test_ops.py differential-checks
    it against the XLA implementation).
    """
    batch, n = boards.shape
    if n != size * size:
        raise ValueError(f"boards have {n} points, size² is {size * size}")
    bpc = _BOARDS_PER_CELL
    padded = -batch % bpc
    if padded:
        boards = jnp.pad(boards, ((0, padded), (0, 0)))
    grids = boards.reshape(batch + padded, size, size)
    kernel = functools.partial(_label_kernel, size=size,
                               sweeps=_sweeps_for(n))
    out = pl.pallas_call(
        kernel,
        grid=((batch + padded) // bpc,),
        in_specs=[pl.BlockSpec((bpc, size, size), lambda b: (b, 0, 0))],
        out_specs=pl.BlockSpec((bpc, size, size), lambda b: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((batch + padded, size, size),
                                       jnp.int32),
        interpret=interpret,
    )(grids)
    return out.reshape(batch + padded, n)[:batch]
