"""Pallas TPU kernel: per-lane ladder-chase reading.

The ladder chase (``features/ladders.py::_chase``) is the framework's
hottest loop: a ``lax.while_loop`` whose trip count is the rung length
of the read. Under the encoder's vmap the XLA formulation runs ONE
lockstep loop over every (board × chase-slot) lane — one 40-rung
ladder anywhere in the batch makes every lane pay 40 trips. This
kernel gives each lane its OWN loop in its own grid cell: inactive
lanes exit after one trip, boards in VMEM, zero HBM traffic between
rungs. Lanes arrive pre-gated: since the encode-path overhaul, the
planes pool BOTH features' slot-gated candidates into one lane set
(``ladders.ladder_planes``) — lanes mix capture (opponent) and escape
(own) prey, which this kernel has always supported because each
lane's prey color is read from its own board (``prey_color`` below).

Mosaic-dictated design (lessons from ``ops/labels.py`` on real v5e:
no in-kernel reshapes, no sub-word vector compares, no gathers or
scatters):

* every per-lane array is FLAT ``(1, 1, N)`` (``N = size²``) — block
  shape equals the trailing array dims, so any ``N`` is accepted;
  neighbor access is pad+slice shifts along the flat axis (±1 with a
  column-boundary mask, ±size needs none);
* per-GROUP quantities (the liberty-count table the response algebra
  needs) use broadcast ``(1, N, N)`` root×point tables reduced along
  one axis — the scatter-free formulation of ``group_data``'s
  dedup-scatter (an empty point is a liberty of root ρ iff any of its
  4 neighbors has label ρ; the OR over directions dedups for free);
* scalars (points, roots, outcomes) live on the scalar core: value
  extraction is ``(x * onehot).sum()``, first-set-index is a masked
  min over iota.

Semantics are IDENTICAL to the XLA ``_chase`` — same carried
incremental min-root labeling, same 2-ply rung (chaser option scored
by the forced escaper response), same tie-breaks (first liberty by
flat index, option pick ``o1 <= o2``, response pick ``L1 >= L2``) —
and ``tests/test_ops.py`` differential-checks the two lane-by-lane on
random chase openings. Opt-in like the labels kernel: the XLA path
stays the default until real-chip measurements favor this one.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# per-option ladder outcomes, ordered so the chaser minimises
# (mirror of features/ladders.py)
_CAPTURED, _CONTINUE, _ESCAPED = 0, 1, 2


def _chase_kernel(board_ref, labels_ref, prey_ref, out_ref,
                  *maybe_core_ref, size: int, depth: int,
                  collect_core: bool = False):
    n = size * size
    SENT = jnp.int32(n)           # empty/off-board label sentinel
    BIG = jnp.int32(4 * n)        # "no point" index sentinel

    board0 = board_ref[...].astype(jnp.int32)    # (1,1,N)
    labels0 = labels_ref[...].astype(jnp.int32)  # (1,1,N)
    prey_oh = prey_ref[...].astype(jnp.int32)    # (1,1,N) one-hot / zeros

    iota_e = jax.lax.broadcasted_iota(jnp.int32, (1, 1, n), 2)
    iota_r = jax.lax.broadcasted_iota(jnp.int32, (1, n, 1), 1)
    col = iota_e % size
    DIRS = (1, -1, size, -size)

    def nbr(x, d, fill):
        """out[e] = x[e+d] (the value at e's neighbor), ``fill``
        off-board. ±1 masks the column wrap; ±size pads off the end."""
        f = jnp.asarray(fill, x.dtype)
        if d == 1:
            v = jnp.pad(x, ((0, 0), (0, 0), (0, 1)),
                        constant_values=fill)[..., 1:]
            return jnp.where(col == size - 1, f, v)
        if d == -1:
            v = jnp.pad(x, ((0, 0), (0, 0), (1, 0)),
                        constant_values=fill)[..., :n]
            return jnp.where(col == 0, f, v)
        if d == size:
            return jnp.pad(x, ((0, 0), (0, 0), (0, size)),
                           constant_values=fill)[..., size:]
        return jnp.pad(x, ((0, 0), (0, 0), (size, 0)),
                       constant_values=fill)[..., :n]

    def dilate(m):
        return (m | nbr(m, 1, False) | nbr(m, -1, False)
                | nbr(m, size, False) | nbr(m, -size, False))

    def scal(x, oh):
        """Scalar value of int32 field ``x`` at one-hot ``oh``."""
        return (x * oh).sum()

    def sbool(m, oh):
        """Scalar: is bool field ``m`` set at one-hot ``oh``."""
        return scal(m.astype(jnp.int32), oh) > 0

    def min_idx(mask):
        return jnp.where(mask, iota_e, BIG).min()

    def onehot(pt):
        return (iota_e == pt).astype(jnp.int32)

    def isum(m):
        return m.astype(jnp.int32).sum()

    def valid_dir(pt, d):
        """Is pt's neighbor in direction d on the board (pt itself may
        be BIG = nowhere, which yields garbage safely gated off by the
        caller's enables)."""
        if d == 1:
            return (pt % size) < size - 1
        if d == -1:
            return (pt % size) > 0
        if d == size:
            return pt < n - size
        return pt >= size

    def libs_table(board, labels):
        """(1,N,1) distinct-liberty count per root."""
        empty = board == 0
        adj = jnp.zeros((1, n, n), jnp.bool_)
        for d in DIRS:
            adj = adj | (nbr(labels, d, SENT) == iota_r)
        return (adj & empty).astype(jnp.int32).sum(axis=2, keepdims=True)

    def table_at(table, root):
        """Scalar table[root] (0 for root == SENT/garbage ≥ n is fine:
        no iota_r row matches)."""
        return (table * (iota_r == root).astype(jnp.int32)).sum()

    prey_color = scal(board0, prey_oh)           # ±1, or 0 if disabled
    chaser = -prey_color

    def place(board, labels, libsT, pt, color):
        """Chaser-move legality + captures at scalar ``pt`` — mirror
        of ladders._place on the carried analysis."""
        oh = onehot(pt)
        cap = jnp.zeros((1, 1, n), jnp.bool_)
        has_empty = jnp.bool_(False)
        own_safe = jnp.bool_(False)
        any_cap = jnp.bool_(False)
        for d in DIRS:
            vd = valid_dir(pt, d)
            qc = scal(nbr(board, d, 0), oh)
            qr = scal(nbr(labels, d, SENT), oh)
            qlibs = table_at(libsT, qr)
            cap_d = vd & (qc == -color) & (qlibs == 1)
            cap = cap | jnp.where(cap_d, labels == qr, False)
            has_empty = has_empty | (vd & (qc == 0) & (qr == SENT))
            own_safe = own_safe | (vd & (qc == color) & (qlibs >= 2))
            any_cap = any_cap | cap_d
        ok = (scal(board, oh) == 0) & (has_empty | own_safe | any_cap)
        return ok, cap & ok

    def relabel(board, labels, pt, color, cap, enabled):
        """Incremental min-root relabel after placing ``color`` at
        ``pt`` and removing ``cap`` — mirror of ladders._relabel_place."""
        oh = onehot(pt)
        ohb = oh > 0
        min_r = BIG
        merged = jnp.zeros((1, 1, n), jnp.bool_)
        for d in DIRS:
            vd = valid_dir(pt, d)
            qc = scal(nbr(board, d, 0), oh)
            qr = scal(nbr(labels, d, SENT), oh)
            same_d = vd & (qc == color)
            min_r = jnp.minimum(min_r, jnp.where(same_d, qr, BIG))
            merged = merged | (same_d & (labels == qr))
        new_root = jnp.minimum(min_r, pt)
        labels1 = jnp.where(merged | ohb, new_root, labels)
        labels1 = jnp.where(cap, SENT, labels1)
        board1 = jnp.where(cap, 0, jnp.where(ohb, color, board))
        return (jnp.where(enabled, board1, board),
                jnp.where(enabled, labels1, labels))

    def escaper_response(b1, labels, M, libsT, libs_field, prey_root,
                         c_pt, cap0):
        """Forced prey response — mirror of _escaper_response_full on
        the pre-chaser-move analysis (labels/M/libsT/libs_field) +
        post-move b1. ``M``/``libs_field`` are rung-constant and
        hoisted by the caller (two N² tensors per rung, not four)."""
        empty1 = b1 == 0
        prey_mask = labels == prey_root
        dil_prey = dilate(prey_mask)
        prey_libs1 = empty1 & dil_prey
        preyL1 = isum(prey_libs1)
        ext_pt = min_idx(prey_libs1)
        c_oh = onehot(c_pt)

        # the merged chaser group around c_pt
        gc_mask = c_oh > 0
        for d in DIRS:
            vd = valid_dir(c_pt, d)
            qc = scal(nbr(b1, d, 0), c_oh)
            qr = scal(nbr(labels, d, SENT), c_oh)
            gc_mask = gc_mask | jnp.where(vd & (qc == chaser),
                                          labels == qr, False)
        gc_nlibs = isum(empty1 & dilate(gc_mask))

        # chaser groups that gained a liberty from the chaser-move
        # capture can be neither counter-captured nor captured
        gained_pt = (b1 == chaser) & dilate(cap0)
        gainedT = (M & gained_pt).any(axis=2, keepdims=True)  # (1,N,1)
        gained_field = (M & gainedT).any(axis=1, keepdims=True)

        # counter-capture target: first chaser stone adjacent to the
        # prey whose group is in atari on b1
        adj_prey = (b1 == chaser) & dil_prey
        atari_pts = adj_prey & jnp.where(
            gc_mask, gc_nlibs == 1,
            (libs_field == 1) & ~gained_field)
        have_cap = atari_pts.any()
        target = min_idx(atari_pts)
        t_oh = onehot(target)
        target_in_gc = sbool(gc_mask, t_oh)
        target_root = scal(labels, t_oh)
        target_mask = jnp.where(target_in_gc, gc_mask,
                                labels == target_root)
        cap_pt = min_idx(empty1 & dilate(target_mask))

        def try_move(pt, enabled):
            oh = onehot(pt)
            ohb = oh > 0
            esc_cap = jnp.zeros((1, 1, n), jnp.bool_)
            gc_adj = jnp.bool_(False)
            merge_mask = jnp.zeros((1, 1, n), jnp.bool_)
            for d in DIRS:
                vd = valid_dir(pt, d)
                qc = scal(nbr(b1, d, 0), oh)
                qr = scal(nbr(labels, d, SENT), oh)
                in_gc_d = sbool(nbr(gc_mask, d, False), oh)
                qlibs = table_at(libsT, qr)
                qgained = sbool(nbr(gained_field, d, False), oh)
                old_cap_d = (vd & (qc == chaser) & ~in_gc_d
                             & (qlibs == 1) & ~qgained)
                esc_cap = esc_cap | jnp.where(old_cap_d,
                                              labels == qr, False)
                gc_adj = gc_adj | (vd & (qc == chaser) & in_gc_d)
                merge_mask = merge_mask | jnp.where(
                    vd & (qc == prey_color), labels == qr, False)
            esc_cap = esc_cap | ((gc_adj & (gc_nlibs == 1)) & gc_mask)
            cluster = ohb | merge_mask
            empty2 = (empty1 & ~ohb) | esc_cap
            comp = jnp.where(sbool(dil_prey, oh),
                             prey_mask | cluster, prey_mask)
            L2 = isum(empty2 & dilate(comp))
            legal = (empty2 & dilate(cluster)).any()
            okm = enabled & sbool(empty1, oh) & legal
            return jnp.where(okm, L2, -1), esc_cap & okm

        L1v, C1 = try_move(ext_pt, preyL1 >= 1)
        L2v, C2 = try_move(cap_pt, have_cap)
        take1 = L1v >= L2v
        respL = jnp.where(take1, L1v, L2v)
        return (preyL1, respL,
                jnp.where(take1, ext_pt, cap_pt),
                jnp.where(take1, C1, C2), respL >= 0)

    def rung(board, labels):
        libsT = libs_table(board, labels)
        M = labels == iota_r                                # (1,N,N)
        libs_field = (M.astype(jnp.int32) * libsT).sum(
            axis=1, keepdims=True)                          # (1,1,N)
        prey_root = scal(labels, prey_oh)
        prey_alive = scal(board, prey_oh) == prey_color
        L = jnp.where(prey_alive, table_at(libsT, prey_root), 0)
        prey_mask = labels == prey_root
        prey_lib_mask = (board == 0) & dilate(prey_mask)
        l1 = min_idx(prey_lib_mask)
        l2 = min_idx(prey_lib_mask & (iota_e != l1))

        def option(lib_pt):
            ok, cap0 = place(board, labels, libsT, lib_pt, chaser)
            oh = onehot(lib_pt)
            b1 = jnp.where(cap0, 0, jnp.where(oh > 0, chaser, board))
            preyL, respL, resp_pt, resp_cap, resp_made = \
                escaper_response(b1, labels, M, libsT, libs_field,
                                 prey_root, lib_pt, cap0)
            resp_logic = jnp.where(
                respL <= 1, _CAPTURED,
                jnp.where(respL >= 3, _ESCAPED, _CONTINUE))
            outcome = jnp.where((L == 2) & ok & (preyL == 1),
                                resp_logic, _ESCAPED)
            return outcome, (lib_pt, cap0, resp_pt, resp_cap, resp_made)

        o1, u1 = option(l1)
        o2, u2 = option(l2)
        pick1 = o1 <= o2
        o = jnp.where(pick1, o1, o2)
        c_pt, cap0, resp_pt, resp_cap, resp_made = jax.tree.map(
            lambda a, b: jnp.where(pick1, a, b), u1, u2)

        pre = jnp.where(
            ~prey_alive, _CAPTURED,
            jnp.where(L >= 3, _ESCAPED,
                      jnp.where(L == 1, _CAPTURED, -1)))
        o = jnp.where(pre >= 0, pre, o)
        advance = (pre < 0) & (o == _CONTINUE)

        board1, labels1 = relabel(board, labels, c_pt, chaser, cap0,
                                  advance)
        board2, labels2 = relabel(board1, labels1, resp_pt, prey_color,
                                  resp_cap, advance & resp_made)
        # this rung's read-core contribution — mirror of the XLA
        # chase's collect_core accumulation (ladders._chase): the
        # prey's stones, the prey point itself, and every cell the
        # rung changed (played stones + captures = the board diff)
        add = ((prey_mask & (board != 0)) | (prey_oh > 0)
               | (board2 != board))
        return board2, labels2, o, add

    def cond(state):
        _, _, done, _, _, r = state
        return ~done & (r < depth)

    def body(state):
        board, labels, done, captured, core, r = state
        board2, labels2, o, add = rung(board, labels)
        return (board2, labels2,
                done | (o != _CONTINUE),
                jnp.where(done, captured, o == _CAPTURED),
                core | (~done & add),
                r + 1)

    enabled = prey_oh.sum() > 0
    init = (board0, labels0, ~enabled, jnp.bool_(False),
            jnp.zeros((1, 1, n), jnp.bool_), jnp.int32(0))
    _, _, _, captured, core, _ = jax.lax.while_loop(cond, body, init)
    out_ref[...] = jnp.broadcast_to(
        (captured & enabled).astype(jnp.int32), (1, 1, n))
    if collect_core:
        maybe_core_ref[0][...] = (core & enabled).astype(jnp.int32)


@functools.partial(jax.jit,
                   static_argnames=("size", "depth", "interpret",
                                    "collect_core"))
def pallas_chase(boards: jax.Array, labels: jax.Array,
                 prey_onehot: jax.Array, size: int, depth: int = 40,
                 interpret: bool = False,
                 collect_core: bool = False) -> jax.Array:
    """Batched ladder chase: for each lane ``i``, is the group at
    ``prey_onehot[i]`` (one-hot over the flat board; all-zero =
    disabled lane) ladder-captured with the chaser to move?

    ``boards``/``labels``: int ``[L, N]`` — a board and its carried
    min-root labeling per lane (see ``ladders._relabel_place``).
    Returns bool ``[L]``. Semantics identical to
    ``vmap(ladders._chase)``; each lane runs its own grid cell, so
    trip counts are per-lane, not batch-lockstep.

    ``collect_core=True`` additionally returns the per-lane read CORE
    (bool ``[L, N]``) — the same accumulation as the XLA chase's
    ``collect_core`` (union over rungs of the prey's group mask plus
    every cell each rung changed), i.e. the seed the incremental
    encoder's footprint expansion (``ladders._chase_read_region``)
    radiates from. Return becomes ``(captured [L], core [L, N])``.
    Collection is a few extra vector ORs per rung — the lanes' own
    while loops and VMEM residency are unchanged.
    """
    lanes, n = boards.shape
    if n != size * size:
        raise ValueError(f"boards have {n} points, size² is {size * size}")
    kernel = functools.partial(_chase_kernel, size=size, depth=depth,
                               collect_core=collect_core)
    spec = pl.BlockSpec((1, 1, n), lambda i: (i, 0, 0))
    shape = jax.ShapeDtypeStruct((lanes, 1, n), jnp.int32)
    out_specs = [spec, spec] if collect_core else spec
    out_shape = [shape, shape] if collect_core else shape
    out = pl.pallas_call(
        kernel,
        grid=(lanes,),
        in_specs=[spec, spec, spec],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(boards.astype(jnp.int32)[:, None, :],
      labels.astype(jnp.int32)[:, None, :],
      prey_onehot.astype(jnp.int32)[:, None, :])
    if collect_core:
        captured, core = out
        return captured[:, 0, 0] > 0, core[:, 0, :] > 0
    return out[:, 0, 0] > 0
