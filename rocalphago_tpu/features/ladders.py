"""Jitted ladder reading for the ladder_capture / ladder_escape planes.

The reference reads ladders with a recursive Python search around
``AlphaGo/preprocessing/preprocess.py``. Recursion with data-dependent
branching doesn't map to XLA, so the TPU design (SURVEY.md §7 hard part
#2) is:

* **candidate compaction** — only (move, prey-group) pairs satisfying
  the ladder precondition are simulated. ``jnp.nonzero(size=K)``
  compacts them into a fixed ``K`` lanes (static shape; overflow beyond
  ``K`` truncates — real boards have few simultaneous ladders);
* **two-ply lockstep reading** — one ``lax.while_loop`` iteration plays
  a full ladder rung: each chaser option (the prey's two liberties) is
  scored by the *forced escaper response* (extend at the last liberty,
  or counter-capture an adjacent chasing group in atari), and the
  chaser takes the best outcome. This 2-ply evaluation is what makes
  the read exact on standard ladder zigzags, where a 1-ply greedy
  chaser picks the wrong side; it remains an approximation vs the
  oracle's full branching on pathological shapes (tests use positions
  where both agree);
* ko inside the read is ignored (as in the reference's reader);
* **shared, gated chase slots** — the full encoder reads BOTH planes
  through :func:`ladder_planes`: one candidate analysis, slot entry
  gated on a live undecided chase (prey back at exactly 2 liberties
  after the opening), and one pooled rung loop whose lanes mix
  capture (opponent) and escape (own) prey — the chase is
  prey-color-agnostic. See docs/PERFORMANCE.md "Encode path" for the
  gating model and the measured defaults.

All functions are pure and vmap over games.
"""

from __future__ import annotations

from typing import NamedTuple

import os

import jax
import jax.numpy as jnp
from jax import lax

from rocalphago_tpu.engine.jaxgo import (
    GoConfig,
    GoState,
    GroupData,
    _dedup_mask,
    lib_counts_from_labels,
    neighbor_analysis,
    neighbors_for,
    relabel_after_place,
)

# per-option ladder outcomes, ordered so the chaser minimises
_CAPTURED, _CONTINUE, _ESCAPED = 0, 1, 2

def _phase1_depth() -> int:
    """Two-phase chase schedule knob (see _compacted_chase): phase 1
    reads all slots to this many rungs lockstep; still-live lanes
    then finish one at a time at 1/slots the loop width. Most lanes
    settle within a few rungs. MEASURED DEFAULT 2 (the
    ``jaxgo._dense_engine`` discipline): ``benchmarks/bench_encode.py``
    CPU A/B on dense 19×19 mid-games, batch 16, shared gating —
    depth 2 won both slot sweeps (91.0 pos/s vs 77.1 @ 1 / 81.4 @ 4
    at 4 slots; 73.9 vs 73.3 / 71.3 at the default 6 — within the
    run-to-run ~10% noise there), 8 pays extra lockstep rungs
    whenever any lane runs deep (62.2), and 40 recovers the old
    single-phase fixed-rung read (21.8 — the baseline; see
    BENCH_RESULTS.md "Encode A/B"). TPU rows are queued in
    ``scripts/tpu_window_hunter2.sh`` (``encode_*`` steps); revisit
    when they land. Read from ``$ROCALPHAGO_LADDER_PHASE1`` at TRACE
    time (same policy as ``_chase_impl``) so A/B sweeps can flip it
    per run. Floor 1: a while_loop body always runs once for live
    lanes, so a "depth-0" phase 1 would still play a rung and
    over-read by one."""
    return max(1, int(os.environ.get("ROCALPHAGO_LADDER_PHASE1", "2")))


def _ladder_gating() -> str:
    """Which slot-gating formulation :func:`ladder_planes` traces:
    ``"shared"`` (default) pools BOTH planes' gated chase candidates
    into ONE compacted slot set and ONE lockstep rung loop;
    ``"split"`` keeps the legacy per-plane chases (two loops of
    ``chase_slots`` each — the pre-overhaul formulation, kept as the
    A/B baseline). MEASURED DEFAULT: shared wins the CPU A/B
    (``benchmarks/bench_encode.py``; the two planes' rung loops merge,
    so a deep chase pays its trips once instead of once per plane —
    see BENCH_RESULTS.md "Encode A/B"). Read from
    ``$ROCALPHAGO_LADDER_GATE`` at trace time."""
    v = os.environ.get("ROCALPHAGO_LADDER_GATE", "shared")
    return "split" if v in ("split", "0", "off") else "shared"


def _place(cfg: GoConfig, board, gd: GroupData, action, color):
    """Light move application using the *pre-move* group analysis:
    resolves captures, flags suicide/occupied as invalid (board
    unchanged). Ko is deliberately not tracked."""
    n = cfg.num_points
    nbrs = neighbors_for(cfg.size)
    board_pad = jnp.concatenate([board, jnp.zeros((1,), board.dtype)])
    lab_pad = jnp.concatenate([gd.labels, jnp.full((1,), n, jnp.int32)])
    my_nbrs = nbrs[action]
    nbr_color = board_pad[my_nbrs]
    nbr_root = lab_pad[my_nbrs]
    valid = my_nbrs < n
    uniq = _dedup_mask(nbr_root)

    cap_k = valid & uniq & (nbr_color == -color) & (
        gd.lib_counts[nbr_root] == 1)
    captured = (gd.labels[:, None] == jnp.where(
        cap_k, nbr_root, -2)[None, :]).any(axis=1)

    has_empty = (valid & (nbr_color == 0)).any()
    own_safe = (valid & (nbr_color == color) & (
        gd.lib_counts[nbr_root] >= 2)).any()
    ok = (board[action] == 0) & (has_empty | own_safe | cap_k.any())

    new_board = jnp.where(captured, 0, board).at[action].set(color)
    return jnp.where(ok, new_board, board), ok, captured & ok





def _relabel_place(cfg: GoConfig, board, labels, pt, color, cap_mask,
                   enabled):
    """Incremental group labels after placing ``color`` at ``pt``
    (legality pre-checked by the caller) and removing the captured
    stones ``cap_mask``.

    Exact with ZERO flood fills: ladder reading only ever *adds* one
    stone at a time and removes whole captured groups, and neither
    operation can split a group — so the min-flat-index labeling of
    :func:`jaxgo.compute_labels` is maintained by pure mask algebra:
    the new stone unions its same-color neighbor groups under
    ``min(pt, their roots)`` (the min of a union of min-rooted groups),
    and captured points revert to the empty sentinel ``N``.

    ``enabled=False`` returns the inputs unchanged (vital under vmap:
    disabled lanes must not corrupt their carried analysis).
    """
    labels1 = relabel_after_place(cfg, board, labels, pt, color,
                                  cap_mask)
    board1 = jnp.where(cap_mask, jnp.int8(0), board).at[pt].set(color)
    return (jnp.where(enabled, board1, board),
            jnp.where(enabled, labels1, labels))


def _dilate2d(size: int, m):
    """bool [size, size] → self ∪ 4-neighborhood, via pad + static
    slices (pure vector ops, same trick as ``compute_labels``)."""
    p = jnp.pad(m, 1)
    return (m | p[2:, 1:-1] | p[:-2, 1:-1]
            | p[1:-1, 2:] | p[1:-1, :-2])


def _local_prey_libs(cfg: GoConfig, board, prey_pt):
    """Liberty count of the group at ``prey_pt`` — EXACT, via a local
    connected-component fill (dilate-within-color to fixpoint) instead
    of the whole-board labeling; converges in group-diameter steps
    (4 unrolled per trip). No production call sites remain (the
    ladder_escape opening now uses the incremental relabel +
    loop-free recount); kept as the independent fill-based oracle
    that ``tests/test_features.py`` checks the
    ``_escaper_response_fast`` algebra against."""
    size = cfg.size
    color = board[prey_pt]
    own = (board == color).reshape(size, size)
    seed = jnp.zeros((size, size), jnp.bool_).at[
        prey_pt // size, prey_pt % size].set(color != 0)

    def body(carry):
        mask, _ = carry
        new = mask
        for _ in range(4):
            new = _dilate2d(size, new) & own
        return new | mask, mask

    mask, _ = lax.while_loop(lambda c: (c[0] != c[1]).any(), body,
                             (seed, jnp.zeros_like(seed)))
    libs = _dilate2d(size, mask) & (board == 0).reshape(size, size)
    return jnp.where(color == 0, 0, libs.sum().astype(jnp.int32))


def _escaper_response_full(cfg: GoConfig, b1, prey_pt, prey_color,
                           prey_mask, gd0, c_pt, cap0):
    """Best forced response of a prey left in atari by the chaser's
    move at ``c_pt``: extend at the last liberty, or counter-capture an
    adjacent chasing group in atari. Unlike a recompute-everything
    formulation, this derives the whole 2-ply analysis from the rung's
    single pre-move analysis ``gd0`` — ZERO extra flood fills.

    Exactness (case by case, ``chaser = -prey_color``):

    * the chaser's move cannot change the PREY group's membership
      (it fills a liberty or captures other prey-colored groups), so
      ``prey_mask`` from ``gd0`` is valid on ``b1``;
    * chaser groups touching ``c_pt`` merged into one group ``Gc``;
      its mask/liberties are computed directly;
    * a chaser group adjacent to a stone the chaser's move captured
      (``cap0``) GAINED at least one liberty, so it has ≥2 now and can
      be neither a counter-capture target nor capturable — excluding
      them outright is exact;
    * every other chaser group is untouched, so ``gd0`` lib counts
      hold, and a 1-liberty group's last liberty is any empty point
      adjacent to it;
    * prey-colored groups surviving on ``b1`` are unchanged, so merges
      from an extension are unions of ``gd0`` label masks.

    Returns ``(preyL1, libs_after_best, board_after_best, resp_pt,
    resp_cap, resp_made)`` where ``preyL1`` is the prey's liberty
    count on ``b1`` (callers gate on it); libs_after_best is -1 when
    no legal response exists (then ``resp_made`` is False and the
    board is returned unchanged). ``resp_pt``/``resp_cap`` are the
    chosen response move and the chaser stones it captured — exactly
    the inputs :func:`_relabel_place` needs to carry the incremental
    labeling past the response.
    """
    n = cfg.num_points
    size = cfg.size
    nbrs = neighbors_for(size)
    chaser = -prey_color
    lab_pad0 = jnp.concatenate(
        [gd0.labels, jnp.full((1,), n, jnp.int32)])
    b1_pad = jnp.concatenate([b1, jnp.zeros((1,), b1.dtype)])
    empty1 = b1 == 0

    def dil(mask):
        return _dilate2d(size, mask.reshape(size, size)).reshape(-1)

    dil_prey = dil(prey_mask)
    prey_libs1 = empty1 & dil_prey
    preyL1 = prey_libs1.sum().astype(jnp.int32)
    ext_pt = jnp.argmax(prey_libs1).astype(jnp.int32)

    # the merged chaser group around c_pt
    c_nbr_roots = lab_pad0[nbrs[c_pt]]
    c_nbr_chaser = b1_pad[nbrs[c_pt]] == chaser
    gc_mask = (gd0.labels[:, None] == jnp.where(
        c_nbr_chaser, c_nbr_roots, -2)[None, :]).any(axis=1)
    gc_mask = gc_mask.at[c_pt].set(True)
    gc_pad = jnp.concatenate([gc_mask, jnp.zeros((1,), jnp.bool_)])
    gc_nlibs = (empty1 & dil(gc_mask)).sum()

    # chaser groups that gained a liberty from the chaser-move capture
    gained_pt = (b1 == chaser) & dil(cap0)
    gained_root = jnp.zeros((n + 1,), jnp.bool_).at[gd0.labels].max(
        gained_pt)

    # counter-capture target: first (lowest-index) chaser stone
    # adjacent to the prey whose group is in atari on b1
    adj_prey = (b1 == chaser) & dil(prey_mask)
    atari_pts = adj_prey & jnp.where(
        gc_mask, gc_nlibs == 1,
        (gd0.lib_counts[gd0.labels] == 1) & ~gained_root[gd0.labels])
    have_cap = atari_pts.any()
    target = jnp.argmax(atari_pts).astype(jnp.int32)
    target_mask = jnp.where(gc_mask[target], gc_mask,
                            gd0.labels == gd0.labels[target])
    cap_pt = jnp.argmax(empty1 & dil(target_mask)).astype(jnp.int32)

    def try_move(pt, enabled):
        onehot = jnp.zeros((n,), jnp.bool_).at[pt].set(True)
        pt_nbr_roots = lab_pad0[nbrs[pt]]
        pt_nbr_chaser = b1_pad[nbrs[pt]] == chaser
        pt_nbr_in_gc = gc_pad[nbrs[pt]]
        valid = nbrs[pt] < n
        # chaser groups captured by the response: adjacent, in atari
        # (their last liberty must then be pt itself)
        old_cap_k = (valid & pt_nbr_chaser & ~pt_nbr_in_gc
                     & (gd0.lib_counts[pt_nbr_roots] == 1)
                     & ~gained_root[pt_nbr_roots])
        esc_cap = (gd0.labels[:, None] == jnp.where(
            old_cap_k, pt_nbr_roots, -2)[None, :]).any(axis=1)
        gc_capped = (valid & pt_nbr_chaser & pt_nbr_in_gc).any() \
            & (gc_nlibs == 1)
        esc_cap = esc_cap | (gc_capped & gc_mask)
        # the played stone's cluster: {pt} ∪ surviving own-color
        # neighbor groups. It joins the PREY's component only when pt
        # itself is adjacent to the prey (two distinct same-color
        # groups are never orthogonally adjacent, so a merge partner
        # cannot bridge them) — a counter-capture played away from the
        # prey must not donate its own liberties to the prey's count.
        merge_k = valid & (b1_pad[nbrs[pt]] == prey_color)
        merge_mask = (gd0.labels[:, None] == jnp.where(
            merge_k, pt_nbr_roots, -2)[None, :]).any(axis=1)
        cluster = onehot | merge_mask
        empty2 = (empty1 & ~onehot) | esc_cap
        comp = jnp.where(dil_prey[pt], prey_mask | cluster, prey_mask)
        L2 = (empty2 & dil(comp)).sum().astype(jnp.int32)
        # move legality = the played stone's own group keeps a liberty
        legal = (empty2 & dil(cluster)).any()
        okm = enabled & empty1[pt] & legal
        b2 = jnp.where(esc_cap, jnp.int8(0), b1).at[pt].set(prey_color)
        return (jnp.where(okm, L2, -1), jnp.where(okm, b2, b1),
                esc_cap & okm)

    L1, B1, C1 = try_move(ext_pt, preyL1 >= 1)
    L2, B2, C2 = try_move(cap_pt, have_cap)
    take1 = L1 >= L2
    respL = jnp.where(take1, L1, L2)
    return (preyL1, respL, jnp.where(take1, B1, B2),
            jnp.where(take1, ext_pt, cap_pt),
            jnp.where(take1[None], C1, C2), respL >= 0)


def _escaper_response_fast(cfg: GoConfig, b1, prey_pt, prey_color,
                           prey_mask, gd0, c_pt, cap0):
    """3-tuple view of :func:`_escaper_response_full` —
    ``(preyL1, libs_after_best, board_after_best)``."""
    preyL1, respL, b2, _, _, _ = _escaper_response_full(
        cfg, b1, prey_pt, prey_color, prey_mask, gd0, c_pt, cap0)
    return preyL1, respL, b2


def _foot_mode() -> str:
    """Which footprint expansion :func:`_chase_read_regions` traces:
    ``"tight"`` (default) derives the region from the actual reads of
    the 2-ply algebra (see that function's derivation), ``"wide"``
    keeps the pre-tightening blanket (``dilate²`` of everything plus a
    second group pass over it) as the A/B baseline and safety valve.
    Both are sound over-approximations; wide is strictly larger, so it
    only costs reuse. Read from ``$ROCALPHAGO_LADDER_FOOT`` at trace
    time (same policy as the other ladder knobs). MEASURED: tight cuts
    the footprint-churn re-chase cascade that capped incremental
    encode at ~2.1–2.3× — see BENCH_RESULTS.md "Incremental encode"
    and the ``encode_cascade`` row of ``bench_encode.py``."""
    v = os.environ.get("ROCALPHAGO_LADDER_FOOT", "tight")
    return "wide" if v in ("wide", "0", "off") else "tight"


def _chase_read_region(cfg: GoConfig, board, labels, core):
    """Sound over-approximation of the board cells a chase's (or an
    opening's) analysis can read, radiating from the accumulated
    ``core`` — the union over plies of the prey's group mask plus
    every cell the simulation played on or captured.

    This is the dependency footprint of the incremental encoder's
    per-lane cache (``features/incremental.py``): a cached opening
    outcome / chase verdict stays valid exactly while no cell of its
    recorded region changes — the standard memoization-with-read-set
    induction (each ply of a re-run read would see only unchanged
    cells, so it makes identical decisions). Crucially it is evaluated
    ONCE per recorded lane against the ENCODE-TIME board — not per
    rung against the simulation boards — which is sound because the
    simulation's own moves are all in ``core``: a group on a simulated
    board is original groups bridged by played cells, so "groups
    touching X on the simulated board" is covered by "groups touching
    ``dilate(X ∪ core)`` on the real board" plus ``core`` itself.

    Derivation of the TIGHT region (default; every read of
    :func:`_place` / :func:`_escaper_response_full` / the rung body is
    accounted for — the wide pre-tightening blanket is kept behind
    ``$ROCALPHAGO_LADDER_FOOT=wide``):

    * ``D2 = dilate²(core)`` — the prey's liberty points are 1 step
      from ``core``, both chaser options and the extension response
      read their own 4-neighborhoods at those points (2 steps), and
      simulated-merge bridging needs no extra step because the
      bridging played cells are themselves in ``core``;
    * ``grp1`` — WHOLE groups with a stone in ``D2``: every group
      whose liberty count, membership or capture the algebra consults
      at the first level (chaser groups at the options, merge
      partners, atari/counter-capture targets) touches the prey or a
      played/option point, i.e. has a stone within ``D2``. Liberty
      counts are group-global, so the whole extent matters, and their
      liberties live in ``dilate(grp1)``;
    * ``R2 = dilate²(grp1)`` — the counter-capture response plays at
      a liberty of a ``grp1`` target (1 step off it) and reads that
      point's own neighborhood (1 more step);
    * ``grp2`` — whole groups with a stone in ``R2 ∪ D2``: the groups
      the counter-capture's legality/merge/capture checks consult
      around its response point, plus (re-)covering the first level;
      their liberty reads live in ``dilate(grp2)``.

    The wide blanket additionally dilates the ENTIRE first ring by two
    (``dilate⁴(core)``) before the second group pass — for a long
    chase path that near-doubles the band around the whole path, which
    is exactly the footprint-churn cascade the incremental encoder
    measured as its limiter. Over-approximation only costs reuse,
    never correctness; tight ⊂ wide by construction."""
    return _chase_read_regions(cfg, board, labels, core[None, :])[0]


def _chase_read_regions(cfg: GoConfig, board, labels, cores):
    """Batched :func:`_chase_read_region`: ``cores`` bool [W, N] →
    footprints bool [W, N], all lanes against the one shared board.

    This runs on EVERY recording ply of the incremental encoder, so
    it is written for CPU op-dispatch cost, not elegance: the 2-D
    dilations are batched pad+slice shifts (no vmap), and the
    whole-group reads ("any core cell in group ρ?") are ONE f32
    matmul against the label one-hot table instead of a vmapped
    scatter-max per lane — bitwise the same result (distinct labels
    hit distinct columns; > 0 recovers the OR), an order of magnitude
    fewer op dispatches."""
    n = cfg.num_points
    size = cfg.size
    w = cores.shape[0]

    def dilate(m, k):
        m2 = m.reshape(w, size, size)
        for _ in range(k):
            p = jnp.pad(m2, ((0, 0), (1, 1), (1, 1)))
            m2 = (m2 | p[:, 2:, 1:-1] | p[:, :-2, 1:-1]
                  | p[:, 1:-1, 2:] | p[:, 1:-1, :-2])
        return m2.reshape(w, n)

    stones = board != 0
    # [N, N+1] one-hot of each stone's group root (empty cells hit the
    # sentinel column n, which no real read consults)
    label_oh = (jnp.where(stones, labels, n)[:, None]
                == jnp.arange(n + 1)[None, :]).astype(jnp.float32)

    def groups_touching(region):
        touched = (region & stones[None, :]).astype(jnp.float32) \
            @ label_oh                                   # [W, N+1]
        return (jnp.take(touched, labels, axis=1) > 0.5) \
            & stones[None, :]

    region = dilate(cores, 2)
    grp1 = groups_touching(region)
    if _foot_mode() == "wide":
        ring = dilate(region | grp1, 2)
        grp2 = groups_touching(ring)
        return ring | grp2 | dilate(grp2, 1)
    ring = dilate(grp1, 2)                  # counter-capture ring
    grp2 = groups_touching(ring | region)
    return region | grp1 | ring | grp2 | dilate(grp2, 1)


def _chase(cfg: GoConfig, board0, labels0, prey_pt, depth: int,
           enabled=True, return_state: bool = False,
           collect_core: bool = False, core0=None):
    """Chaser to move against a two-liberty prey; True if prey is
    ladder-captured. Each iteration = one full rung (chaser move +
    forced escaper response).

    ``return_state=True`` additionally returns ``(unresolved, board,
    labels)`` — the lanes that hit the ``depth`` cap mid-chase and
    the position they stopped at. The chase state is fully (board,
    labels, prey_pt), so a capped chase RESUMES exactly by calling
    :func:`_chase` again on the returned position with the remaining
    depth (the two-phase schedule in :func:`_compacted_chase`).

    ZERO flood fills anywhere in the loop: the caller seeds the
    group labeling (``labels0``, from the plane-level analysis plus
    :func:`_relabel_place` for the opening moves) and each rung
    carries it forward with the same incremental relabeling — sound
    because a chase only adds single stones and removes whole captured
    groups, neither of which can split a group. Liberty counts are
    recomputed loop-free from the labels (:func:`jaxgo.lib_counts_from_labels`).
    Previous designs refilled the whole board once (originally seven
    times) per rung; under vmap every lane/game stalls on the slowest
    lane's fill, which made ladders ~99% of the 48-plane encode.

    ``enabled=False`` starts the loop already done — vital under
    ``vmap`` over candidate lanes, where the while_loop runs until
    EVERY lane converges: without the gate, empty/garbage lanes chase
    to full ``depth`` on every call, making typical positions pay the
    worst case.

    ``collect_core=True`` additionally accumulates the chase's read
    CORE (bool [N]; seeded from ``core0``): the union over rungs of
    the prey's group mask plus every cell the rung changed (played
    stones and captures) — pure ORs of masks each rung computes
    anyway, so collection is ~free. The caller expands the final core
    ONCE with :func:`_chase_read_region` into the dependency footprint
    the incremental encoder's verdict cache invalidates on (see that
    function's soundness note for why a single end-of-chase expansion
    against the encode-time board covers every rung's reads). Appended
    to the return tuple (``captured, core`` / ``captured, unresolved,
    board, labels, core``)."""
    n = cfg.num_points
    nbrs = neighbors_for(cfg.size)
    prey_color = board0[prey_pt].astype(jnp.int8)

    class Carry(NamedTuple):
        board: jax.Array
        labels: jax.Array
        done: jax.Array
        captured: jax.Array
        rung: jax.Array
        settled: jax.Array      # done by OUTCOME (vs the depth cap)
        core: jax.Array         # bool [N] accumulated read core
        #   (all-False and never updated unless collect_core)

    def option_outcome(board, gd, prey_mask, lib_pt, enabled):
        """Chaser fills ``lib_pt``; returns (outcome, relabeling
        inputs for both plies). Pure mask algebra — no fills."""
        b1, ok, cap0 = _place(cfg, board, gd, lib_pt, -prey_color)
        preyL, respL, _, resp_pt, resp_cap, resp_made = \
            _escaper_response_full(cfg, b1, prey_pt, prey_color,
                                   prey_mask, gd, lib_pt, cap0)
        resp_logic = jnp.where(
            respL <= 1, _CAPTURED,
            jnp.where(respL >= 3, _ESCAPED, _CONTINUE))
        # an option only matters if it's a legal move that keeps atari
        outcome = jnp.where(enabled & ok & (preyL == 1),
                            resp_logic, _ESCAPED)
        return outcome, (lib_pt, cap0, resp_pt, resp_cap, resp_made)

    def body(c: Carry) -> Carry:
        board, labels = c.board, c.labels
        lib_counts = lib_counts_from_labels(cfg, board, labels)
        gd = GroupData(labels, None, lib_counts, None, None)
        lab_pad = jnp.concatenate(
            [labels, jnp.full((1,), n, jnp.int32)])
        root = labels[prey_pt]
        prey_alive = board[prey_pt] == prey_color
        L = jnp.where(prey_alive, lib_counts[root], 0)
        prey_mask = labels == root
        empty = board == 0
        lib_pts = empty & (lab_pad[nbrs] == root).any(axis=1)
        l1 = jnp.argmax(lib_pts).astype(jnp.int32)
        l2 = jnp.argmax(lib_pts & (jnp.arange(n) != l1)).astype(jnp.int32)

        o1, u1 = option_outcome(board, gd, prey_mask, l1, L == 2)
        o2, u2 = option_outcome(board, gd, prey_mask, l2, L == 2)
        pick1 = o1 <= o2
        o = jnp.where(pick1, o1, o2)
        c_pt, cap0, resp_pt, resp_cap, resp_made = jax.tree.map(
            lambda a, b: jnp.where(pick1, a, b), u1, u2)

        # prey already captured / in atari / safe before we move
        pre = jnp.where(
            ~prey_alive, _CAPTURED,
            jnp.where(L >= 3, _ESCAPED,
                      jnp.where(L == 1, _CAPTURED, -1)))
        o = jnp.where(pre >= 0, pre, o)
        # ~done: a lane stopped by the depth cap must FREEZE — its
        # exit board is the phase-2 resume point (return_state), so
        # free extra plies here would double-count reading depth and
        # could settle an outcome the frozen `captured` never sees
        advance = (pre < 0) & (o == _CONTINUE) & ~c.done

        board1, labels1 = _relabel_place(
            cfg, board, labels, c_pt, -prey_color, cap0, advance)
        board2, labels2 = _relabel_place(
            cfg, board1, labels1, resp_pt, prey_color, resp_cap,
            advance & resp_made)

        core = c.core
        if collect_core:
            # this rung's reads radiate from the prey (masked to
            # stones — a dead prey's sentinel root would select every
            # empty cell; the rung then stops on prey_pt alone) and
            # from the cells it changed (played stones + captures =
            # the rung's board diff)
            add = ((prey_mask & (board != 0))
                   | (jnp.arange(n) == prey_pt)
                   | (board2 != board))
            core = jnp.where(~c.done, core | add, core)

        out_of_depth = c.rung + 1 >= depth
        return Carry(
            board=board2,
            labels=labels2,
            done=c.done | (o != _CONTINUE) | out_of_depth,
            captured=jnp.where(c.done, c.captured, o == _CAPTURED),
            rung=c.rung + 1,
            settled=c.settled | (~c.done & (o != _CONTINUE)),
            core=core,
        )

    core_init = (jnp.zeros((n,), jnp.bool_) if core0 is None
                 else jnp.asarray(core0))
    init = Carry(board0, labels0, ~jnp.asarray(enabled, jnp.bool_),
                 jnp.bool_(False), jnp.int32(0),
                 ~jnp.asarray(enabled, jnp.bool_), core_init)
    final = lax.while_loop(lambda c: ~c.done, body, init)
    captured = final.captured & jnp.asarray(enabled, jnp.bool_)
    if not return_state:
        return (captured, final.core) if collect_core else captured
    unresolved = ~final.settled & jnp.asarray(enabled, jnp.bool_)
    if collect_core:
        return captured, unresolved, final.board, final.labels, \
            final.core
    return captured, unresolved, final.board, final.labels


def _chase_impl() -> str:
    """Which chase implementation to trace: ``"xla"`` (default — the
    batch-lockstep while_loop), ``"pallas"`` (the per-lane TPU kernel
    ``ops.chase``), or ``"interpret"`` (the kernel in the Pallas
    interpreter — CPU CI). Read from ``$ROCALPHAGO_PALLAS_CHASE`` at
    trace time; the kernel is opt-in until real-chip measurements
    favor it (same policy as ``ops.labels``)."""
    v = os.environ.get("ROCALPHAGO_PALLAS_CHASE", "")
    return {"1": "pallas", "pallas": "pallas",
            "interpret": "interpret"}.get(v, "xla")


def _compacted_chase(cfg: GoConfig, boards, labels, prey_pts,
                     need_chase, depth: int, slots: int):
    """Run the chase for the lanes flagged ``need_chase``, first
    compacted into ``slots`` slots (bool [K] → results bool [K]).

    After the opening filter, typically 0–2 of the K candidate lanes
    actually need a chase; compacting them means the expensive rung
    loop runs ``slots`` wide instead of ``K`` wide (the loop's
    per-trip cost is proportional to its width, and under the
    encoder's vmap every board pays every trip). Overflow beyond
    ``slots`` truncates — the same bounded-capacity contract as
    ``_candidate_lanes``; callers must map uncovered lanes to the
    conservative plane value. Lanes may mix prey colors (the pooled
    capture+escape set from :func:`ladder_planes`): the chase reads
    each lane's prey color from its board. Returns ``(captured [K],
    covered [K])`` where ``covered`` marks lanes whose chase actually
    ran."""
    k = need_chase.shape[0]
    slot_idx = _compact_indices(need_chase, slots, k)
    valid = slot_idx < k
    safe = jnp.where(valid, slot_idx, 0)
    if os.environ.get("ROCALPHAGO_DEBUG_LADDER_OVERFLOW") == "1":
        # runtime signal for the silent truncation contract (advisor
        # r2): flag positions whose live chases exceed capacity so a
        # user encoding dense ladder problems knows to raise
        # ``ladder_chase_slots``. Trace-time opt-in — zero cost off.
        # host-side condition: under the encoder's vmap a lax.cond
        # lowers to both-branches select, which would print for every
        # board; the callback sees each board's own count instead
        def _warn(c):
            if int(c) > slots:
                print(f"ladders: {int(c)} live chases > {slots} "
                      "chase slots — truncating (raise "
                      "ladder_chase_slots)")

        jax.debug.callback(_warn, need_chase.sum())
    impl = _chase_impl()
    if impl == "xla":
        # TWO-PHASE schedule (VERDICT r3 #5). The vmapped while_loop
        # locksteps every lane of every board in the batch: ONE deep
        # chase anywhere makes all B×slots lanes pay its full trip
        # count through the expensive two-ply body. Measured on
        # random 19×19 mid-games, typical lanes settle in ≤9 rungs
        # while a stray lane runs to the 40 cap — so phase 1 reads
        # everyone to a short cap lockstep, then the still-live
        # lanes finish ONE AT A TIME as scalar chases (resume is
        # exact: the chase state is (board, labels, prey_pt)). Each
        # scalar loop runs at 1/slots the width, and a loop whose
        # lane doesn't exist exits in zero trips — so typical boards
        # pay nothing for the tail, EVERY slotted lane is still read
        # to full depth (the slots-restore-exactness contract), and
        # the worst case (all slots deep) costs what the single
        # lockstep loop did.
        d1 = min(_phase1_depth(), depth)
        prey = prey_pts[safe]
        captured, unres, b_end, lab_end = jax.vmap(
            lambda b, l, p, v: _chase(cfg, b, l, p, d1, enabled=v,
                                      return_state=True))(
                boards[safe], labels[safe], prey, valid)
        if depth > d1:
            deep_idx = _compact_indices(unres, slots, slots)
            for s in range(slots):
                idx = deep_idx[s]
                live = idx < slots
                at = jnp.where(live, idx, 0)
                cap_s = _chase(cfg, b_end[at], lab_end[at], prey[at],
                               depth - d1, enabled=live)
                captured = captured.at[idx].set(cap_s, mode="drop")
    else:
        from rocalphago_tpu.ops.chase import pallas_chase

        n = cfg.num_points
        prey_oh = ((jnp.arange(n)[None, :] == prey_pts[safe][:, None])
                   & valid[:, None])
        captured = pallas_chase(boards[safe], labels[safe], prey_oh,
                                cfg.size, depth,
                                interpret=impl == "interpret")
    scatter = jnp.zeros((k,), jnp.bool_)
    return (scatter.at[slot_idx].set(captured & valid, mode="drop"),
            scatter.at[slot_idx].set(valid, mode="drop"))


def _compact_indices(mask, size: int, fill_value):
    """First ``size`` set indices of a 1-D bool mask, ascending,
    padded with ``fill_value`` — the shared compaction primitive of
    the candidate/slot machinery (here and the incremental refresh
    scheduler).

    Kept as ``jnp.nonzero(size=..., fill_value=...)`` BY MEASUREMENT:
    a scatter-free rewrite (log-depth ``associative_scan`` ranks +
    per-slot argmax gather over the ``[size, N]`` rank-match matrix)
    looked faster in profiler traces of the warm no-churn floor, but
    regressed the real 19x19 trajectory benchmark from ~2.5 ms to
    ~4.3 ms per position — XLA:CPU's sized-nonzero lowering beats the
    dense comparison matrix once chases actually run. Trace spans
    overweight the serial while-loops; trust the wall-clock bench
    (docs/PERFORMANCE.md "Incremental encode")."""
    return jnp.nonzero(mask, size=size,
                       fill_value=fill_value)[0].astype(jnp.int32)


def _candidate_lanes(cfg: GoConfig, state: GoState, gd: GroupData,
                     legal, prey_libs: int, prey_is_opp: bool,
                     lanes: int, analysis=None):
    """Compact (move, prey) pairs matching the precondition into K
    lanes. Returns (move_pt [K], prey_pt [K], valid [K]).

    This is the first gating stage (docs/PERFORMANCE.md "Encode
    path"): only strings at the exact ladder precondition — opponent
    strings at 2 liberties (capture) or own strings in atari (escape)
    — generate lanes at all. EXACT by the planes' definitions: a
    ladder capture starts by filling one of a 2-liberty group's
    liberties (a 1-liberty group is a plain capture, ≥3 can't be
    laddered this ply), and a ladder escape extends an atari group at
    its last liberty. Pass ``analysis`` (a
    :func:`jaxgo.neighbor_analysis` result) to share one neighbor
    lookup between both planes' enumerations."""
    n = cfg.num_points
    nbrs = neighbors_for(cfg.size)
    if analysis is None:
        analysis = neighbor_analysis(cfg, state.board, gd.labels)
    nbr_color, nbr_root, uniq, _ = analysis

    want = -state.turn if prey_is_opp else state.turn
    cand = (legal[:, None] & uniq & (nbr_color == want)
            & (gd.lib_counts[nbr_root] == prey_libs))   # [N, 4]
    flat_idx = _compact_indices(cand.reshape(-1), lanes, 4 * n)
    valid = flat_idx < 4 * n
    safe = jnp.where(valid, flat_idx, 0)
    move_pt = (safe // 4).astype(jnp.int32)
    prey_pt = nbrs[move_pt, safe % 4]
    return move_pt, prey_pt, valid


def _capture_opening(cfg: GoConfig, state: GoState, gd: GroupData,
                     move_pt, prey_pt, valid):
    """Vmapped capture opening over the candidate lanes: play the
    chaser's first move, score the prey's forced response, and carry
    the incremental labeling through both plies. Returns ``(boards
    [K,N], labels [K,N], need_chase [K], direct [K])`` — the second
    gating stage: ONLY lanes whose response leaves the prey back at
    exactly 2 liberties (``respL == 2`` — a live, undecided chase)
    enter the chase slots. Exact: ``respL <= 1`` is a capture decided
    with no chase (``direct``), ``respL >= 3`` is a clean escape, and
    both are classified here without consuming a slot."""
    me = state.turn

    def opening(mv, pr, ok):
        board1, placed, cap0 = _place(cfg, state.board, gd, mv, me)
        # prey is now in atari; its forced response decides the
        # opening — derived from the plane-level gd, no refill
        prey_mask = gd.labels == gd.labels[pr]
        _, respL, _, resp_pt, resp_cap, resp_made = \
            _escaper_response_full(
                cfg, board1, pr, -me, prey_mask, gd, mv, cap0)
        need_chase = ok & placed & (respL == 2)
        # carry the incremental labeling through both opening plies so
        # the chase starts with a valid analysis and never refills
        b1r, lab1 = _relabel_place(
            cfg, state.board, gd.labels, mv, me, cap0, ok & placed)
        b2r, lab2 = _relabel_place(
            cfg, b1r, lab1, resp_pt, -me, resp_cap,
            need_chase & resp_made)
        direct = ok & placed & (respL <= 1)   # captured with no chase
        return b2r, lab2, need_chase, direct

    return jax.vmap(opening)(move_pt, prey_pt, valid)


def _escape_opening(cfg: GoConfig, state: GoState, gd: GroupData,
                    move_pt, prey_pt, valid):
    """Vmapped escape opening: extend the atari group at its last
    liberty and recount. Second gating stage for the escape plane:
    only extensions that land on exactly 2 liberties (``L == 2`` — an
    undecided ladder) enter the chase slots; ``L >= 3`` is a decided
    escape (``direct``), ``L <= 1`` a decided failure — both
    classified slot-free."""
    me = state.turn

    def opening(mv, pr, ok):
        board1, placed, cap0 = _place(cfg, state.board, gd, mv, me)
        # own extension may merge groups — the incremental relabel
        # handles the merge exactly, and the loop-free liberty recount
        # replaces the old per-lane local fill
        b1r, lab1 = _relabel_place(
            cfg, state.board, gd.labels, mv, me, cap0, ok & placed)
        libs1 = lib_counts_from_labels(cfg, b1r, lab1)
        L = jnp.where(b1r[pr] == me, libs1[lab1[pr]], 0)
        need_chase = ok & placed & (L == 2)
        direct = ok & placed & (L >= 3)       # escaped with no chase
        return b1r, lab1, need_chase, direct

    return jax.vmap(opening)(move_pt, prey_pt, valid)


def ladder_capture_plane(cfg: GoConfig, state: GoState, gd: GroupData,
                         legal, depth: int = 40, lanes: int = 16,
                         chase_slots: int = 6) -> jax.Array:
    """bool [N]: legal moves that ladder-capture an adjacent two-liberty
    opponent group. Single-plane entry point (tests, one-plane
    encodes); the full encoder computes both planes through
    :func:`ladder_planes`, which shares the candidate analysis and the
    chase between them."""
    n = cfg.num_points
    move_pt, prey_pt, valid = _candidate_lanes(
        cfg, state, gd, legal, prey_libs=2, prey_is_opp=True, lanes=lanes)
    b2r, lab2, need_chase, direct = _capture_opening(
        cfg, state, gd, move_pt, prey_pt, valid)
    chased, _ = _compacted_chase(cfg, b2r, lab2, prey_pt, need_chase,
                                 depth, chase_slots)
    captured = direct | (need_chase & chased)
    return jnp.zeros((n,), jnp.bool_).at[move_pt].max(captured & valid)


def ladder_escape_plane(cfg: GoConfig, state: GoState, gd: GroupData,
                        legal, depth: int = 40, lanes: int = 16,
                        chase_slots: int = 6) -> jax.Array:
    """bool [N]: legal moves that rescue an own group in atari from a
    ladder (extension at its last liberty that survives the read).
    Single-plane entry point — see :func:`ladder_capture_plane`."""
    n = cfg.num_points
    move_pt, prey_pt, valid = _candidate_lanes(
        cfg, state, gd, legal, prey_libs=1, prey_is_opp=False, lanes=lanes)
    b1r, lab1, need_chase, direct = _escape_opening(
        cfg, state, gd, move_pt, prey_pt, valid)
    chased, covered = _compacted_chase(cfg, b1r, lab1, prey_pt,
                                       need_chase, depth, chase_slots)
    # overflow lanes (chase needed but no slot) must stay conservative
    # False — an unread escape is not asserted
    escaped = direct | (need_chase & covered & ~chased)
    return jnp.zeros((n,), jnp.bool_).at[move_pt].max(escaped & valid)


def ladder_planes(cfg: GoConfig, state: GoState, gd: GroupData,
                  legal, depth: int = 40, lanes: int = 16,
                  chase_slots: int = 6):
    """Both ladder planes from ONE shared read:
    ``(ladder_capture [N], ladder_escape [N])``.

    The encode-path overhaul (docs/PERFORMANCE.md "Encode path").
    Ladder work scales with the number of GENUINELY CHASEABLE strings,
    not with the board, via three gates and one shared loop:

    1. **candidate gating** (:func:`_candidate_lanes`) — only strings
       at the ladder precondition (opponent strings at 2 liberties /
       own strings in atari) generate lanes; one
       :func:`jaxgo.neighbor_analysis` serves both planes. Exact by
       definition of the planes.
    2. **slot gating** (the openings) — a lane consumes a chase slot
       ONLY when its opening leaves a live, undecided chase (prey back
       at exactly 2 liberties). Decided openings (direct capture,
       clean escape, illegal move) are classified slot-free — exact,
       because a prey at ≤1 liberties after the forced response is
       captured outright and one at ≥3 can no longer be laddered by
       the 2-ply reader.
    3. **shared chase slots** — both planes' surviving candidates are
       pooled into ONE ``chase_slots``-wide compacted chase (the chase
       is prey-color-agnostic: :func:`_chase` reads the prey's color
       from its board, so capture lanes — opponent prey — and escape
       lanes — own prey — share lanes of the same ``lax.while_loop``).
       One lockstep rung loop + one scalar deep tail replace the two
       per-plane loops, so a deep ladder pays its trips once, not once
       per plane. The loop EXITS EARLY the trip every pooled chase has
       resolved (``_chase``'s ``done`` reduction — with zero live
       chases it runs zero trips).

    Truncation contract: capacity is SHARED — capture candidates fill
    slots first (compaction order), escape candidates take what's
    left; overflow beyond ``chase_slots`` reads the conservative False
    on both planes (never a spurious capture or escape). With slots ≥
    live chases the pooled read is BIT-IDENTICAL to the split
    formulation (tests/test_features.py::TestSharedGating).

    ``$ROCALPHAGO_LADDER_GATE=split`` traces the legacy per-plane
    formulation instead (two independent ``chase_slots``-wide chases)
    — the measured A/B baseline (``benchmarks/bench_encode.py``).
    """
    n = cfg.num_points
    analysis = neighbor_analysis(cfg, state.board, gd.labels)
    cap_mv, cap_pr, cap_ok = _candidate_lanes(
        cfg, state, gd, legal, prey_libs=2, prey_is_opp=True,
        lanes=lanes, analysis=analysis)
    esc_mv, esc_pr, esc_ok = _candidate_lanes(
        cfg, state, gd, legal, prey_libs=1, prey_is_opp=False,
        lanes=lanes, analysis=analysis)
    cap_b, cap_l, cap_need, cap_direct = _capture_opening(
        cfg, state, gd, cap_mv, cap_pr, cap_ok)
    esc_b, esc_l, esc_need, esc_direct = _escape_opening(
        cfg, state, gd, esc_mv, esc_pr, esc_ok)

    if _ladder_gating() == "split":
        # legacy baseline: two independent chases, chase_slots each
        cap_chased, _ = _compacted_chase(
            cfg, cap_b, cap_l, cap_pr, cap_need, depth, chase_slots)
        esc_chased, esc_cov = _compacted_chase(
            cfg, esc_b, esc_l, esc_pr, esc_need, depth, chase_slots)
    else:
        chased, covered = _compacted_chase(
            cfg, jnp.concatenate([cap_b, esc_b]),
            jnp.concatenate([cap_l, esc_l]),
            jnp.concatenate([cap_pr, esc_pr]),
            jnp.concatenate([cap_need, esc_need]), depth, chase_slots)
        cap_chased, esc_chased = chased[:lanes], chased[lanes:]
        esc_cov = covered[lanes:]

    captured = cap_direct | (cap_need & cap_chased)
    # overflow lanes (chase needed but no slot) stay conservative
    # False on both planes — an unread chase asserts nothing
    escaped = esc_direct | (esc_need & esc_cov & ~esc_chased)
    return (jnp.zeros((n,), jnp.bool_).at[cap_mv].max(
                captured & cap_ok),
            jnp.zeros((n,), jnp.bool_).at[esc_mv].max(
                escaped & esc_ok))
