"""Feature-encoder API: the reference's ``Preprocess`` contract, TPU-side.

Parity: ``AlphaGo/preprocessing/preprocess.py::Preprocess``
(``Preprocess(feature_list)``, ``.state_to_tensor(state)``,
``.output_dim``; SURVEY.md §1 L1) — except tensors are NHWC
``[B, size, size, F]`` float32 (TPU conv layout) instead of the
reference's Theano NCHW, and states are the device engine's
:class:`~rocalphago_tpu.engine.jaxgo.GoState` (use
:func:`~rocalphago_tpu.engine.jaxgo.from_pygo` at host boundaries).

Observability (docs/OBSERVABILITY.md): both jitted encode programs are
compile-tracked (``jax_compiles_total{entry="encode.one"|"encode.batch"}``
— the warm-cache smoke in ``tests/test_features.py`` pins that a
repeat call compiles nothing), every call lands in the per-position
encode-cost histogram ``encode_pos_us{board=...}`` plus the
``encode_positions_total`` counter, and each call opens an ``encode``
span so ``scripts/obs_report.py`` can show where encode time goes.
Calls BLOCK on the result (``jax.block_until_ready``) — this API is
the host boundary (GTP, host MCTS waves, data conversion), whose
callers consume the tensor immediately, and blocking is what makes
the per-position microseconds honest instead of dispatch latency.
"""

from __future__ import annotations

import functools
import time

import jax
import numpy as np

from rocalphago_tpu.engine.jaxgo import GoConfig, GoState
from rocalphago_tpu.features.planes import encode
from rocalphago_tpu.features.pyfeatures import (
    DEFAULT_FEATURES,
    FEATURE_PLANES,
    LADDER_FEATURES,
    output_planes,
)
from rocalphago_tpu.obs import jaxobs, trace
from rocalphago_tpu.obs import registry as obs_registry

#: per-position encode cost edges, MICROSECONDS (the headline CPU
#: encode sits at ~10³–10⁴ µs/pos; a healthy chip should land 10¹–10²)
ENCODE_US_EDGES = (10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
                   2500.0, 5000.0, 10000.0, 25000.0, 50000.0,
                   100000.0, 250000.0, 1000000.0)


def observe_incremental(prev_stats, new_stats, positions=None):
    """Fold one incremental-encode step's device-side stat delta into
    the process obs registry (host boundaries only — the stats vector
    lives on device as part of the ``EncodeCache`` carry and callers
    snapshot it where they already sync).

    ``prev_stats``/``new_stats`` are the cache's int32 ``stats``
    vectors (``incremental.STAT_FIELDS`` layout) before and after the
    step; ``prev_stats=None`` means a fresh cache (all-zero baseline).
    Returns ``new_stats`` as a host array for the caller to carry.
    Counters: ``encode_delta_total`` (positions through the delta
    path — the from-scratch sibling is ``encode_full_total``) and
    ``encode_incr_<field>_total`` per stat field, the inputs of
    ``scripts/obs_report.py``'s incremental hit-rate line."""
    from rocalphago_tpu.features import incremental as _incr

    # batched caches carry one stats vector per game — fold to totals
    cur = np.asarray(jax.device_get(new_stats), np.int64) \
        .reshape(-1, len(_incr.STAT_FIELDS)).sum(axis=0)
    prev = (np.zeros_like(cur) if prev_stats is None
            else np.asarray(prev_stats, np.int64))
    if positions is None:   # default: the cache's own encode count
        positions = int(cur[_incr.STAT_ENCODES]
                        - prev[_incr.STAT_ENCODES])
    if positions > 0:
        obs_registry.counter("encode_delta_total").inc(positions)
    for i, field in enumerate(_incr.STAT_FIELDS):
        if field == "encodes":
            continue        # encode_delta_total already counts these
        d = int(cur[i] - prev[i])
        if d > 0:
            obs_registry.counter(f"encode_incr_{field}_total").inc(d)
    return cur


def count_cache_reset(reason: str) -> None:
    """Count one incremental-encode cache invalidation at a host
    boundary (``encode_cache_resets_total{reason=...}``): new games,
    rewinds/undo, board switches — the explicit full-re-encode
    fallbacks of the delta path."""
    obs_registry.counter("encode_cache_resets_total",
                         reason=reason).inc()


class Preprocess:
    """Jitted encoder over a fixed feature list and board config.

    ``feature_list`` entries name plane groups (see
    ``pyfeatures.FEATURE_PLANES``); the full default set is the 48-plane
    AlphaGo encoding.

    Ladder-plane capacity knobs (all static under jit):

    - ``ladder_depth``: max chase rungs read per ladder (default 40 —
      enough to cross a 19×19 board twice).
    - ``ladder_lanes``: max candidate (move, prey) pairs examined per
      plane (default 16).
    - ``ladder_chase_slots``: max ladder chases actually *run* per
      encode (default 6). When both ladder planes are requested the
      capacity is SHARED between them (one pooled gated chase,
      capture candidates first — ``ladders.ladder_planes``); a
      single-plane encode gets the full capacity for that plane.
      Chases beyond capacity are SILENTLY dropped in board row-major
      candidate order and their cells read the conservative ``False``
      (a truncated read never asserts a capture or an escape). Real
      positions essentially never hold >4 simultaneous live chases
      per color (randomized differential bound: <0.3% of cells;
      ``tests/test_features.py``), but dense whole-board ladder
      problems can — raise this (e.g. to 16) when encoding such
      positions; cost is roughly linear in the chase loop's width.
      MEASURED DEFAULT 6: the CPU A/B (``benchmarks/bench_encode.py``,
      dense 19×19, shared/phase1=2) ran ~85 pos/s at 4 slots, ~74 at
      6, ~69 at 8 — 6 trades ~13% against the fastest setting to keep
      the POOLED capacity near the pre-overhaul per-plane total
      (4 + 4) and dense-board truncation well inside the 1% oracle
      bound (BENCH_RESULTS.md "Encode A/B").
    """

    def __init__(self, feature_list=DEFAULT_FEATURES,
                 cfg: GoConfig = GoConfig(),
                 ladder_depth: int = 40, ladder_lanes: int = 16,
                 ladder_chase_slots: int = 6):
        unknown = [f for f in feature_list if f not in FEATURE_PLANES]
        if unknown:
            raise KeyError(f"unknown features: {unknown}")
        if not feature_list:
            raise ValueError("feature_list must name at least one feature")
        self.feature_list = tuple(feature_list)
        self.cfg = cfg
        self.output_dim = output_planes(self.feature_list)
        fn = functools.partial(
            encode, cfg, features=self.feature_list,
            ladder_depth=ladder_depth, ladder_lanes=ladder_lanes,
            ladder_chase_slots=ladder_chase_slots)
        self._one = jaxobs.track("encode.one", jax.jit(fn))
        self._batch = jaxobs.track("encode.batch",
                                   jax.jit(jax.vmap(fn)))
        board = str(cfg.size)
        self._pos_us = obs_registry.histogram(
            "encode_pos_us", edges=ENCODE_US_EDGES, board=board)
        self._positions = obs_registry.counter(
            "encode_positions_total", board=board)
        self._full = obs_registry.counter("encode_full_total")
        # which plane family this encoder pays for — the ladder-free
        # configuration's footprint in a run's metrics (serve pools,
        # trainers and actors all build their encoders here, so the
        # counter says whether ANY live encoder still carries the
        # handcrafted ladder planes)
        ladder = any(f in LADDER_FEATURES for f in self.feature_list)
        obs_registry.counter(
            "encode_encoders_total",
            planes="ladder" if ladder else "noladder").inc()
        # incremental (delta) encode state — see :meth:`advance`:
        # the jitted encode_step program (built on first use), the
        # carried EncodeCache, and the last snapshot of its on-device
        # stats vector (host side, for per-call registry deltas)
        self._lad_kw = dict(ladder_depth=ladder_depth,
                            ladder_lanes=ladder_lanes,
                            ladder_chase_slots=ladder_chase_slots)
        self._delta_step = None
        self._cache = None
        self._cache_stats = None
        self._sig = None        # jitted eval-signature program (lazy)

    def _timed(self, fn, arg, batch: int) -> jax.Array:
        with trace.span("encode", board=self.cfg.size, batch=batch):
            t0 = time.monotonic()
            out = jax.block_until_ready(fn(arg))
            dt = time.monotonic() - t0
        self._pos_us.observe(dt * 1e6 / max(batch, 1))
        self._positions.inc(batch)
        return out

    def state_to_tensor(self, state: GoState) -> jax.Array:
        """One state → ``[1, size, size, F]`` float32."""
        self._full.inc()
        return self._timed(self._one, state, 1)[None]

    def state_signature(self, states: GoState) -> jax.Array:
        """Eval signatures (uint32 ``[B, 2]``) of batched states — the
        transposition key under which this encoder's planes (and so
        any NN eval of them) may be reused, carried off the engine's
        incremental hash instead of rehashed on the host
        (:func:`rocalphago_tpu.engine.jaxgo.eval_signature`). Host
        boundaries that submit to a cache-enabled
        :class:`~rocalphago_tpu.serve.evaluator.BatchingEvaluator`
        pass this as ``keys=``."""
        if self._sig is None:
            from rocalphago_tpu.engine.jaxgo import eval_signature

            self._sig = jaxobs.track(
                "encode.signature",
                jax.jit(jax.vmap(functools.partial(eval_signature,
                                                   self.cfg))))
        return self._sig(states)

    def states_to_tensor(self, states: GoState) -> jax.Array:
        """Batched states (leading axis) → ``[B, size, size, F]``."""
        batch = int(jax.tree.leaves(states)[0].shape[0])
        self._full.inc(batch)
        return self._timed(self._batch, states, batch)

    # ------------------------------------------------- incremental API

    def reset_cache(self, reason: str = "new_game") -> None:
        """Drop the incremental-encode carry (explicit full-re-encode
        fallback): call on new games, rewinds/undo, or any history
        jump the caller knows about. NOT required for correctness —
        :meth:`advance` diffs boards and invalidates stale ladder
        verdicts by footprint, so a carried cache is always
        bit-identical — but an explicit reset keeps reuse stats
        honest and is counted per ``reason``
        (``encode_cache_resets_total{reason=...}``)."""
        if self._cache is not None:
            count_cache_reset(reason)
        self._cache = None
        self._cache_stats = None

    def advance(self, state: GoState, move=None) -> jax.Array:
        """Opt-in STATEFUL encode for sequential host-boundary callers
        → ``[1, size, size, F]`` float32, bit-identical to
        :meth:`state_to_tensor` at every call.

        Successive positions share almost all of their expensive
        ladder analysis; ``advance`` carries an
        :class:`~rocalphago_tpu.features.incremental.EncodeCache`
        across calls and re-runs the pooled ladder chase only for
        lanes whose recorded read footprint intersects the board
        delta (docs/PERFORMANCE.md "Incremental encode").

        ``move=None`` (the common form): encode ``state`` itself —
        the caller already stepped the engine. ``move`` (flat index,
        ``N`` = pass): step ``state`` by ``move`` on device and encode
        the successor (:func:`incremental.encode_delta`); the caller
        keeps its own engine state.

        A cold or reset cache re-encodes from scratch by construction
        (every lane refreshes); correctness never depends on the
        cache matching the position — see :meth:`reset_cache`."""
        from rocalphago_tpu.features import incremental as _incr

        if self._delta_step is None:
            step_fn = functools.partial(
                _incr.encode_step, self.cfg,
                features=self.feature_list, **self._lad_kw)
            self._delta_step = jaxobs.track(
                "encode.delta",
                jax.jit(lambda s, c: step_fn(s, c)))
        if move is not None:
            from rocalphago_tpu.engine.jaxgo import step as _step

            state = _step(self.cfg, state,
                          jax.numpy.asarray(move, jax.numpy.int32))
        if self._cache is None:
            self._cache = _incr.init_cache(self.cfg)
        with trace.span("encode", board=self.cfg.size, batch=1,
                        delta=True):
            t0 = time.monotonic()
            planes, self._cache = self._delta_step(state, self._cache)
            planes = jax.block_until_ready(planes)
            dt = time.monotonic() - t0
        self._pos_us.observe(dt * 1e6)
        self._positions.inc()
        self._cache_stats = observe_incremental(
            self._cache_stats, self._cache.stats)
        return planes[None]
