"""Feature-encoder API: the reference's ``Preprocess`` contract, TPU-side.

Parity: ``AlphaGo/preprocessing/preprocess.py::Preprocess``
(``Preprocess(feature_list)``, ``.state_to_tensor(state)``,
``.output_dim``; SURVEY.md §1 L1) — except tensors are NHWC
``[B, size, size, F]`` float32 (TPU conv layout) instead of the
reference's Theano NCHW, and states are the device engine's
:class:`~rocalphago_tpu.engine.jaxgo.GoState` (use
:func:`~rocalphago_tpu.engine.jaxgo.from_pygo` at host boundaries).
"""

from __future__ import annotations

import functools

import jax

from rocalphago_tpu.engine.jaxgo import GoConfig, GoState
from rocalphago_tpu.features.planes import encode
from rocalphago_tpu.features.pyfeatures import (
    DEFAULT_FEATURES,
    FEATURE_PLANES,
    output_planes,
)


class Preprocess:
    """Jitted encoder over a fixed feature list and board config.

    ``feature_list`` entries name plane groups (see
    ``pyfeatures.FEATURE_PLANES``); the full default set is the 48-plane
    AlphaGo encoding.

    Ladder-plane capacity knobs (all static under jit):

    - ``ladder_depth``: max chase rungs read per ladder (default 40 —
      enough to cross a 19×19 board twice).
    - ``ladder_lanes``: max candidate (move, prey) pairs examined per
      plane (default 16).
    - ``ladder_chase_slots``: max ladder chases actually *run* per
      plane (default 4). Chases beyond capacity are SILENTLY dropped
      in board row-major candidate order and their cells read the
      conservative ``False`` (a truncated read never asserts a
      capture or an escape). Real positions essentially never hold
      >4 simultaneous live chases per color (randomized differential
      bound: <0.3% of cells; ``tests/test_features.py``), but dense
      whole-board ladder problems can — raise this (e.g. to 16) when
      encoding such positions; cost is roughly linear in the chase
      loop's width.
    """

    def __init__(self, feature_list=DEFAULT_FEATURES,
                 cfg: GoConfig = GoConfig(),
                 ladder_depth: int = 40, ladder_lanes: int = 16,
                 ladder_chase_slots: int = 4):
        unknown = [f for f in feature_list if f not in FEATURE_PLANES]
        if unknown:
            raise KeyError(f"unknown features: {unknown}")
        if not feature_list:
            raise ValueError("feature_list must name at least one feature")
        self.feature_list = tuple(feature_list)
        self.cfg = cfg
        self.output_dim = output_planes(self.feature_list)
        fn = functools.partial(
            encode, cfg, features=self.feature_list,
            ladder_depth=ladder_depth, ladder_lanes=ladder_lanes,
            ladder_chase_slots=ladder_chase_slots)
        self._one = jax.jit(fn)
        self._batch = jax.jit(jax.vmap(fn))

    def state_to_tensor(self, state: GoState) -> jax.Array:
        """One state → ``[1, size, size, F]`` float32."""
        return self._one(state)[None]

    def states_to_tensor(self, states: GoState) -> jax.Array:
        """Batched states (leading axis) → ``[B, size, size, F]``."""
        return self._batch(states)
