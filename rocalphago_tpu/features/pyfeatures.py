"""Host-side oracle feature encoder (slow, obviously-correct).

Computes the AlphaGo 48-plane set from a :class:`pygo.GameState` by
literal candidate-move simulation (``copy()`` + ``do_move``), the way
the reference's ``AlphaGo/preprocessing/preprocess.py::Preprocess``
does. Exists purely as the correctness oracle for the vectorized
device encoder (:mod:`rocalphago_tpu.features.planes`) — plane-by-plane
comparison in ``tests/test_features.py`` — and is not on any hot path.

Plane layout (48 total, in ``DEFAULT_FEATURES`` order):

========================  ======  =====================================
feature                   planes  semantics (all relative to player to
                                  move)
========================  ======  =====================================
board                     3       own stones / opponent stones / empty
ones                      1       constant 1
turns_since               8       age of stone: 0..6, 7+
liberties                 8       group liberties: 1..7, 8+
capture_size              8       opponent stones a legal move would
                                  capture: 0..6, 7+
self_atari_size           8       own-group size if the move leaves it
                                  in self-atari: 1..7, 8+
liberties_after           8       own-group liberties after the move:
                                  1..7, 8+
ladder_capture            1       move is a working ladder capture
ladder_escape             1       move is a working ladder escape
sensibleness              1       legal and does not fill own true eye
zeros                     1       constant 0
========================  ======  =====================================

One extra plane-group exists beyond the 48: ``color`` (1 plane,
constant 1 when black is to move) — the AlphaGo *value* network's 49th
input plane. Komi breaks color symmetry, so without it a value net
cannot distinguish a position from its color-swapped mirror (outcomes
differ by 2·komi). ``VALUE_FEATURES`` is the 49-plane value-net set.
"""

from __future__ import annotations

import os

import numpy as np

from rocalphago_tpu.engine import pygo

DEFAULT_FEATURES = (
    "board", "ones", "turns_since", "liberties", "capture_size",
    "self_atari_size", "liberties_after", "ladder_capture",
    "ladder_escape", "sensibleness", "zeros",
)

# the value net's 49-plane input: the 48 policy planes + player color
VALUE_FEATURES = DEFAULT_FEATURES + ("color",)

#: the two handcrafted ladder plane groups — ~88% of encode cost
#: (bench_encode.py no-ladder row), the target of the ladder-free
#: self-play configuration (docs/PERFORMANCE.md "Ladder-free encode")
LADDER_FEATURES = ("ladder_capture", "ladder_escape")


def ladder_planes_enabled() -> bool:
    """ROCALPHAGO_LADDER_PLANES: ``off``/``0`` drops both handcrafted
    ladder planes from NEW feature specs (the KataGo route: the net
    recovers the signal via global pooling + aux heads instead of the
    encoder paying for it every position). Default on — the shipped
    48/49-plane encoding. Read where specs are BORN (models/specs.py
    CLI, fresh-net defaults); nets loaded from a saved spec keep the
    feature list they were trained with regardless of this knob."""
    return os.environ.get("ROCALPHAGO_LADDER_PLANES", "on") \
        not in ("off", "0")


def active_features(features) -> tuple:
    """``features`` minus the ladder plane groups when
    ``ROCALPHAGO_LADDER_PLANES=off`` — unchanged (same tuple) when the
    knob is on, so the defaults-on path is bit-identical."""
    if ladder_planes_enabled():
        return tuple(features)
    return tuple(f for f in features if f not in LADDER_FEATURES)


def default_features() -> tuple:
    """Knob-aware policy feature set (48 planes, 46 ladder-free)."""
    return active_features(DEFAULT_FEATURES)


def value_features() -> tuple:
    """Knob-aware value feature set (49 planes, 47 ladder-free)."""
    return active_features(VALUE_FEATURES)

FEATURE_PLANES = {
    "board": 3, "ones": 1, "turns_since": 8, "liberties": 8,
    "capture_size": 8, "self_atari_size": 8, "liberties_after": 8,
    "ladder_capture": 1, "ladder_escape": 1, "sensibleness": 1,
    "zeros": 1, "color": 1,
}


def output_planes(features=DEFAULT_FEATURES) -> int:
    return sum(FEATURE_PLANES[f] for f in features)


def _one_hot8(plane_stack, x, y, value, lo):
    """Set plane ``clip(value - lo, 0, 7)`` at (x, y)."""
    plane_stack[x, y, min(max(value - lo, 0), 7)] = 1.0


def state_to_planes(st: pygo.GameState,
                    features=DEFAULT_FEATURES,
                    ladder_depth: int = 40) -> np.ndarray:
    """Encode ``st`` → float32 ``[size, size, F]`` (NHWC, TPU layout)."""
    size, me = st.size, st.current_player
    legal = {m for m in st.get_legal_moves(include_eyes=True)}
    out = []
    for name in features:
        f = np.zeros((size, size, FEATURE_PLANES[name]), np.float32)
        if name == "board":
            f[:, :, 0] = st.board == me
            f[:, :, 1] = st.board == -me
            f[:, :, 2] = st.board == 0
        elif name == "ones":
            f[:, :, 0] = 1.0
        elif name == "turns_since":
            for x in range(size):
                for y in range(size):
                    if st.board[x, y] != 0 and st.stone_ages[x, y] >= 0:
                        age = st.turns_played - 1 - st.stone_ages[x, y]
                        _one_hot8(f, x, y, age, 0)
        elif name == "liberties":
            for x in range(size):
                for y in range(size):
                    if st.board[x, y] != 0:
                        _one_hot8(f, x, y, st.liberty_count((x, y)), 1)
        elif name in ("capture_size", "self_atari_size", "liberties_after"):
            for (x, y) in legal:
                sim = st.copy()
                before = (sim.num_white_prisoners if me == pygo.BLACK
                          else sim.num_black_prisoners)
                sim.do_move((x, y))
                if name == "capture_size":
                    after = (sim.num_white_prisoners if me == pygo.BLACK
                             else sim.num_black_prisoners)
                    _one_hot8(f, x, y, after - before, 0)
                else:
                    stones, libs = sim.get_group((x, y))
                    if name == "liberties_after":
                        _one_hot8(f, x, y, len(libs), 1)
                    elif len(libs) == 1:
                        _one_hot8(f, x, y, len(stones), 1)
        elif name == "ladder_capture":
            for (x, y) in legal:
                if is_ladder_capture(st, (x, y), ladder_depth):
                    f[x, y, 0] = 1.0
        elif name == "ladder_escape":
            for (x, y) in legal:
                if is_ladder_escape(st, (x, y), ladder_depth):
                    f[x, y, 0] = 1.0
        elif name == "sensibleness":
            for (x, y) in legal:
                if not st.is_eye((x, y), me):
                    f[x, y, 0] = 1.0
        elif name == "zeros":
            pass
        elif name == "color":
            f[:, :, 0] = 1.0 if me == pygo.BLACK else 0.0
        else:
            raise KeyError(f"unknown feature {name!r}")
        out.append(f)
    return np.concatenate(out, axis=-1)


# ---------------------------------------------------------------- ladders


def _adjacent_groups(st: pygo.GameState, stones, color):
    """Distinct groups of ``color`` orthogonally adjacent to ``stones``
    (as a list of (stones, liberties) with duplicates removed)."""
    seen, out = set(), []
    for s in stones:
        for nb in st.get_neighbors(s):
            if st.board[nb] == color and nb not in seen:
                g_stones, g_libs = st.get_group(nb)
                seen |= g_stones
                out.append((g_stones, g_libs))
    return out


def ladder_captured(st: pygo.GameState, prey_point, depth: int) -> bool:
    """Full-branching depth-limited ladder read: is the group at
    ``prey_point`` captured with ``st.current_player`` to move?"""
    if depth <= 0:
        return False
    if st.board[prey_point] == 0:
        return True
    prey_color = st.board[prey_point]
    stones, libs = st.get_group(prey_point)
    to_move = st.current_player

    if to_move == prey_color:  # escaper
        if len(libs) >= 3:
            return False
        options = [lib for lib in libs if st.is_legal(lib)]
        for g_stones, g_libs in _adjacent_groups(st, stones, -prey_color):
            if len(g_libs) == 1:
                (cap,) = g_libs
                if st.is_legal(cap):
                    options.append(cap)
        for move in options:
            sim = st.copy()
            sim.do_move(move)
            if not ladder_captured(sim, prey_point, depth - 1):
                return False
        return True
    else:  # chaser
        if len(libs) >= 3:
            return False
        if len(libs) == 1:
            (last,) = libs
            return st.is_legal(last)
        for lib in libs:
            if st.is_legal(lib):
                sim = st.copy()
                sim.do_move(lib)
                if ladder_captured(sim, prey_point, depth - 1):
                    return True
        return False


def is_ladder_capture(st: pygo.GameState, action, depth: int = 40) -> bool:
    """Playing ``action`` starts a working ladder on an adjacent
    opponent group that currently has exactly two liberties."""
    me = st.current_player
    for nb in st.get_neighbors(action):
        if st.board[nb] == -me:
            _, libs = st.get_group(nb)
            if len(libs) == 2 and action in libs:
                sim = st.copy()
                sim.do_move(action)
                if ladder_captured(sim, nb, depth):
                    return True
    return False


def is_ladder_escape(st: pygo.GameState, action, depth: int = 40) -> bool:
    """Playing ``action`` rescues an own group in atari from a ladder
    (extension at its last liberty that then survives the read)."""
    me = st.current_player
    for nb in st.get_neighbors(action):
        if st.board[nb] == me:
            _, libs = st.get_group(nb)
            if len(libs) == 1 and action in libs:
                sim = st.copy()
                sim.do_move(action)
                if not ladder_captured(sim, nb, depth):
                    return True
    return False
