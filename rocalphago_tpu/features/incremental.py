"""Incremental 48-plane encoding: update from the move delta.

Self-play and MCTS visit SUCCESSIVE positions, so almost all of each
48-plane tensor's expensive analysis is unchanged ply-to-ply — yet the
from-scratch encoder re-reads every ladder every time, and the ladder
work (candidate openings + chases) dominates sequential encode cost
(BENCH_RESULTS.md "Encode A/B" / "Incremental encode"). This module is
the delta path: an :class:`EncodeCache` carried through the sequential
hot loops (a jit-compatible pytree) and an :func:`encode_step` that
recomputes only what a move can change:

* the cheap planes (board/liberties/turns-since aging, the
  candidate-simulation planes — all loop-free vector work over the
  played point, captured strings and the liberty frontier of adjacent
  strings) ride the exact same :func:`planes.encode_analysis` +
  :func:`planes.assemble_planes` code as the from-scratch path; on
  CPU their cost is op-dispatch-bound, so "recompute the dense vector
  pass" IS the cheapest correct delta (masking a vector op saves
  nothing — see docs/PERFORMANCE.md "Incremental encode");
* the two LADDER planes — the cost center — ride a per-lane outcome
  cache: every candidate lane's OPENING verdict (live chase needed /
  decided directly) and, when a pooled chase ran, its chase VERDICT
  are recorded together with one read FOOTPRINT (the chase's
  accumulated core expanded once by
  :func:`ladders._chase_read_region`) AND the record-time board. A
  cached outcome is consulted exactly while the CURRENT board matches
  the entry's recorded board on every footprint cell — a stone only
  flips a distant ladder if it lands on that ladder's recorded read
  region (the footprint rule). Unrelated stone churn therefore never
  KILLS an entry: the per-ply test is two-tier — a coarse per-board-
  region bitmask key (``REGION_BLOCK``² cell blocks packed into one
  uint32) cheaply clears entries whose footprint regions saw no churn
  at all, and only region-suspect entries pay the cell-exact
  comparison against their recorded board. An entry that fails the
  cell test goes DORMANT rather than dying — it revives the ply the
  board drifts back to its recorded footprint state (common around
  short capture/recapture exchanges), because the comparison is
  absolute, not a one-ply delta.

On the single-state sequential path (GTP root advance, ``Preprocess``
``advance``, ``bench_encode --trajectory``) the expensive blocks sit
behind ``lax.switch``/``lax.cond``, so a fully-warm ply pays only the
vector floor plus the candidate scan: openings run compacted to
``refresh_slots`` lanes only for lanes whose cache entry is missing or
invalidated (with a full-width fallback when more than
``refresh_slots`` lanes are dirty at once — correctness never depends
on the compaction), and the pooled chase plus footprint expansion run
only when some slotted lane lacks a valid verdict. Under ``vmap``
(:func:`batched_delta_encoder`) those conds lower to selects that
execute both branches, so the batched carry passes ``refresh_slots=0``
— openings always run full-width (same vector cost as the from-scratch
read) and the win is the verdict reuse itself, which cuts the
batch-lockstep rung-loop trips that dominate batched encode.

BIT-IDENTITY CONTRACT: ``encode_step`` produces exactly the planes of
``planes.encode`` at every ply, warm or cold — the delta path must
never be "approximately" right. The mechanism: candidate enumeration,
slot assignment and overflow truncation are recomputed fresh each ply
by the SAME code as the from-scratch shared-gated read, so the read's
COVERAGE is identical; a cached opening outcome / chase verdict is
only consulted where the memoization induction proves it equal to the
fresh computation (no footprint cell changed ⇒ each ply of a re-run
read sees only unchanged cells ⇒ identical decisions). Pinned by
``tests/test_incremental.py``: trajectory fuzz (multi-stone captures,
ko, edge/corner ladders, passes) asserting bit-identity against the
from-scratch ``Preprocess`` at every ply with the ``pyfeatures``
oracle as the independent check.

The cached read always traces the default SHARED/XLA chase
formulation; the ``ROCALPHAGO_LADDER_GATE=split`` and pallas-kernel
A/B knobs apply to the from-scratch path only.

COLD / INVALIDATED caches are not an error path: a cold cache simply
has no valid entries, so every lane refreshes and every live chase
runs (and records), which IS the from-scratch shared read plus
footprint bookkeeping. Host boundaries (``Preprocess.advance``, the
GTP root advance) still reset the cache explicitly on new games /
rewinds / board switches — see ``features/api.py`` — and count the
reason (``encode_cache_resets_total{reason=...}``).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from rocalphago_tpu.engine.jaxgo import (
    GoConfig,
    GoState,
    neighbor_analysis,
    step,
)
from rocalphago_tpu.features.ladders import (
    _candidate_lanes,
    _compact_indices,
    _capture_opening,
    _chase,
    _chase_read_regions,
    _escape_opening,
    _phase1_depth,
)
from rocalphago_tpu.features.planes import (
    assemble_planes,
    encode_analysis,
)

#: default outcome-ring capacity. Ring retention must comfortably
#: exceed the reuse distance or the cache sits in an eviction-forced
#: refresh equilibrium (measured on dense 19×19 random tails: a
#: 48-entry ring rotated itself dry and refreshes pinned at the
#: record width; 128 leaves invalidation, not eviction, as the
#: limiting factor). [V, N] bools are small (46 KB at 19×19).
VERDICT_SLOTS = 128

#: how many dirty CAPTURE / ESCAPE lanes one encode refreshes
#: compacted (and records). More lanes than this dirty at once falls
#: back to that kind's full-width opening pass — correctness never
#: depends on the compaction. Segregated by kind so each opening
#: algebra runs once at its own width instead of both running over
#: one mixed set. MEASURED DEFAULT (8, 4): the 19×19 random-tail A/B
#: (``bench_encode.py --trajectory``) ran ~2350 µs/pos at (8, 4) vs
#: ~2600 at (12, 6) and ~2500 at (4, 2) — wide enough that full-width
#: fallbacks stay rare (13 in a 100-ply dense tail), narrow enough
#: that the per-ply record/expansion work stops paying for idle lanes.
REFRESH_SLOTS = (8, 4)

# stats vector layout (int32 [9], accumulated on device; host
# boundaries snapshot it into the obs registry — see features/api.py,
# which iterates STAT_FIELDS generically, so new fields flow straight
# to ``encode_incr_<field>_total`` counters). The last three are the
# invalidation-cascade view: ``foot_hits`` counts region-coarse key
# hits (entries whose footprint REGIONS saw churn and paid the
# cell-exact test), ``entries_invalidated`` the subset that actually
# failed it and went dormant, ``verdict_flips`` the chases forced by
# a dormant entry's cached verdict (re-chases of known ladders — the
# cascade's cost), and ``entries_revived`` dormant entries whose
# footprint drifted back to its recorded state.
(STAT_ENCODES, STAT_REFRESHED, STAT_CHASES, STAT_REUSED,
 STAT_INVALIDATED, STAT_FALLBACKS, STAT_FOOT_HITS, STAT_FLIPS,
 STAT_REVIVED) = range(9)
STAT_FIELDS = ("encodes", "lanes_refreshed", "chases_run",
               "verdicts_reused", "entries_invalidated",
               "refresh_fallbacks", "foot_hits", "verdict_flips",
               "entries_revived")

#: side length of the square cell blocks the coarse footprint keys
#: quantize the board into. One uint32 bit per block: 4 → 25 regions
#: at 19×19 (the bitmask folds mod 32 on boards that would exceed 32
#: regions — still sound, just coarser).
REGION_BLOCK = 4


def _region_ids(cfg: GoConfig):
    """int32 [N]: each cell's coarse-region bit position (< 32)."""
    size = cfg.size
    per_row = -(-size // REGION_BLOCK)
    flat = jnp.arange(cfg.num_points)
    rid = ((flat // size) // REGION_BLOCK) * per_row \
        + (flat % size) // REGION_BLOCK
    return rid % 32


def _region_bits(cfg: GoConfig, cells):
    """Pack a cell mask (bool [..., N]) into its coarse-region
    bitmask (uint32 [...]): bit r set iff any cell of region r is
    set. Two footprints can interact only if their bitmasks AND —
    the cheap first tier of the invalidation test."""
    onehot = _region_ids(cfg)[:, None] == jnp.arange(32)[None, :]
    hit = (cells[..., :, None] & onehot).any(axis=-2)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    # regions are distinct bits, so the sum IS the bitwise OR
    return (hit * weights).sum(axis=-1, dtype=jnp.uint32)


def enabled(default: bool) -> bool:
    """Resolve the one incremental-encode knob,
    ``ROCALPHAGO_ENCODE_INCR``: unset → the calling path's measured
    default (sequential single-state paths pass True, the batched
    self-play loop passes False — see
    ``selfplay.incremental_default``), ``"1"``/``"0"`` → force
    on/off everywhere (the bench A/B lever). Read at trace/build
    time, like the ladder knobs."""
    import os

    v = os.environ.get("ROCALPHAGO_ENCODE_INCR", "")
    if v == "":
        return default
    return v == "1"


class EncodeCache(NamedTuple):
    """Delta-encode carry: the previous board + the per-lane ladder
    outcome ring with the dependency metadata needed to invalidate it.

    All arrays are fixed-shape (``N = size²``, ``V = ring capacity``);
    the cache is a pytree — vmap it over games for the batched
    self-play carry (:func:`init_caches`). An entry is keyed by the
    lane identity ``(move, prey root, prey color, lane kind)`` and
    holds the opening outcome (``need``/``direct``), the pooled-chase
    verdict when one ran (``verdict`` valid iff ``has_verdict``), and
    the dependency guard: the read footprint, its coarse-region
    bitmask key, and the record-time board the footprint cells are
    revalidated against (an entry is CONSULTED while the current
    board matches ``entry_board`` on every ``entry_foot`` cell — a
    mismatched entry is dormant, not dead, and revives if the board
    drifts back)."""

    board: jax.Array            # int8 [N]  board at the last encode
    entry_key: jax.Array        # int32 [V] packed lane key: move |
    #   prey_root << 10 | (prey_color + 1) << 20 | kind << 22
    #   (-1 = never written; packed keys are always >= 0)
    entry_need: jax.Array       # bool [V]  opening → live chase needed
    entry_direct: jax.Array     # bool [V]  opening → decided directly
    entry_verdict: jax.Array    # bool [V]  chase verdict (captured)
    entry_has_verdict: jax.Array  # bool [V]
    entry_valid: jax.Array      # bool [V]  slot written & not superseded
    entry_foot: jax.Array       # bool [V, N] recorded read footprint
    entry_board: jax.Array      # int8 [V, N] board at record time —
    #   only its entry_foot cells are ever consulted
    entry_footmask: jax.Array   # uint32 [V] coarse-region key of foot
    entry_clean: jax.Array      # bool [V] footprint regions unchurned
    #   since the last passing cell test (clean ⇒ board matches
    #   entry_board on entry_foot — the cell test is skipped)
    entry_live: jax.Array       # bool [V] last ply's consult verdict
    #   (valid & cell-test pass) — transition bookkeeping for the
    #   invalidated/revived stats
    ptr: jax.Array              # int32 []  ring write pointer
    stats: jax.Array            # int32 [9] see STAT_FIELDS


def init_cache(cfg: GoConfig,
               verdict_slots: int = VERDICT_SLOTS) -> EncodeCache:
    """A cold cache: no valid entries, empty previous board (which is
    also exactly right for a fresh game)."""
    n = cfg.num_points
    v = verdict_slots
    return EncodeCache(
        board=jnp.zeros((n,), jnp.int8),
        entry_key=jnp.full((v,), -1, jnp.int32),
        entry_need=jnp.zeros((v,), jnp.bool_),
        entry_direct=jnp.zeros((v,), jnp.bool_),
        entry_verdict=jnp.zeros((v,), jnp.bool_),
        entry_has_verdict=jnp.zeros((v,), jnp.bool_),
        entry_valid=jnp.zeros((v,), jnp.bool_),
        entry_foot=jnp.zeros((v, n), jnp.bool_),
        entry_board=jnp.zeros((v, n), jnp.int8),
        entry_footmask=jnp.zeros((v,), jnp.uint32),
        entry_clean=jnp.zeros((v,), jnp.bool_),
        entry_live=jnp.zeros((v,), jnp.bool_),
        ptr=jnp.int32(0),
        stats=jnp.zeros((len(STAT_FIELDS),), jnp.int32),
    )


def init_caches(cfg: GoConfig, batch: int,
                verdict_slots: int = VERDICT_SLOTS) -> EncodeCache:
    """A batch of cold caches (leading axis on every leaf) — the
    self-play loop's carry sibling of ``jaxgo.new_states``."""
    one = init_cache(cfg, verdict_slots)
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x, (batch,) + x.shape), one)


def ladder_planes_cached(cfg: GoConfig, state: GoState, gd, legal,
                         cache: EncodeCache, depth: int = 40,
                         lanes: int = 16, chase_slots: int = 6,
                         refresh_slots=REFRESH_SLOTS):
    """Both ladder planes through the per-lane outcome cache:
    ``(ladder_capture [N], ladder_escape [N], cache')``.

    Same three gates as ``ladders.ladder_planes`` (candidate gating,
    slot gating, shared pooled chase slots) — candidate enumeration,
    slot assignment and overflow truncation are recomputed fresh, so
    the read's COVERAGE is bit-identical to the from-scratch shared
    formulation. The deltas: a lane whose ``(move, prey root, prey
    color, kind)`` matches a still-valid entry reuses the recorded
    opening outcome (skipping its opening algebra) and, when the
    entry carries a chase verdict, reuses that too while still
    CONSUMING its chase slot (coverage parity); only dirty lanes run
    openings (compacted per kind to ``refresh_slots = (capture,
    escape)`` widths; that kind's full-width fallback beyond) and
    only slotted lanes without a valid verdict chase.

    ``refresh_slots=0`` disables the compaction branches entirely
    (openings always full-width, gated to the refresh lanes) — the
    right trace under ``vmap``, where ``lax.switch`` would execute
    every branch anyway.

    Invalidation is two-tier and ABSOLUTE (not a one-ply delta):

    1. coarse-region keys — the one-ply churn ``board !=
       cache.board`` is packed into a per-region uint32 bitmask
       (:func:`_region_bits`); entries whose footprint-region key
       doesn't intersect it provably still match their recorded
       board (the carried ``entry_clean`` invariant) and skip tier 2;
    2. cell-exact revalidation — region-suspect entries compare the
       CURRENT board against their RECORD-TIME board on their exact
       footprint cells. A match means every re-run of the recorded
       read would see identical cells (the memoization induction), so
       the entry is consulted as if untouched — churn in the region's
       slop cells, or churn that has since reverted (capture /
       recapture), costs nothing. Only a genuine footprint mismatch
       makes the entry DORMANT: unmatched by lookups, so its lane
       re-opens (and re-chases if still live) and re-records — but
       the entry itself persists until superseded and revives if the
       board drifts back to its recorded footprint state.
    """
    n = cfg.num_points
    v = cache.entry_key.shape[0]
    k = 2 * lanes
    wc, we = refresh_slots if refresh_slots else REFRESH_SLOTS
    wc, we = min(wc, lanes), min(we, lanes)
    rec = wc + we
    if v < rec:
        raise ValueError(
            f"outcome ring ({v}) must hold at least one encode's "
            f"record width ({rec})")
    iota = jnp.arange(n)

    # --- 1. candidates: fresh every ply, same code as from-scratch ---
    analysis = neighbor_analysis(cfg, state.board, gd.labels)
    cap_mv, cap_pr, cap_ok = _candidate_lanes(
        cfg, state, gd, legal, prey_libs=2, prey_is_opp=True,
        lanes=lanes, analysis=analysis)
    esc_mv, esc_pr, esc_ok = _candidate_lanes(
        cfg, state, gd, legal, prey_libs=1, prey_is_opp=False,
        lanes=lanes, analysis=analysis)
    mv = jnp.concatenate([cap_mv, esc_mv])
    pr = jnp.concatenate([cap_pr, esc_pr])
    ok = jnp.concatenate([cap_ok, esc_ok])
    kind = jnp.concatenate([jnp.zeros((lanes,), jnp.int8),
                            jnp.ones((lanes,), jnp.int8)])
    pr_safe = jnp.minimum(pr, n - 1)       # garbage lanes: ok=False
    prey_root = gd.labels[pr_safe]
    prey_color = state.board[pr_safe]
    lane_key = (mv | (prey_root << 10)
                | ((prey_color.astype(jnp.int32) + 1) << 20)
                | (kind.astype(jnp.int32) << 22))

    # --- 2. invalidate + look up: tier 1, the coarse-region keys —
    # one uint32 AND against the ply's churn bitmask clears entries
    # whose footprint regions saw nothing (entry_clean invariant:
    # clean ⇒ board still matches entry_board on entry_foot) ---
    changed = state.board != cache.board
    churn_bits = _region_bits(cfg, changed)
    region_hit = (cache.entry_footmask & churn_bits) != 0
    clean = cache.entry_clean & ~region_hit
    suspect = cache.entry_valid & ~clean
    foot_hits = (cache.entry_valid & region_hit).sum(dtype=jnp.int32)

    # tier 2, cell-exact revalidation of the suspects: absolute
    # comparison against the RECORD-TIME board restricted to the
    # recorded footprint — region slop and reverted churn pass and
    # cost nothing; a genuine mismatch makes the entry dormant (it
    # revives if the board drifts back). Skipped entirely on plies
    # with no suspects (the common warm ply).
    def cell_test(_):
        return ((state.board[None, :] != cache.entry_board)
                & cache.entry_foot).any(axis=-1)

    cellbad = suspect & lax.cond(
        suspect.any(), cell_test,
        lambda _: jnp.zeros((v,), jnp.bool_), None)
    live = cache.entry_valid & ~cellbad
    entry_clean = cache.entry_valid & ~cellbad
    invalidated = (cache.entry_live & ~live).sum(dtype=jnp.int32)
    revived = (live & cache.entry_valid
               & ~cache.entry_live).sum(dtype=jnp.int32)

    keymatch = cache.entry_key[None, :] == lane_key[:, None]   # [K, V]
    match = live[None, :] & keymatch
    hit = match.any(axis=-1) & ok
    ent = jnp.argmax(match, axis=-1)
    c_need = cache.entry_need[ent] & hit
    c_direct = cache.entry_direct[ent] & hit
    c_has = cache.entry_has_verdict[ent] & hit
    c_verdict = cache.entry_verdict[ent]
    # a lane whose key matches only a DORMANT verdict entry is a
    # verdict flip when it actually re-chases (the cascade stat)
    dormant_verdict = ((cache.entry_valid & ~live
                        & cache.entry_has_verdict)[None, :]
                       & keymatch).any(axis=-1)

    # --- 3. refresh set: unknown opening, or a verdict gap (a hit
    # lane that needs a chase but has no recorded verdict must re-open
    # so the chase has its opening board) — UNLESS the gap lane
    # certainly cannot win a chase slot this ply: lanes that are
    # certainly needing (hit with a cached need) and ahead of it in
    # lane order already fill the slots. Without that guard a
    # persistent overflow lane (need, no slot, hence never a verdict)
    # would drag the opening pass into every otherwise-warm ply.
    # Sound: certain-need lanes are a SUBSET of the actual need lanes,
    # so "certain rank ≥ slots" implies "actual rank ≥ slots" = no
    # slot = no chase = its opening board is never consumed. Compacted
    # PER KIND so each opening algebra runs once at its own width. ---
    certain_before = jnp.cumsum(
        jnp.concatenate([jnp.zeros((1,), jnp.int32),
                         (hit & c_need).astype(jnp.int32)[:-1]]))
    gap = c_need & ~c_has & (certain_before < chase_slots)
    refresh = ok & (~hit | gap)
    nref = refresh.sum(dtype=jnp.int32)

    def kind_openings(opening_fn, kmv, kpr, kref, w):
        """One kind's openings over its refresh lanes: compacted to
        ``w`` when they fit, that kind's full width beyond (the
        fallback that keeps compaction a pure optimization), skipped
        when clean. Returns full-width rows + the compact index."""
        nk = kref.sum(dtype=jnp.int32)
        idx = _compact_indices(kref, w, lanes)
        valid = idx < lanes
        safe = jnp.where(valid, idx, 0)
        zb = jnp.broadcast_to(state.board, (lanes, n))
        zl = jnp.broadcast_to(gd.labels, (lanes, n))
        zf = jnp.zeros((lanes,), jnp.bool_)

        def none(_):
            return zb, zl, zf, zf

        def compact(_):
            bw, lw, nw, dw = opening_fn(
                cfg, state, gd, kmv[safe], kpr[safe],
                valid & kref[safe])
            return (zb.at[idx].set(bw, mode="drop"),
                    zl.at[idx].set(lw, mode="drop"),
                    zf.at[idx].set(nw, mode="drop"),
                    zf.at[idx].set(dw, mode="drop"))

        def full(_):
            return opening_fn(cfg, state, gd, kmv, kpr, kref)

        if refresh_slots:
            branch = (nk > 0).astype(jnp.int32) + \
                (nk > w).astype(jnp.int32)
            out = lax.switch(branch, (none, compact, full), None)
        else:
            out = full(None)
        return out + (idx, valid, nk)

    cb, cl, cn, cd, cridx, crvalid, ncap = kind_openings(
        _capture_opening, cap_mv, cap_pr, refresh[:lanes], wc)
    eb, el, en, ed, eridx, ervalid, nesc = kind_openings(
        _escape_opening, esc_mv, esc_pr, refresh[lanes:], we)
    boards_f = jnp.concatenate([cb, eb])
    labels_f = jnp.concatenate([cl, el])
    need_f = jnp.concatenate([cn, en])
    direct_f = jnp.concatenate([cd, ed])
    ridx = jnp.concatenate([cridx, eridx + lanes])
    ridx = jnp.where(jnp.concatenate([crvalid, ervalid]), ridx, k)
    rvalid = ridx < k
    rsafe = jnp.where(rvalid, ridx, 0)
    fellback = (ncap > wc) | (nesc > we)

    zero_f = jnp.zeros((k,), jnp.bool_)
    need = jnp.where(hit, c_need, need_f) & ok
    direct = jnp.where(hit, c_direct, direct_f) & ok

    # --- 4. slot assignment over ALL need-lanes (coverage parity with
    # the from-scratch shared pool: hit lanes consume slots too) ---
    slot_idx = _compact_indices(need, chase_slots, k)
    svalid = slot_idx < k
    ssafe = jnp.where(svalid, slot_idx, 0)
    covered = zero_f.at[slot_idx].set(svalid, mode="drop")
    run = svalid & ~(hit & c_has)[ssafe]
    any_run = run.any()

    # --- 5. pooled chase, only when some slotted lane lacks a verdict.
    # Lanes with reused verdicts enter disabled (zero trips). Collects
    # each chase's read CORE, seeded with the opening's board diff.
    # The verdict cache usually leaves only 1–2 lanes actually running
    # — those skip the slots-wide lockstep phase entirely and chase
    # scalar at full depth (the schedule is internal: verdicts are
    # identical either way); 3+ running lanes take the same two-phase
    # schedule as ladders._compacted_chase. ---
    d1 = min(_phase1_depth(), depth)

    def chase_block(_):
        prey = pr_safe[ssafe]
        boards_s = boards_f[ssafe]
        labels_s = labels_f[ssafe]
        open_core = ((gd.labels[None, :] == prey_root[ssafe][:, None])
                     & (state.board != 0)[None, :]
                     | (iota[None, :] == mv[ssafe][:, None])
                     | (boards_s != state.board[None, :]))
        zero_cap = jnp.zeros((chase_slots,), jnp.bool_)
        zero_core = jnp.zeros((chase_slots, n), jnp.bool_)

        def narrow(_):
            widx = _compact_indices(run, 2, chase_slots)
            capt, core = zero_cap, zero_core
            for j in range(2):
                live = widx[j] < chase_slots
                at = jnp.where(live, widx[j], 0)
                cap_j, core_j = _chase(
                    cfg, boards_s[at], labels_s[at], prey[at], depth,
                    enabled=live, collect_core=True,
                    core0=open_core[at])
                capt = capt.at[widx[j]].set(cap_j, mode="drop")
                core = core.at[widx[j]].set(core_j, mode="drop")
            return capt, core

        def wide(_):
            captured, unres, b_end, lab_end, core = jax.vmap(
                lambda b, l, p, en, c0: _chase(
                    cfg, b, l, p, d1, enabled=en, return_state=True,
                    collect_core=True, core0=c0))(
                    boards_s, labels_s, prey, run, open_core)
            if depth > d1:
                deep_idx = _compact_indices(unres, chase_slots,
                                            chase_slots)
                for s in range(chase_slots):
                    idx = deep_idx[s]
                    live = idx < chase_slots
                    at = jnp.where(live, idx, 0)
                    cap_s, core_s = _chase(
                        cfg, b_end[at], lab_end[at], prey[at],
                        depth - d1, enabled=live, collect_core=True,
                        core0=core[at])
                    captured = captured.at[idx].set(cap_s,
                                                    mode="drop")
                    core = core.at[idx].set(core_s, mode="drop")
            return captured, core

        captured, core = lax.cond(
            run.sum(dtype=jnp.int32) <= 2, narrow, wide, None)
        return captured & run, core & run[:, None]

    chased_s, core_s = lax.cond(
        any_run, chase_block,
        lambda _: (jnp.zeros((chase_slots,), jnp.bool_),
                   jnp.zeros((chase_slots, n), jnp.bool_)), None)
    chased = zero_f.at[slot_idx].set(chased_s, mode="drop")
    ran = zero_f.at[slot_idx].set(run, mode="drop")
    chase_core = jnp.zeros((k, n), jnp.bool_).at[slot_idx].set(
        core_s, mode="drop")

    # --- 6. planes: the from-scratch formulas, verdicts from cache or
    # chase (an uncovered overflow lane reads the conservative False
    # on both planes either way) ---
    verdict = jnp.where(hit & c_has, c_verdict, chased)
    captured_lane = direct[:lanes] | (
        need[:lanes] & covered[:lanes] & verdict[:lanes])
    escaped_lane = direct[lanes:] | (
        need[lanes:] & covered[lanes:] & ~verdict[lanes:])
    plane_cap = jnp.zeros((n,), jnp.bool_).at[cap_mv].max(
        captured_lane & cap_ok)
    plane_esc = jnp.zeros((n,), jnp.bool_).at[esc_mv].max(
        escaped_lane & esc_ok)

    # --- 7. record the refreshed lanes (first `rec` in lane order —
    # beyond that is only a reuse loss, never a correctness one).
    # One footprint expansion per recorded lane over the merged
    # opening+chase core, against the encode-time board. ---
    any_rec = rvalid.any()

    def expand_block(_):
        open_core_w = ((gd.labels[None, :]
                        == prey_root[rsafe][:, None])
                       & (state.board != 0)[None, :]
                       | (iota[None, :] == mv[rsafe][:, None])
                       | (boards_f[rsafe] != state.board[None, :]))
        core_w = (open_core_w | chase_core[rsafe]) & rvalid[:, None]
        foot = _chase_read_regions(cfg, state.board, gd.labels,
                                   core_w)
        return foot, _region_bits(cfg, foot)

    foot_w, footbits_w = lax.cond(
        any_rec, expand_block,
        lambda _: (jnp.zeros((rec, n), jnp.bool_),
                   jnp.zeros((rec,), jnp.uint32)), None)

    # entries superseded by a re-recorded lane die before the ring
    # write — dormant twins included, else a later revival could
    # shadow the fresher entry (either would be correct — each entry
    # is a self-contained memoization — but one canonical entry per
    # key keeps the ring honest)
    rec_lane = zero_f.at[ridx].set(True, mode="drop")
    superseded = (keymatch & rec_lane[:, None]).any(axis=0)

    dest = jnp.where(rvalid, (cache.ptr + jnp.arange(rec)) % v, v)
    n_new = rvalid.sum(dtype=jnp.int32)
    new_cache = cache._replace(
        board=state.board,
        entry_key=cache.entry_key.at[dest].set(
            lane_key[rsafe], mode="drop"),
        entry_need=cache.entry_need.at[dest].set(
            need_f[rsafe], mode="drop"),
        entry_direct=cache.entry_direct.at[dest].set(
            direct_f[rsafe], mode="drop"),
        entry_verdict=cache.entry_verdict.at[dest].set(
            chased[rsafe], mode="drop"),
        entry_has_verdict=cache.entry_has_verdict.at[dest].set(
            ran[rsafe], mode="drop"),
        entry_valid=(cache.entry_valid & ~superseded).at[dest].set(
            rvalid, mode="drop"),
        entry_foot=cache.entry_foot.at[dest].set(
            foot_w, mode="drop"),
        entry_board=cache.entry_board.at[dest].set(
            jnp.broadcast_to(state.board, (rec, n)), mode="drop"),
        entry_footmask=cache.entry_footmask.at[dest].set(
            footbits_w, mode="drop"),
        entry_clean=(entry_clean & ~superseded).at[dest].set(
            rvalid, mode="drop"),
        entry_live=(live & ~superseded).at[dest].set(
            rvalid, mode="drop"),
        ptr=(cache.ptr + n_new) % v,
        # one vector add, not nine scalar scatters — the warm path is
        # op-dispatch-bound on CPU (STAT_* layout)
        stats=cache.stats + jnp.stack(
            [jnp.int32(0),
             nref,
             run.sum(dtype=jnp.int32),
             (svalid & (hit & c_has)[ssafe]).sum(dtype=jnp.int32),
             invalidated,
             fellback.astype(jnp.int32),
             foot_hits,
             (run & dormant_verdict[ssafe]).sum(dtype=jnp.int32),
             revived]),
    )
    return plane_cap, plane_esc, new_cache


def encode_step(cfg: GoConfig, state: GoState, cache: EncodeCache,
                features: tuple = None,
                ladder_depth: int = 40, ladder_lanes: int = 16,
                ladder_chase_slots: int = 6,
                refresh_slots=REFRESH_SLOTS,
                gd=None):
    """Encode ``state`` against the cache of the PREVIOUS position →
    ``(planes [size, size, F], cache')``.

    Bit-identical to ``planes.encode(cfg, state, ...)`` at every call
    (see the module docstring's contract); the cache only modulates
    how much ladder work actually runs. The O(N) aging pass for the
    turns-since planes, the board/liberty planes and the
    candidate-simulation planes ride the exact same
    ``encode_analysis`` + ``assemble_planes`` code as the from-scratch
    path. Feature sets without both ladder planes get no reuse
    (nothing expensive to reuse) but keep the carry contract.
    """
    from rocalphago_tpu.features.pyfeatures import DEFAULT_FEATURES

    if features is None:
        features = DEFAULT_FEATURES
    gd, ci, legal = encode_analysis(cfg, state, features, gd)
    lad_kw = dict(depth=ladder_depth, lanes=ladder_lanes,
                  chase_slots=ladder_chase_slots)
    lad_cap = lad_esc = None
    if "ladder_capture" in features and "ladder_escape" in features:
        lad_cap, lad_esc, cache = ladder_planes_cached(
            cfg, state, gd, legal, cache,
            refresh_slots=refresh_slots, **lad_kw)
    else:
        cache = cache._replace(board=state.board)
    cache = cache._replace(
        stats=cache.stats.at[STAT_ENCODES].add(1))
    planes = assemble_planes(cfg, state, features, gd, ci, legal,
                             lad_cap, lad_esc, lad_kw)
    return planes, cache


def encode_delta(cfg: GoConfig, prev_state: GoState,
                 cache: EncodeCache, move, features: tuple = None,
                 **encode_kwargs):
    """Play ``move`` (flat index, ``N`` = pass) on ``prev_state`` and
    delta-encode the successor → ``(planes, cache')``.

    Convenience form of the carry contract for callers that hold the
    previous position and the move; callers that already stepped the
    engine (the fused self-play ply) call :func:`encode_step` on the
    successor directly — the two are equivalent because the cache
    diffs boards, not moves.
    """
    new_state = step(cfg, prev_state, jnp.asarray(move, jnp.int32))
    return encode_step(cfg, new_state, cache, features=features,
                       **encode_kwargs)


def batched_delta_encoder(cfg: GoConfig, features: tuple,
                          **encode_kwargs):
    """``(states, caches, gd=None) -> (planes [B, s, s, F], caches')``
    — the delta sibling of ``planes.batched_encoder``, for the fused
    sequential hot loops (the self-play ply carry). Callers holding a
    per-ply ``jaxgo.group_data`` pass it to share the analysis, same
    convention as the from-scratch encoder.

    Traces with ``refresh_slots=0`` (full-width openings, no host
    branches) unless overridden: under ``vmap`` the single-state
    path's ``lax.switch`` branches all execute as selects, so the
    compaction would cost MORE than it saves — the batched win is the
    verdict reuse cutting the lockstep rung-loop trips."""
    encode_kwargs.setdefault("refresh_slots", 0)
    one = functools.partial(encode_step, cfg, features=features,
                            **encode_kwargs)
    with_gd = jax.vmap(lambda s, c, g: one(s, c, gd=g))
    no_gd = jax.vmap(lambda s, c: one(s, c))

    def enc(states: GoState, caches: EncodeCache, gd=None):
        return (no_gd(states, caches) if gd is None
                else with_gd(states, caches, gd))

    return enc
