"""48-plane AlphaGo feature encoding, device-native.

Parity target: the reference's ``AlphaGo/preprocessing/preprocess.py``
(SURVEY.md §1 L1). Public surface:

* :class:`Preprocess` — jitted encoder (``state_to_tensor``,
  ``output_dim``), NHWC layout;
* :data:`DEFAULT_FEATURES` / :data:`FEATURE_PLANES` — the feature-name
  ⇄ plane-count contract shared with saved model specs;
* :mod:`pyfeatures` — the slow host oracle used by tests.
"""

from rocalphago_tpu.features.api import Preprocess  # noqa: F401
from rocalphago_tpu.features.pyfeatures import (  # noqa: F401
    DEFAULT_FEATURES,
    FEATURE_PLANES,
    LADDER_FEATURES,
    VALUE_FEATURES,
    active_features,
    default_features,
    ladder_planes_enabled,
    output_planes,
    value_features,
)
