"""Device-side 48-plane encoder: pure jitted function of engine state.

The reference encoder (``AlphaGo/preprocessing/preprocess.py``) loops
over board cells in Python and *simulates each candidate move* with
``state.copy() + do_move`` for the capture-size / self-atari /
liberties-after planes — its famous hot spot (SURVEY.md §3.2). Here the
same planes are **exact** but come from dense bitmap algebra on the
engine's :class:`~rocalphago_tpu.engine.jaxgo.GroupData`:

* a candidate's captures are its ≤4 deduped neighbor groups in atari —
  sizes come from ``gd.sizes``, captured stones from ``gd.member``;
* the merged own group after the move is ``{p} ∪ own neighbor groups``
  (bitmap OR), its liberties ``|dilate(M) ∩ new_empty|`` where
  ``new_empty`` adds the captured points — one [N,4,N] gather instead
  of N board simulations.

Everything vmaps over games; no per-cell Python anywhere.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from rocalphago_tpu.engine.jaxgo import (
    neighbor_analysis,
    GoConfig,
    GoState,
    GroupData,
    _dedup_mask,
    diagonals_for,
    group_data,
    legal_mask,
    neighbors_for,
)


class CandidateInfo(NamedTuple):
    """Per-candidate-move analysis (valid where the move is legal)."""

    capture_size: jax.Array     # int32 [N] opponent stones captured
    own_size_after: jax.Array   # int32 [N] own merged-group size
    libs_after: jax.Array       # int32 [N] own merged-group liberties
    legal: jax.Array            # bool  [N] board moves only (no pass)


@functools.lru_cache(maxsize=None)
def _packed_consts(size: int):
    """Trace-time constants of the packed-bitmap board representation
    (bit ``c % 32`` of word ``c // 32`` is cell ``c``): per-cell word
    index / bit value, the packed identity rows, and the not-col-0 /
    not-col-last masks the E/W bitstream shifts use."""
    import numpy as np

    n = size * size
    w = (n + 31) // 32
    cells = np.arange(n)
    word = cells // 32
    bit = np.uint32(1) << (cells % 32).astype(np.uint32)
    eye = np.zeros((n, w), np.uint32)
    eye[cells, word] = bit
    notcol0 = np.zeros((w,), np.uint32)
    notcol_last = np.zeros((w,), np.uint32)
    for c in cells:
        if c % size != 0:
            notcol0[c // 32] |= np.uint32(1) << np.uint32(c % 32)
        if c % size != size - 1:
            notcol_last[c // 32] |= np.uint32(1) << np.uint32(c % 32)
    # numpy, not jnp: these are cached across jit traces, and a jnp
    # constant materialized inside one trace may not escape to another
    return (word.astype(np.int32), bit, eye, notcol0, notcol_last)


def _packed_shift(x: jax.Array, k: int) -> jax.Array:
    """Shift packed bitstreams (uint32 [..., W]) toward HIGHER cell
    indices by ``k`` bits (negative = lower), zero-filled; requires
    ``0 < |k| < 32``."""
    if k > 0:
        prev = jnp.concatenate(
            [jnp.zeros_like(x[..., :1]), x[..., :-1]], axis=-1)
        return (x << k) | (prev >> (32 - k))
    nxt = jnp.concatenate(
        [x[..., 1:], jnp.zeros_like(x[..., :1])], axis=-1)
    return (x >> -k) | (nxt << (32 + k))


def _packed_dilate(size: int, x: jax.Array) -> jax.Array:
    """Packed-bitmap 4-neighborhood dilation: self ∪ N/S (bitstream
    shift by ±size, falls off the ends) ∪ E/W (shift by ±1, row edges
    masked so file-a/file-last never wrap)."""
    _, _, _, notcol0, notcol_last = _packed_consts(size)
    return (x
            | _packed_shift(x, size) | _packed_shift(x, -size)
            | (_packed_shift(x, 1) & notcol0)
            | (_packed_shift(x, -1) & notcol_last))


def candidate_info(cfg: GoConfig, state: GoState,
                   gd: GroupData) -> CandidateInfo:
    """Exact capture/merge/liberty analysis of every candidate move.

    The merged-group / captured-point bitmaps are PACKED (uint32 words
    over cells, built straight from ``gd.labels`` by one scatter-add —
    distinct bits of a word never collide, so add IS bitwise-or);
    dilation is bitstream shifts and the liberty count a population
    count. The dense [N, 4, N] member gather + boolean reductions this
    replaces were ~70% of the whole non-ladder encode on CPU
    (sequential profile, PR 6); ``gd.member`` is no longer read.
    """
    n = cfg.num_points
    board, me = state.board, state.turn
    empty = board == 0
    word, bitval, eye_p, _, _ = _packed_consts(cfg.size)
    w = eye_p.shape[-1]

    nbr_color, nbr_root, uniq, _ = neighbor_analysis(cfg, board, gd.labels)

    own_k = uniq & (nbr_color == me)
    cap_k = uniq & (nbr_color == -me) & (gd.lib_counts[nbr_root] == 1)

    capture_size = (cap_k * gd.sizes[nbr_root]).sum(axis=1)
    own_size_after = 1 + (own_k * gd.sizes[nbr_root]).sum(axis=1)

    # packed member rows per group (row N = the empty sentinel = 0)
    member_p = jnp.zeros((n + 1, w), jnp.uint32).at[gd.labels, word].add(
        jnp.where(~empty, bitval, jnp.uint32(0)))
    member_p = member_p.at[n].set(jnp.uint32(0))
    nbr_member_p = member_p[nbr_root]                    # [N, 4, W]
    own_sel = jnp.where(own_k[:, :, None], nbr_member_p, jnp.uint32(0))
    cap_sel = jnp.where(cap_k[:, :, None], nbr_member_p, jnp.uint32(0))
    merged = (eye_p | own_sel[:, 0] | own_sel[:, 1]
              | own_sel[:, 2] | own_sel[:, 3])           # [N, W]
    cap_pts = cap_sel[:, 0] | cap_sel[:, 1] | cap_sel[:, 2] | cap_sel[:, 3]

    empty_p = jnp.zeros((w,), jnp.uint32).at[word].add(
        jnp.where(empty, bitval, jnp.uint32(0)))
    new_empty = (empty_p[None, :] & ~eye_p) | cap_pts
    dilated = _packed_dilate(cfg.size, merged)
    libs_after = jax.lax.population_count(
        dilated & new_empty).sum(axis=1).astype(jnp.int32)

    legal = legal_mask(cfg, state, gd)[:n]
    return CandidateInfo(capture_size.astype(jnp.int32),
                         own_size_after.astype(jnp.int32),
                         libs_after, legal)


def true_eyes(cfg: GoConfig, state: GoState, owner) -> jax.Array:
    """bool [N]: empty points that are true eyes of ``owner`` (same
    diagonal rule as ``pygo.GameState.is_eye``)."""
    n = cfg.num_points
    nbrs = neighbors_for(cfg.size)
    diags = diagonals_for(cfg.size)
    board = state.board
    board_pad = jnp.concatenate([board, jnp.zeros((1,), board.dtype)])
    empty = board == 0

    valid_n = nbrs < n
    eyeish = empty & ((board_pad[nbrs] == owner) | ~valid_n).all(axis=1)
    valid_d = diags < n
    bad = (valid_d & (board_pad[diags] == -owner)).sum(axis=1)
    off_board = 4 - valid_d.sum(axis=1)
    return eyeish & jnp.where(off_board > 0, bad == 0, bad <= 1)


def _one_hot8(value: jax.Array, lo: int, active: jax.Array) -> jax.Array:
    """[N] int → [N, 8] one-hot of ``clip(value - lo, 0, 7)``, zeroed
    where ``active`` is False."""
    idx = jnp.clip(value - lo, 0, 7)
    return (jax.nn.one_hot(idx, 8, dtype=jnp.float32)
            * active[:, None].astype(jnp.float32))


def needs_member(features: tuple) -> bool:
    """Whether these features require ``group_data(with_member=True)``
    — callers precomputing a shared ``gd`` for :func:`encode` must
    match this. Always False since :func:`candidate_info` switched to
    packed bitmaps built straight from ``gd.labels``: no plane reads
    the dense ``gd.member`` rows anymore (superko's zxor is the only
    remaining consumer, and ``group_data`` handles that itself). Kept
    as the single source of truth for the convention."""
    del features
    return False


def needs_candidates(features: tuple) -> bool:
    """Whether these features need :func:`candidate_info` (the
    per-candidate-move capture/merge/liberty analysis)."""
    return any(f in ("capture_size", "self_atari_size",
                     "liberties_after") for f in features)


def encode_analysis(cfg: GoConfig, state: GoState, features: tuple,
                    gd: "GroupData | None" = None):
    """The per-state analysis every encode variant shares:
    ``(gd, ci, legal)`` — group data (built with member rows iff the
    candidate-simulation planes need them), the candidate-move info
    (None when unneeded) and the board-move legality mask. Factored
    out so the incremental encoder (:mod:`features.incremental`) and
    the from-scratch :func:`encode` analyse identically — bit-identity
    between the two paths starts here."""
    n = cfg.num_points
    if gd is None:
        gd = group_data(cfg, state.board,
                        with_member=needs_member(features),
                        with_zxor=cfg.enforce_superko,
                        labels=state.labels)
    ci = None
    if needs_candidates(features):
        ci = candidate_info(cfg, state, gd)
        legal = ci.legal
    else:
        legal = legal_mask(cfg, state, gd)[:n]
    return gd, ci, legal


def assemble_planes(cfg: GoConfig, state: GoState, features: tuple,
                    gd: "GroupData", ci, legal, lad_cap, lad_esc,
                    lad_kw: dict) -> jax.Array:
    """Stack the requested plane groups → ``[size, size, F]``. The
    ladder planes are passed in when both were computed by a shared
    read (``ladder_planes`` / the incremental cached read); a
    single-plane request falls back to the per-plane reader here.
    Shared verbatim by :func:`encode` and ``features/incremental.py``
    so the two paths cannot drift plane-by-plane."""
    from rocalphago_tpu.features import ladders as _ladders

    n = cfg.num_points
    board, me = state.board, state.turn
    empty = board == 0
    has_stone = ~empty

    out = []
    for name in features:
        if name == "board":
            f = jnp.stack([(board == me), (board == -me), empty],
                          axis=-1).astype(jnp.float32)
        elif name == "ones":
            f = jnp.ones((n, 1), jnp.float32)
        elif name == "turns_since":
            age = state.step_count - 1 - state.stone_ages
            f = _one_hot8(age, 0, has_stone & (state.stone_ages >= 0))
        elif name == "liberties":
            libs = gd.lib_counts[gd.labels]
            f = _one_hot8(libs, 1, has_stone)
        elif name == "capture_size":
            f = _one_hot8(ci.capture_size, 0, legal)
        elif name == "self_atari_size":
            f = _one_hot8(ci.own_size_after, 1, legal & (ci.libs_after == 1))
        elif name == "liberties_after":
            f = _one_hot8(ci.libs_after, 1, legal)
        elif name == "ladder_capture":
            cap = (lad_cap if lad_cap is not None
                   else _ladders.ladder_capture_plane(
                       cfg, state, gd, legal, **lad_kw))
            f = cap.astype(jnp.float32)[:, None]
        elif name == "ladder_escape":
            esc = (lad_esc if lad_esc is not None
                   else _ladders.ladder_escape_plane(
                       cfg, state, gd, legal, **lad_kw))
            f = esc.astype(jnp.float32)[:, None]
        elif name == "sensibleness":
            f = (legal & ~true_eyes(cfg, state, me)).astype(
                jnp.float32)[:, None]
        elif name == "zeros":
            f = jnp.zeros((n, 1), jnp.float32)
        elif name == "color":
            # AlphaGo's value-net 49th plane: 1 iff black to move
            # (komi asymmetry; see pyfeatures module docstring)
            f = jnp.broadcast_to((me == 1).astype(jnp.float32), (n, 1))
        else:
            raise KeyError(f"unknown feature {name!r}")
        out.append(f)
    flat = jnp.concatenate(out, axis=-1)
    return flat.reshape(cfg.size, cfg.size, -1)


def encode(cfg: GoConfig, state: GoState,
           features: tuple = None,
           ladder_depth: int = 40,
           ladder_lanes: int = 16,
           ladder_chase_slots: int = 6,
           gd: "GroupData | None" = None) -> jax.Array:
    """Encode one game state → float32 ``[size, size, F]`` (NHWC).

    ``features`` is a tuple of plane-group names (static under jit);
    default is the full 48-plane AlphaGo set. Pass a precomputed ``gd``
    (built with ``with_member`` if the candidate-simulation planes are
    requested) to share one flood fill with the caller's own analysis
    — the self-play ply does this (encode + sensibleness per ply).

    When BOTH ladder planes are requested (the default set), they are
    computed by ONE shared, gated read (:func:`ladders.ladder_planes`:
    one candidate analysis, one pooled chase-slot set, one rung loop)
    — the encode-path overhaul; see docs/PERFORMANCE.md "Encode path".
    Sequential callers (self-play, MCTS root advance) should prefer
    the delta sibling ``features/incremental.py::encode_step``, which
    produces bit-identical planes while reusing prior ladder-chase
    verdicts across successive positions.
    """
    from rocalphago_tpu.features import ladders as _ladders
    from rocalphago_tpu.features.pyfeatures import DEFAULT_FEATURES

    if features is None:
        features = DEFAULT_FEATURES
    gd, ci, legal = encode_analysis(cfg, state, features, gd)

    # both ladder planes ride one shared gated chase; a single-plane
    # request keeps the cheaper per-plane read
    lad_cap = lad_esc = None
    lad_kw = dict(depth=ladder_depth, lanes=ladder_lanes,
                  chase_slots=ladder_chase_slots)
    if "ladder_capture" in features and "ladder_escape" in features:
        lad_cap, lad_esc = _ladders.ladder_planes(
            cfg, state, gd, legal, **lad_kw)
    return assemble_planes(cfg, state, features, gd, ci, legal,
                           lad_cap, lad_esc, lad_kw)


def batched_encoder(cfg: GoConfig, features: tuple, **encode_kwargs):
    """``(states, gd=None) -> planes [B, size, size, F]`` — the ONE
    definition of the vmapped encode every fused hot loop uses (the
    self-play ply, the device-search evaluation, the replay-gradient
    plies, the rollout leg). Callers that already hold a per-ply
    :func:`jaxgo.group_data` pass it to share the analysis (the
    shared-gd convention); ``gd=None`` recomputes inside. Encoder
    knobs (``ladder_depth``/``ladder_lanes``/``ladder_chase_slots``)
    thread through ``encode_kwargs``, so a call-site A/B or a future
    default change lands at every hot loop at once."""
    one = functools.partial(encode, cfg, features=features,
                            **encode_kwargs)
    with_gd = jax.vmap(lambda s, g: one(s, gd=g))
    no_gd = jax.vmap(lambda s: one(s))

    def enc(states: GoState, gd=None) -> jax.Array:
        return no_gd(states) if gd is None else with_gd(states, gd)

    return enc
