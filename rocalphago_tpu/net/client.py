"""The client-side reconnect/backoff loop every wire client shares.

A wire client faces exactly three retriable outcomes: the socket
dropped (kill, drain nudge, network — a ``*Closed`` exception or a
raw ``OSError``), the server shed with a structured refusal carrying
``retry_after_s`` (``overload``/``draining``), or a plain transient.
:func:`call_with_backoff` retries all three with the SAME
deterministic-jitter exponential backoff the trainers use
(:func:`rocalphago_tpu.runtime.retries.backoff_delay` — an
interrupted-and-resumed run replays the identical sleep schedule),
and **honors the server's hint**: when a refusal carries
``retry_after_s``, the sleep is at least that long, so a fleet of
shed clients backs off to the server's own pacing instead of
hammering the accept queue on the jitter floor.

Anything that classifies as a programming error raises immediately
— retrying a typo burns the backoff budget in front of the real
traceback (the same line :mod:`rocalphago_tpu.runtime.retries`
draws).
"""

from __future__ import annotations

import time

from rocalphago_tpu.runtime import retries


def default_transient(exc: BaseException) -> bool:
    """Is this a wire outcome worth a reconnect/retry?

    True for socket-level failures (``OSError`` and friends), for
    any exception carrying a non-None ``retry_after_s`` (a
    structured refusal), and for the wire clients' ``*Closed`` /
    ``*Refused`` taxonomy by name — so the helper needs no import
    of every protocol's exception classes.
    """
    if isinstance(exc, (OSError, TimeoutError, ConnectionError)):
        return True
    if getattr(exc, "retry_after_s", None) is not None:
        return True
    return type(exc).__name__.endswith(("Closed", "Refused"))


def call_with_backoff(fn, *, attempts: int = 6,
                      base_delay: float = 0.25, max_delay: float = 5.0,
                      seed: int = 0, key: str = "net.client",
                      transient=None, sleep=time.sleep):
    """Invoke ``fn()`` until it succeeds or the budget runs out.

    Between attempts sleeps ``max(backoff_delay(attempt, ...),
    retry_after_s)`` — deterministic jitter as the floor, the
    server's refusal hint as the override. ``transient(exc) -> bool``
    replaces :func:`default_transient`; non-transient exceptions and
    the final attempt's exception propagate unchanged. ``sleep`` is
    injectable so tests assert the schedule instead of waiting it.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    classify = default_transient if transient is None else transient
    for attempt in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — classified below
            if attempt + 1 >= attempts or not classify(e):
                raise
            delay = retries.backoff_delay(attempt, base_delay,
                                          max_delay, seed, key)
            hint = getattr(e, "retry_after_s", None)
            if hint is not None:
                delay = max(delay, float(hint))
            sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
