"""The shared line-server core: accept, admit, hand off, drain.

:class:`LineServerCore` is the machinery PR 15's gateway proved
under the soak — the timeout-listener accept loop, the structured
admission refusal (``overload``/``draining`` error frames, never a
hang), the per-connection handler threads and registry, and the
bounded three-step graceful drain — factored out so the gateway and
the replay service run the SAME code. It is **composed, not
inherited**: the owning server passes its conversation handler and
its refusal-frame builder in, keeps its own lock for its own
request counters, and the core keeps its own lock for the
connection registry (the static lock model is per-class, and two
small locks with no nesting beat one shared one).

What the owner supplies:

* ``handler(conn, reader, cid)`` — the whole conversation, run on a
  dedicated thread; the core closes the socket and unregisters the
  connection when it returns (the owner's fault wall lives inside);
* ``refusal(code)`` — builds the typed error frame for an
  at-accept shed (``code`` is ``"overload"`` or ``"draining"``);
  the owner counts the error and attaches its ``retry_after_s``;
* optional live/accepted/shed metrics instruments (the owner names
  them, keeping metric names literal where the inventory lint
  reads them).

Drain (the same three bounded steps docs/GATEWAY.md documents):
stop accepting (close the listener — its 0.2 s timeout is the only
portable way to pop a blocked ``accept()``), nudge idle connections
with a read-side shutdown and join handlers within ``drain_s``,
then cut stragglers with ``SHUT_RDWR`` + close, re-snapshotting the
registry until it empties or the tail expires. Phase events land on
the owner's metrics logger as ``{prefix}_requested`` /
``{prefix}_accept_stopped`` / ``{prefix}_drained``.
"""

from __future__ import annotations

import socket
import threading

from rocalphago_tpu.analysis import lockcheck
from rocalphago_tpu.net import protocol
from rocalphago_tpu.runtime.deadline import Deadline


class LineServerCore:
    """Threaded NDJSON accept/admission/drain core (module
    docstring). ``port=0`` binds an ephemeral port; ``name`` prefixes
    thread names and drain-phase events."""

    def __init__(self, *, host: str = "127.0.0.1", port: int = 0,
                 max_conns: int = 64, drain_s: float = 10.0,
                 handler, refusal, name: str = "net", metrics=None,
                 live_gauge=None, accepted_counter=None,
                 shed_counter=None):
        self.host = host
        self._port_arg = int(port)
        self.max_conns = int(max_conns)
        self.drain_s = float(drain_s)
        self.metrics = metrics
        self.name = name
        self._handler = handler
        self._refusal = refusal
        self._live_g = live_gauge
        self._acc_c = accepted_counter
        self._shed_c = shed_counter
        self._lock = lockcheck.make_lock("LineServerCore._lock")
        self._conns: dict = {}       # guarded-by: self._lock
        self._live = 0               # guarded-by: self._lock
        self._next_cid = 0           # guarded-by: self._lock
        self._accepted = 0           # guarded-by: self._lock
        self._shed = 0               # guarded-by: self._lock
        self._draining = False       # guarded-by: self._lock
        self._sock: socket.socket | None = None
        self._bound_port: int | None = None
        self._accept_thread: threading.Thread | None = None

    # ------------------------------------------------------ lifecycle

    def start(self) -> "LineServerCore":
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self._port_arg))
        s.listen(128)
        # a timeout on the listener is the only portable way to wake
        # the accept loop on drain: closing a socket from another
        # thread does NOT interrupt a blocked accept() on Linux
        s.settimeout(0.2)
        self._sock = s
        self._bound_port = s.getsockname()[1]
        t = threading.Thread(target=self._accept_loop,
                             name=f"{self.name}-accept")
        t.start()
        self._accept_thread = t
        return self

    @property
    def port(self) -> int:
        # cached at bind time so the address survives drain (the
        # listener socket is closed first)
        return self._bound_port

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def counters(self) -> dict:
        """Snapshot for the owner's probe: live/accepted/shed conns
        plus the draining flag."""
        with self._lock:
            return {"live": self._live, "accepted": self._accepted,
                    "shed": self._shed, "draining": self._draining}

    def _emit(self, phase: str, **fields) -> None:
        if self.metrics is not None:
            self.metrics.log("drain", phase=phase, **fields)

    def drain(self, reason: str = "requested",
              timeout: float | None = None) -> None:
        """Graceful stop: refuse new work, finish what is in flight,
        quiesce every handler thread (module docstring). Idempotent;
        bounded by ``timeout`` (default ``drain_s``)."""
        timeout = self.drain_s if timeout is None else timeout
        with self._lock:
            already = self._draining
            self._draining = True
        if already:
            return
        self._emit(f"{self.name}_requested", reason=reason)
        # 1. stop accepting: closing the listener pops the accept loop
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
        self._emit(f"{self.name}_accept_stopped")
        # 2. nudge idle connections: a read-side shutdown EOFs their
        # next readline; handlers finish the request in flight, say
        # goodbye on the still-open write side and return
        with self._lock:
            conns = list(self._conns.values())
        for conn, _t in conns:
            try:
                conn.shutdown(socket.SHUT_RD)
            except OSError:
                pass
        deadline = Deadline.after(timeout)
        for _conn, t in conns:
            t.join(timeout=max(0.05, deadline.remaining() or 0.05))
        # 3. stragglers — including connections admitted just before
        # _draining was set and registered after step 2's snapshot —
        # get the read-side nudge again plus the write side cut;
        # close() alone does not wake a blocked readline on Linux, so
        # loop the SHUT_RD until _conns empties or the tail expires
        tail = Deadline.after(5.0)
        while True:
            with self._lock:
                leftover = list(self._conns.values())
            if not leftover or tail.expired():
                break
            for conn, _t in leftover:
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
            for _conn, t in leftover:
                t.join(timeout=max(0.05, tail.remaining() or 0.05))
        with self._lock:
            live = self._live
        self._emit(f"{self.name}_drained", live_conns=live)

    # -------------------------------------------------------- accept

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                with self._lock:
                    if self._draining:
                        return
                continue
            except OSError:
                return                 # listener closed: drain/close
            with self._lock:
                refuse = None
                if self._draining:
                    refuse = "draining"
                elif self._live >= self.max_conns:
                    refuse = "overload"
                    self._shed += 1
                else:
                    self._live += 1
                    self._accepted += 1
                    cid = self._next_cid
                    self._next_cid += 1
                if self._live_g is not None:
                    self._live_g.set(self._live)
            if refuse is not None:
                if refuse == "overload" and self._shed_c is not None:
                    self._shed_c.inc()
                self.send(conn, self._refusal(refuse))
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            if self._acc_c is not None:
                self._acc_c.inc()
            t = threading.Thread(target=self._run_conn,
                                 args=(conn, cid),
                                 name=f"{self.name}-conn-{cid}")
            with self._lock:
                self._conns[cid] = (conn, t)
            t.start()

    # ------------------------------------------------------- handler

    def send(self, conn, msg: dict) -> bool:
        """One frame onto one socket; False when the peer is gone
        mid-reply (the handler treats that as a disconnect)."""
        try:
            conn.sendall(protocol.encode_frame(msg))
            return True
        except (OSError, ValueError):
            return False

    def _run_conn(self, conn, cid: int) -> None:
        reader = conn.makefile("rb")
        try:
            self._handler(conn, reader, cid)
        finally:
            try:
                reader.close()     # drops the makefile's fd reference
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                self._conns.pop(cid, None)
                self._live = max(0, self._live - 1)
                if self._live_g is not None:
                    self._live_g.set(self._live)
