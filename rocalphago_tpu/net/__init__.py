"""Shared wire-service core: the machinery every NDJSON server reuses.

PR 15 built the play gateway; this package extracts the parts of it
that were never gateway-specific so the replay service (and any
later wire front end) reuses ONE proven implementation instead of a
divergent copy:

* :mod:`~rocalphago_tpu.net.protocol` — NDJSON framing (one JSON
  object per line, sorted keys), the frame-bound / torn-frame /
  blank-line reader rules, and typed error frames;
* :mod:`~rocalphago_tpu.net.server` — :class:`~rocalphago_tpu.net
  .server.LineServerCore`: the threaded accept loop with structured
  admission (``overload``/``draining`` refusals, never hangs), the
  per-connection handler threads and registry, and the bounded
  three-step graceful drain;
* :mod:`~rocalphago_tpu.net.client` — :func:`~rocalphago_tpu.net
  .client.call_with_backoff`: the reconnect/backoff loop every wire
  client shares, honoring a refusal's ``retry_after_s`` hint on top
  of :func:`rocalphago_tpu.runtime.retries.backoff_delay`'s
  deterministic jitter.

Protocol *content* (message types, error-code vocabularies, hello
frames, versioning) stays with each service — ``gateway/`` and
``replaynet/`` each pin their own — so this layer never needs a
cross-service schema bump.
"""
