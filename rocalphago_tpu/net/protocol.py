"""NDJSON framing shared by every wire protocol (gateway, replaynet).

One frame = one JSON object on one line. The rules every reader and
writer here agrees on — identical to the gateway protocol PR 15
proved under chaos, now factored so the replay service speaks them
byte-for-byte:

* frames encode with **sorted keys** (byte-stable frames make
  wire-level tests and captures diffable);
* a line longer than the frame bound (newline included) is refused
  with a FATAL ``frame_too_big`` — the reader cannot resynchronize
  mid-line, so the connection drops;
* a torn frame (EOF before the newline) is a disconnect, not an
  error;
* a blank line is neither — it is skipped, so keepalive-style bare
  newlines do not kill the conversation;
* undecodable JSON on an intact line is a NON-fatal
  ``bad_request`` — the line boundary survived, the connection can
  report and go on.

Error-code vocabularies stay per-protocol: :func:`error_frame`
validates against the ``codes`` tuple its caller pins (the gateway's
``ERROR_CODES``, replaynet's) so a typo'd code fails loudly in tests
rather than shipping an unknown refusal.
"""

from __future__ import annotations

import json


class ProtocolError(Exception):
    """A frame the reader cannot accept; ``code`` names why and
    ``fatal`` says whether the connection can survive it (a torn
    byte stream cannot — the next line boundary is unknowable)."""

    def __init__(self, code: str, msg: str, fatal: bool = False):
        super().__init__(msg)
        self.code = code
        self.fatal = fatal


def encode_frame(msg: dict) -> bytes:
    """One dict → one NDJSON line (sorted keys: byte-stable frames
    make wire-level tests and captures diffable)."""
    return (json.dumps(msg, sort_keys=True) + "\n").encode("utf-8")


def read_frame(reader, limit: int):
    """Next frame off a buffered binary reader.

    Returns the decoded dict, or None on a clean EOF / torn trailing
    line (both are disconnects). Blank lines are not frames and not
    disconnects — a keepalive-style bare newline is skipped and the
    read continues. Raises :class:`ProtocolError` for a line longer
    than ``limit`` bytes, newline included (fatal) or undecodable
    JSON (non-fatal: the line boundary survived, the connection can
    report and go on).
    """
    while True:
        line = reader.readline(limit + 1)
        if not line:
            return None
        if len(line) > limit:
            # longer than the bound whether or not the newline made
            # it into the read: a complete limit+1-byte line and a
            # partial read mid-line are both over
            raise ProtocolError(
                "frame_too_big",
                f"frame exceeds {limit} bytes", fatal=True)
        if not line.endswith(b"\n"):
            return None                   # torn frame at EOF
        line = line.strip()
        if line:
            break                         # blank line: keep reading
    try:
        msg = json.loads(line.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise ProtocolError("bad_request", f"undecodable frame: {e}")
    if not isinstance(msg, dict):
        raise ProtocolError("bad_request",
                            "frame must be a JSON object")
    return msg


def error_frame(code: str, msg: str, id=None,
                retry_after_s: float | None = None,
                codes: tuple | None = None) -> dict:
    """A typed refusal frame. ``codes`` is the calling protocol's
    error vocabulary; passing it turns a typo'd code into an
    AssertionError in tests instead of an unknown refusal on the
    wire."""
    if codes is not None:
        assert code in codes, code
    out = {"type": "error", "code": code, "msg": msg}
    if id is not None:
        out["id"] = id
    if retry_after_s is not None:
        out["retry_after_s"] = round(float(retry_after_s), 3)
    return out
