"""ctypes binding for the native C++ game replayer.

Builds ``native/goreplay.cpp`` with the system ``g++`` on first use
(cached as ``native/libgoreplay.so``) and exposes
:func:`replay_arrays`; every caller must handle :func:`available`
being False (no compiler / unsupported platform) by falling back to
the pure-Python ``pygo`` replay. See ``native/goreplay.cpp`` for
parity notes.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

from rocalphago_tpu.analysis import lockcheck

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "native", "goreplay.cpp")
_LIB = os.path.join(_REPO, "native", "libgoreplay.so")

_lock = lockcheck.make_lock("native._lock")
_lib = None               # guarded-by: _lock
_tried = False            # guarded-by: _lock


def _build() -> bool:
    """Compile to a temp path and atomically rename into place, so a
    concurrent or killed build can never leave a truncated .so that
    the mtime check would then trust forever."""
    tmp = f"{_LIB}.tmp.{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC,
             "-o", tmp],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SRC):
            return None
        if not os.path.exists(_LIB) or (
                os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            # stale/corrupt artifact (e.g. different arch) — rebuild once
            if not _build():
                return None
            try:
                lib = ctypes.CDLL(_LIB)
            except OSError:
                return None
        lib.go_replay.restype = ctypes.c_int
        lib.go_replay.argtypes = [
            ctypes.c_int,
            np.ctypeslib.ndpointer(np.int32), ctypes.c_int,
            np.ctypeslib.ndpointer(np.int32), ctypes.c_int,
            np.ctypeslib.ndpointer(np.int32),
            np.ctypeslib.ndpointer(np.int8), ctypes.c_int,
            np.ctypeslib.ndpointer(np.int8),
            np.ctypeslib.ndpointer(np.int8),
            np.ctypeslib.ndpointer(np.int32),
            np.ctypeslib.ndpointer(np.int32),
            np.ctypeslib.ndpointer(np.int32),
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


class IllegalReplay(ValueError):
    """A recorded move was illegal (ply index in ``.ply``)."""

    def __init__(self, ply: int):
        super().__init__(f"illegal move at ply {ply}")
        self.ply = ply


def replay_arrays(size: int, setup_black, setup_white, moves, colors):
    """Replay a recorded game natively.

    ``moves`` are flat actions (``size*size`` = pass), ``colors``
    ±1 per ply. Returns pre-move snapshots
    ``(boards int8 [T,N], to_move int8 [T], kos int32 [T],
    steps int32 [T], ages int32 [T,N])``.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native replayer unavailable")
    n = size * size
    t = len(moves)
    sb = np.ascontiguousarray(setup_black, np.int32).reshape(-1)
    sw = np.ascontiguousarray(setup_white, np.int32).reshape(-1)
    mv = np.ascontiguousarray(moves, np.int32).reshape(-1)
    cl = np.ascontiguousarray(colors, np.int8).reshape(-1)
    boards = np.empty((t, n), np.int8)
    to_move = np.empty((t,), np.int8)
    kos = np.empty((t,), np.int32)
    steps = np.empty((t,), np.int32)
    ages = np.empty((t, n), np.int32)
    # ndpointer rejects zero-size views; give empties real storage
    if t == 0:
        boards = np.empty((1, n), np.int8)
        to_move = np.empty((1,), np.int8)
        kos = np.empty((1,), np.int32)
        steps = np.empty((1,), np.int32)
        ages = np.empty((1, n), np.int32)
    rc = lib.go_replay(
        size,
        sb if sb.size else np.zeros(1, np.int32), sb.size,
        sw if sw.size else np.zeros(1, np.int32), sw.size,
        mv if mv.size else np.zeros(1, np.int32),
        cl if cl.size else np.zeros(1, np.int8), t,
        boards, to_move, kos, steps, ages)
    if rc < 0:
        raise IllegalReplay(-rc - 1)
    return (boards[:t], to_move[:t], kos[:t], steps[:t], ages[:t])
