"""Minimal SGF (Smart Game Format) reader/writer, host-side.

Replaces the reference's dependency on the ``sgf`` pip package
(``AlphaGo/util.py::sgf_iter_states`` replays records through the
engine; SURVEY.md §2 "SGF↔state utils"). Only the subset of SGF needed
for Go game records is implemented: one gametree, ``SZ/KM/HA/RE``
headers, ``AB/AW`` setup stones, ``B/W`` move nodes, pass as ``[]`` or
``[tt]`` (boards ≤ 19).

Coordinates: SGF ``"ab"`` = column a (y=0), row b (x=1) → our ``(x, y)``
board indices; the writer emits the inverse mapping.
"""

from __future__ import annotations

import re
import string
from dataclasses import dataclass, field

import numpy as np

from rocalphago_tpu.engine import pygo

_LETTERS = string.ascii_lowercase


class SGFError(ValueError):
    pass


@dataclass
class SGFGame:
    size: int = 19
    komi: float = 7.5
    handicap: int = 0
    setup_black: list = field(default_factory=list)  # AB points (x, y)
    setup_white: list = field(default_factory=list)  # AW points
    moves: list = field(default_factory=list)        # (color, (x,y)|None)
    result: str = ""                                 # RE value, e.g. B+3.5
    properties: dict = field(default_factory=dict)   # other root props

    @property
    def winner(self) -> int:
        if self.result.upper().startswith("B"):
            return pygo.BLACK
        if self.result.upper().startswith("W"):
            return pygo.WHITE
        return 0


_TOKEN = re.compile(
    r"\s*(?:;|\(|\)|([A-Za-z]{1,8})((?:\s*\[(?:[^\]\\]|\\.)*\])+))",
    re.DOTALL)
_VALUE = re.compile(r"\[((?:[^\]\\]|\\.)*)\]", re.DOTALL)


def _point(val: str, size: int):
    """SGF coordinate value → (x, y) or None for pass."""
    val = val.strip()
    if val == "" or (val == "tt" and size <= 19):
        return None
    if len(val) != 2 or val[0] not in _LETTERS or val[1] not in _LETTERS:
        raise SGFError(f"bad point {val!r}")
    y, x = _LETTERS.index(val[0]), _LETTERS.index(val[1])
    if not (0 <= x < size and 0 <= y < size):
        raise SGFError(f"point {val!r} off a {size}x{size} board")
    return (x, y)


def parse(text: str) -> SGFGame:
    """Parse the first gametree of an SGF document (variations beyond
    the main line are ignored, as in the reference pipeline)."""
    if "(" not in text or ";" not in text:
        raise SGFError("not an SGF document")
    game = SGFGame()
    # The first child gametree at any branch point is the main-line
    # continuation (SGF spec); later siblings are variations and are
    # skipped. ``children[-1]`` counts subtrees opened at the current
    # level; ``skip_depth`` marks the shallowest variation being skipped.
    depth = 0
    children = [0]
    skip_depth: int | None = None
    seen_props: list[tuple[str, list[str]]] = []
    for m in _TOKEN.finditer(text):
        tok = m.group(0).strip()
        if tok == "(":
            children[-1] += 1
            if skip_depth is None and depth >= 1 and children[-1] > 1:
                skip_depth = depth + 1
            depth += 1
            children.append(0)
            continue
        if tok == ")":
            depth -= 1
            children.pop()
            if skip_depth is not None and depth < skip_depth:
                skip_depth = None
            if depth <= 0:
                break
            continue
        if tok == ";" or skip_depth is not None:
            continue
        ident = m.group(1).upper()
        # SGF escaping: backslash makes the next char literal
        values = [re.sub(r"\\(.)", r"\1", v.group(1), flags=re.DOTALL)
                  for v in _VALUE.finditer(m.group(2))]
        seen_props.append((ident, values))
    if not seen_props:
        raise SGFError("no SGF properties found")

    # first pass: size must be known before points are parsed
    for ident, values in seen_props:
        if ident == "SZ":
            try:
                game.size = int(values[0])
            except ValueError as e:
                raise SGFError(f"bad SZ {values[0]!r}") from e
            if not (2 <= game.size <= 26):
                raise SGFError(f"unsupported board size {game.size}")
    for ident, values in seen_props:
        if ident == "SZ":
            continue
        elif ident == "KM":
            try:
                game.komi = float(values[0])
            except ValueError:
                game.komi = 7.5
        elif ident == "HA":
            game.handicap = int(values[0])
        elif ident == "AB":
            game.setup_black += [_point(v, game.size) for v in values]
        elif ident == "AW":
            game.setup_white += [_point(v, game.size) for v in values]
        elif ident == "RE":
            game.result = values[0]
        elif ident in ("B", "W"):
            color = pygo.BLACK if ident == "B" else pygo.WHITE
            game.moves.append((color, _point(values[0], game.size)))
        else:
            game.properties.setdefault(ident, values[0])
    return game


def replay(game: SGFGame, enforce_superko: bool = False):
    """Build the initial GameState for ``game`` and yield
    ``(state, move, player)`` before each move is applied — the
    reference's ``sgf_iter_states`` contract. The caller may encode
    ``state`` and then the generator plays ``move``."""
    st = pygo.GameState(size=game.size, komi=game.komi,
                        enforce_superko=enforce_superko)
    if game.setup_black and not game.setup_white:
        st.place_handicaps(game.setup_black)
    elif game.setup_black or game.setup_white:
        # free setup (AB+AW): stones get age 0, same as handicaps
        for p in game.setup_black:
            st.board[p] = pygo.BLACK
            st.stone_ages[p] = 0
        for p in game.setup_white:
            st.board[p] = pygo.WHITE
            st.stone_ages[p] = 0
        # re-derive the carried hash from the raw setup edits, then
        # restart the superko history at the setup position
        from rocalphago_tpu.engine.zobrist import position_table
        zob = position_table(st.size)
        h = np.zeros(2, np.uint32)
        for p in game.setup_black:
            h = h ^ zob[p[0] * st.size + p[1], 0]
        for p in game.setup_white:
            h = h ^ zob[p[0] * st.size + p[1], 1]
        st.zobrist_hash = h
        st._hash_history = dict.fromkeys([h.tobytes()])
    if game.moves:
        # the record's first move decides whose turn it is after setup
        st.current_player = game.moves[0][0]
    for color, move in game.moves:
        yield st, move, color
        st.do_move(move, color)
    return


def render(game: SGFGame, app: str = "rocalphago_tpu") -> str:
    """Serialize a game back to SGF text."""
    def pt(p):
        if p is None:
            return ""
        x, y = p
        return f"{_LETTERS[y]}{_LETTERS[x]}"

    def esc(val) -> str:
        return str(val).replace("\\", "\\\\").replace("]", "\\]")

    # only game-info properties belong in the root node; parse()
    # collects unhandled props from every node, so unknown keys (e.g.
    # per-move C comments) must not be relocated here
    root_props = ("PB", "PW", "PL", "GN", "DT", "EV", "RO", "SO", "US",
                  "AN", "CP", "GC", "RU", "TM", "OT", "CA", "ST", "HA")
    parts = [f"(;GM[1]FF[4]AP[{app}]SZ[{game.size}]KM[{game.komi}]"]
    if game.result:
        parts.append(f"RE[{esc(game.result)}]")
    for key in root_props:
        if key in game.properties:
            parts.append(f"{key}[{esc(game.properties[key])}]")
    if game.setup_black:
        parts.append("AB" + "".join(f"[{pt(p)}]" for p in game.setup_black))
    if game.setup_white:
        parts.append("AW" + "".join(f"[{pt(p)}]" for p in game.setup_white))
    for color, move in game.moves:
        tag = "B" if color == pygo.BLACK else "W"
        parts.append(f";{tag}[{pt(move)}]")
    parts.append(")")
    return "".join(parts)


def from_moves(size: int, komi: float, moves, result: str = "") -> SGFGame:
    """Build an SGFGame from engine-style (color, (x,y)|None) moves —
    used by self-play to persist games."""
    return SGFGame(size=size, komi=komi, moves=list(moves), result=result)


def from_gamestate(state) -> SGFGame:
    """Snapshot a host ``pygo.GameState`` (history + handicaps + score)
    into an SGFGame — the reference's ``save_gamestate_to_sgf``
    utility (SURVEY.md §2 "SGF↔state utils")."""
    moves = []
    color = pygo.BLACK if not state.handicaps else pygo.WHITE
    for mv in state.history:
        moves.append((color, mv))
        color = -color
    result = ""
    if state.is_end_of_game:
        black, white = state.get_scores()
        if black > white:
            result = f"B+{black - white:g}"
        elif white > black:
            result = f"W+{white - black:g}"
        else:
            result = "0"
    game = SGFGame(size=state.size, komi=state.komi,
                   setup_black=list(state.handicaps), moves=moves,
                   result=result)
    if state.handicaps:
        game.handicap = len(state.handicaps)
        game.properties["HA"] = str(len(state.handicaps))
    return game


def save_gamestate(state, path: str) -> None:
    """Write a game in progress (or finished) to an SGF file."""
    with open(path, "w") as f:
        f.write(render(from_gamestate(state)))
