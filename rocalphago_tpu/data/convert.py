"""SGF corpus → training-data converter (device-batched encoding).

Parity: ``AlphaGo/preprocessing/game_converter.py::GameConverter``
(``convert_game``, ``sgfs_to_hdf5``, the ``run_game_converter`` CLI with
``--features/--directory/--recurse/--outfile``; SURVEY.md §3.4). The
reference encodes positions one at a time in host Python; here games are
replayed on host (rules bookkeeping) but positions are *encoded on
device in fixed-size batches* through the jitted 48-plane encoder — the
expensive planes (candidate analysis, ladders) run vectorized.

Native output is sharded ``.npz`` (uint8 NHWC states + int32 flat
actions + JSON manifest) for the prefetching input pipeline; an HDF5
writer in the reference's layout (uint8 NCHW ``states``/``actions``
datasets) is kept for interchange.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import warnings

import numpy as np

from rocalphago_tpu.data import native, sgf as sgflib
from rocalphago_tpu.engine import pygo
from rocalphago_tpu.engine.jaxgo import GoConfig, GoState, seed_labels
from rocalphago_tpu.features import DEFAULT_FEATURES, Preprocess

_ENCODE_BATCH = 128  # static batch for the jitted encoder (padded)


def pack_states(cfg: GoConfig, boards, turns, kos, steps, ages) -> GoState:
    """Assemble a batched GoState from raw numpy fields (hash/history
    zeroed — converters run with superko off, so legality inside the
    encoder never consults them). The carried labels are seeded with
    one compiled batched fill (:func:`jaxgo.seed_labels`)."""
    import jax.numpy as jnp
    b = len(boards)
    n = cfg.num_points
    state = GoState(
        board=jnp.asarray(np.asarray(boards, np.int8)),
        turn=jnp.asarray(np.asarray(turns, np.int8)),
        ko=jnp.asarray(np.asarray(kos, np.int32)),
        pass_count=jnp.zeros((b,), jnp.int8),
        done=jnp.zeros((b,), jnp.bool_),
        step_count=jnp.asarray(np.asarray(steps, np.int32)),
        hash=jnp.zeros((b, 2), jnp.uint32),
        hash_history=jnp.zeros((b, cfg.max_history, 2), jnp.uint32),
        stone_ages=jnp.asarray(np.asarray(ages, np.int32)),
        prisoners=jnp.zeros((b, 2), jnp.int32),
        labels=jnp.full((b, n), n, jnp.int32),
    )
    return seed_labels(cfg, state)


class GameConverter:
    """Replay SGF games and emit (encoded state, expert action) pairs."""

    def __init__(self, feature_list=DEFAULT_FEATURES, board_size: int = 19,
                 ladder_depth: int = 40, ladder_lanes: int = 16,
                 ladder_chase_slots: int = 4):
        self.board_size = board_size
        self.cfg = GoConfig(size=board_size, enforce_superko=False,
                            max_history=8)
        self.pre = Preprocess(feature_list, cfg=self.cfg,
                              ladder_depth=ladder_depth,
                              ladder_lanes=ladder_lanes,
                              ladder_chase_slots=ladder_chase_slots)
        self.feature_list = tuple(feature_list)

    # ------------------------------------------------------------ encoding

    def _encode_fields(self, fields):
        """fields: list of (board, turn, ko, step, ages) → [n,s,s,F]
        uint8, padding the jit batch to a static size."""
        out = []
        for i in range(0, len(fields), _ENCODE_BATCH):
            chunk = fields[i:i + _ENCODE_BATCH]
            pad = _ENCODE_BATCH - len(chunk)
            rows = chunk + [chunk[-1]] * pad
            st = pack_states(self.cfg, *map(list, zip(*rows)))
            t = np.asarray(self.pre.states_to_tensor(st))
            out.append(t[:len(chunk)])
        planes = np.concatenate(out, axis=0)
        return (planes > 0.5).astype(np.uint8)

    def convert_game(self, sgf_text: str, include_passes: bool = False):
        """One game → (states uint8 [n,s,s,F] NHWC, actions int32 [n]).

        Positions whose move is a pass are dropped unless
        ``include_passes`` (the policy output space is board points, as
        in the reference; pass handling lives at the agent layer).
        Rules replay runs through the native C++ replayer when built
        (exact pygo parity; see ``native/goreplay.cpp``), else pygo.
        """
        game = sgflib.parse(sgf_text)
        if game.size != self.board_size:
            raise sgflib.SGFError(
                f"board size {game.size} != converter size "
                f"{self.board_size}")
        if native.available():
            return self._convert_game_native(game, include_passes)
        n = self.cfg.num_points
        fields, actions = [], []
        for st, move, player in sgflib.replay(game):
            if move is None and not include_passes:
                continue
            if player != st.current_player:
                # out-of-turn move (free placement SGF) — skip position
                continue
            # snapshot with copies: pygo mutates stone_ages in place as
            # the generator advances, so a view here would silently
            # give every position the END-of-game ages (caught by the
            # native-replayer differential test)
            fields.append((
                np.array(st.board, np.int8).reshape(-1),
                np.int8(st.current_player),
                np.int32(-1 if st.ko is None
                         else st.ko[0] * game.size + st.ko[1]),
                np.int32(st.turns_played),
                np.array(st.stone_ages, np.int32).reshape(-1),
            ))
            actions.append(n if move is None
                           else move[0] * game.size + move[1])
        if not fields:
            return (np.zeros((0, game.size, game.size,
                              self.pre.output_dim), np.uint8),
                    np.zeros((0,), np.int32))
        return (self._encode_fields(fields),
                np.asarray(actions, np.int32))

    def _convert_game_native(self, game, include_passes: bool):
        size = game.size
        n = self.cfg.num_points
        flat = lambda p: p[0] * size + p[1]  # noqa: E731
        moves = np.asarray([n if mv is None else flat(mv)
                            for _, mv in game.moves], np.int32)
        colors = np.asarray([c for c, _ in game.moves], np.int8)
        boards, to_move, kos, steps, ages = native.replay_arrays(
            size, [flat(p) for p in game.setup_black],
            [flat(p) for p in game.setup_white], moves, colors)
        keep = [t for t in range(len(moves))
                if (include_passes or moves[t] != n)
                and colors[t] == to_move[t]]
        if not keep:
            return (np.zeros((0, size, size, self.pre.output_dim),
                             np.uint8), np.zeros((0,), np.int32))
        fields = [(boards[t], np.int8(to_move[t]), np.int32(kos[t]),
                   np.int32(steps[t]), ages[t]) for t in keep]
        return (self._encode_fields(fields),
                np.asarray([moves[t] for t in keep], np.int32))

    # ------------------------------------------------------------- corpora

    def _iter_sgf_files(self, directory: str, recurse: bool):
        if recurse:
            for root, _, names in sorted(os.walk(directory)):
                for name in sorted(names):
                    if name.lower().endswith(".sgf"):
                        yield os.path.join(root, name)
        else:
            for name in sorted(os.listdir(directory)):
                if name.lower().endswith(".sgf"):
                    yield os.path.join(directory, name)

    def sgfs_to_shards(self, files, out_prefix: str,
                       shard_size: int = 8192,
                       ignore_errors: bool = True) -> dict:
        """Convert SGF files to ``{out_prefix}-NNNNN.npz`` shards plus a
        ``{out_prefix}-manifest.json``. Corrupt or illegal games are
        skipped with a warning (reference ``ignore_errors`` behavior).
        """
        parent = os.path.dirname(out_prefix)
        if parent:
            os.makedirs(parent, exist_ok=True)
        buf_s, buf_a = [], []
        counts, errors = [], []
        n_shards = n_positions = n_games = 0

        def flush():
            nonlocal n_shards, n_positions
            if not buf_s:
                return
            states = np.concatenate(buf_s, axis=0)
            actions = np.concatenate(buf_a, axis=0)
            path = f"{out_prefix}-{n_shards:05d}.npz"
            np.savez_compressed(path, states=states, actions=actions)
            counts.append(len(actions))
            n_shards += 1
            n_positions += len(actions)
            buf_s.clear()
            buf_a.clear()

        for path in files:
            try:
                with open(path, "r", errors="replace") as f:
                    states, actions = self.convert_game(f.read())
            except (sgflib.SGFError, pygo.IllegalMove, OSError,
                    ValueError) as e:
                if not ignore_errors:
                    raise
                errors.append({"file": path, "error": str(e)})
                warnings.warn(f"skipping {path}: {e}")
                continue
            if len(actions) == 0:
                continue
            n_games += 1
            buf_s.append(states)
            buf_a.append(actions)
            if sum(len(a) for a in buf_a) >= shard_size:
                flush()
        flush()

        manifest = {
            "format": "rocalphago_tpu/npz-shards/v1",
            "board_size": self.board_size,
            "features": list(self.feature_list),
            "planes": self.pre.output_dim,
            "layout": "NHWC",
            "num_shards": n_shards,
            "num_positions": n_positions,
            "num_games": n_games,
            "shard_counts": counts,
            "errors": errors,
        }
        with open(f"{out_prefix}-manifest.json", "w") as f:
            json.dump(manifest, f, indent=2)
        return manifest

    def sgfs_to_hdf5(self, files, outfile: str,
                     ignore_errors: bool = True) -> int:
        """Reference-layout HDF5: growable uint8 ``states`` (n, F, s, s)
        NCHW + int32 ``actions`` (n,), feature list as a file attr."""
        import h5py
        parent = os.path.dirname(outfile)
        if parent:
            os.makedirs(parent, exist_ok=True)
        n_positions = 0
        with h5py.File(outfile, "w") as h5:
            s = self.board_size
            states = h5.create_dataset(
                "states", shape=(0, self.pre.output_dim, s, s),
                maxshape=(None, self.pre.output_dim, s, s),
                dtype=np.uint8, chunks=(64, self.pre.output_dim, s, s),
                compression="lzf")
            acts = h5.create_dataset(
                "actions", shape=(0,), maxshape=(None,), dtype=np.int32,
                chunks=(1024,))
            h5.attrs["features"] = ",".join(self.feature_list)
            h5.attrs["board_size"] = s
            for path in files:
                try:
                    with open(path, "r", errors="replace") as f:
                        st, ac = self.convert_game(f.read())
                except (sgflib.SGFError, pygo.IllegalMove, OSError,
                        ValueError) as e:
                    if not ignore_errors:
                        raise
                    warnings.warn(f"skipping {path}: {e}")
                    continue
                if len(ac) == 0:
                    continue
                k = len(ac)
                states.resize(n_positions + k, axis=0)
                acts.resize(n_positions + k, axis=0)
                states[n_positions:] = st.transpose(0, 3, 1, 2)  # → NCHW
                acts[n_positions:] = ac
                n_positions += k
        return n_positions


def run_game_converter(argv=None):
    """CLI mirroring the reference's ``run_game_converter``."""
    ap = argparse.ArgumentParser(
        description="Convert SGF games to training data")
    ap.add_argument("--directory", "-d", required=True)
    ap.add_argument("--outfile", "-o", required=True,
                    help="shard prefix (npz) or .h5 path (hdf5)")
    ap.add_argument("--recurse", "-R", action="store_true")
    ap.add_argument("--features", default=",".join(DEFAULT_FEATURES))
    ap.add_argument("--size", type=int, default=19)
    ap.add_argument("--format", choices=("npz", "hdf5"), default="npz")
    ap.add_argument("--shard-size", type=int, default=8192)
    args = ap.parse_args(argv)

    conv = GameConverter(tuple(args.features.split(",")),
                         board_size=args.size)
    files = conv._iter_sgf_files(args.directory, args.recurse)
    if args.format == "npz":
        manifest = conv.sgfs_to_shards(files, args.outfile,
                                       shard_size=args.shard_size)
        print(json.dumps({k: manifest[k] for k in
                          ("num_shards", "num_positions", "num_games")}))
    else:
        n = conv.sgfs_to_hdf5(files, args.outfile)
        print(json.dumps({"num_positions": n}))


if __name__ == "__main__":
    run_game_converter(sys.argv[1:])
