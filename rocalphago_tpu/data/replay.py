"""Replay-buffer service: the hand-off point between self-play actors
and the sharded learner (docs/SCALE.md).

A bounded, thread-safe ring of finished self-play batches
(:class:`ZeroGames`). Producers (``training/actor.py``) ``put``
batches — blocking when full (pacing) or evicting the oldest
(free-run) — and consumers take them out either FIFO
(:meth:`ReplayBuffer.next_batch`, the bit-exact lockstep path) or by
prioritized-recency draw (:meth:`ReplayBuffer.sample`, geometric from
the newest entry, which approximates the KataGo-style sliding window
without ever blocking the learner on a specific game).

Durability and transport:

- crash-safe spill: with ``spill_dir`` set, every accepted entry is
  persisted via :func:`rocalphago_tpu.runtime.atomic.atomic_write_json`
  (tmp + fsync + rename — a crash never leaves a torn file) and
  removed again when consumed or evicted; :meth:`ReplayBuffer.restore`
  reloads whatever survived, skipping anything unreadable.
- tolerant-JSONL ingest: :class:`JsonlIngester` tails ``*.jsonl``
  shards written by out-of-process actors (one game record per line),
  consuming only newline-terminated lines so a writer crashed
  mid-line never poisons the stream — the torn tail is simply re-read
  on the next poll once completed.

Observability (all emitted OUTSIDE the buffer lock):
``replay_fill_games`` gauge, ``replay_ingest_games_total`` counter,
``replay_ingest_per_min`` gauge, ``replay_sample_staleness_seconds``
histogram (age of each consumed/sampled entry),
``replay_evicted_games_total`` + ``replay_spilled_total`` counters.
Blocking waits are tagged :func:`rocalphago_tpu.runtime.watchdog
.waiting_on` ``("replay_fill")`` so a starving learner's stall events
are distinguishable from a hang.

This module is deliberately jax-free (numpy only): report scripts and
out-of-process ingest helpers can import it without touching a
backend.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import time
from typing import NamedTuple

import numpy as np

from rocalphago_tpu.analysis import lockcheck
from rocalphago_tpu.obs import registry
from rocalphago_tpu.runtime import atomic, watchdog

CAPACITY_ENV = "ROCALPHAGO_REPLAY_CAPACITY"
SAMPLE_P_ENV = "ROCALPHAGO_REPLAY_SAMPLE_P"


def default_capacity() -> int:
    """Buffer capacity in entries (one entry = one self-play batch)."""
    return int(os.environ.get(CAPACITY_ENV, "8"))


def default_sample_p() -> float:
    """Geometric recency parameter for :meth:`ReplayBuffer.sample`."""
    return float(os.environ.get(SAMPLE_P_ENV, "0.5"))


#: Record-schema version written by :func:`games_to_record`. v1:
#: the five core fields, no ``schema`` key. v2: adds the OPTIONAL
#: self-play-economics fields (``full``/``ownership``/``score``,
#: present only when recorded). Readers accept any version ≤ current
#: (absent optionals synthesize as None); records from a FUTURE
#: schema raise :class:`UnknownSchemaError` so ingest can count and
#: skip them instead of mis-reading half-understood data.
RECORD_SCHEMA = 2


class UnknownSchemaError(ValueError):
    """Record written by a newer schema than this reader knows."""


class ZeroGames(NamedTuple):
    """One finished self-play batch — the unit the buffer stores.

    Raw recorder dtypes, exactly as ``training.zero``'s self-play
    returns them (the learner does its own float casts, so a
    host round-trip through the buffer stays bit-exact):

    - ``actions``: ``[T, B]`` int32 move indices per ply
    - ``live``: ``[T, B]`` bool — ply happened before the game ended
    - ``visits``: ``[T, B, A]`` visit counts (int32) or improved-
      policy targets (float32, gumbel mode; normalized pruned
      targets with forced-playout pruning)
    - ``winners``: ``[B]`` int32 (+1 black / -1 white / 0 draw)
    - ``finished``: ``[B]`` bool — game ended by two passes

    Self-play-economics fields (schema v2; ``None`` when the game was
    generated with the flags off — v1 records load with all three
    None):

    - ``full``: ``[T, B]`` bool — ply ran a FULL search (playout-cap
      randomization; only these plies carry policy targets)
    - ``ownership``: ``[B, N]`` int8 terminal ownership labels
      (black-positive; :func:`rocalphago_tpu.ops.labels
      .terminal_labels`)
    - ``score``: ``[B]`` float32 terminal score margins (black −
      white, komi included)
    """

    actions: np.ndarray
    live: np.ndarray
    visits: np.ndarray
    winners: np.ndarray
    finished: np.ndarray
    full: np.ndarray | None = None
    ownership: np.ndarray | None = None
    score: np.ndarray | None = None


class ReplayEntry(NamedTuple):
    """A buffered batch plus its provenance: ``seq`` (ingest order),
    ``version`` (params snapshot that played it — staleness = learner
    version minus this) and ``t_ingest`` (monotonic, for age)."""

    seq: int
    version: int
    games: ZeroGames
    t_ingest: float


def compute_game_id(games: ZeroGames) -> str:
    """Content-hash identity of one batch: sha256 over every
    present field's name, dtype, shape and raw bytes (16 hex chars).

    The id is a pure function of the game CONTENT — transport
    metadata (``version``/``seq``) is excluded — so the same batch
    re-encoded, re-shipped after an ambiguous ack, re-read after a
    shard rotation or re-spilled under a fresh sequence number hashes
    to the same id. That property is what lets every dedup window
    (replaynet's server, :class:`JsonlIngester`) collapse
    at-least-once delivery into effectively exactly-once."""
    h = hashlib.sha256()
    for name, arr in zip(ZeroGames._fields, games):
        if arr is None:
            continue
        a = np.asarray(arr)
        h.update(name.encode("utf-8"))
        h.update(str(a.dtype).encode("utf-8"))
        h.update(str(a.shape).encode("utf-8"))
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()[:16]


def games_to_record(games: ZeroGames, version: int = 0,
                    seq: int = 0, game_id: str | None = None) -> dict:
    """JSON-serializable record preserving shapes and dtypes.
    Optional (None) fields are simply absent from the record — a
    flags-off game writes exactly the v1 field set plus the
    ``schema`` tag. Every record carries its content-hash
    ``game_id`` (:func:`compute_game_id`; pass it in when already
    known to skip the rehash)."""
    rec = {"version": int(version), "seq": int(seq),
           "schema": RECORD_SCHEMA,
           "game_id": game_id or compute_game_id(games)}
    for name, arr in zip(ZeroGames._fields, games):
        if arr is None:
            continue
        a = np.asarray(arr)
        rec[name] = a.tolist()
        rec[name + "_dtype"] = str(a.dtype)
    return rec


def record_game_id(rec: dict, games: ZeroGames | None = None) -> str:
    """A record's ``game_id`` — the embedded one when present, else
    recomputed from ``games`` (the parsed batch; older records wrote
    no id, and the content hash is recomputable by design)."""
    gid = rec.get("game_id")
    if gid:
        return str(gid)
    if games is None:
        games, _ = record_to_games(rec)
    return compute_game_id(games)


def record_to_games(rec: dict) -> tuple[ZeroGames, int]:
    """Inverse of :func:`games_to_record`; raises ``KeyError`` /
    ``TypeError`` / ``ValueError`` on malformed records (callers
    treat those as torn input and skip). v1 records (no ``schema``
    key) and v2 records missing optional fields synthesize those
    fields as None; a FUTURE schema raises
    :class:`UnknownSchemaError` (counted separately by
    :class:`JsonlIngester` — unknown ≠ torn)."""
    schema = int(rec.get("schema", 1))
    if schema > RECORD_SCHEMA:
        raise UnknownSchemaError(
            f"record schema {schema} is newer than this reader's "
            f"{RECORD_SCHEMA}")
    arrs = []
    for name in ZeroGames._fields:
        if name in ZeroGames._field_defaults and name not in rec:
            arrs.append(None)
            continue
        arrs.append(np.asarray(rec[name],
                               dtype=np.dtype(rec[name + "_dtype"])))
    return ZeroGames(*arrs), int(rec.get("version", 0))


class ReplayBuffer:
    """Bounded thread-safe ring of :class:`ReplayEntry`.

    ``capacity`` is in entries; ``put(block=True)`` paces producers
    (waits for a FIFO consumer to make room), ``put(block=False)``
    evicts the oldest entry instead — the right mode when the
    consumer is :meth:`sample`, which never removes entries.
    """

    def __init__(self, capacity: int | None = None, *,
                 sample_p: float | None = None,
                 spill_dir: str | None = None, seed: int = 0):
        self.capacity = (default_capacity() if capacity is None
                         else int(capacity))
        if self.capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.sample_p = (default_sample_p() if sample_p is None
                         else float(sample_p))
        if not 0.0 < self.sample_p <= 1.0:
            raise ValueError(f"sample_p must be in (0, 1], "
                             f"got {self.sample_p}")
        self.spill_dir = spill_dir
        self._cond = lockcheck.make_condition("ReplayBuffer._cond")
        self._entries: list[ReplayEntry] = []  # guarded-by: self._cond
        self._seq = 0                          # guarded-by: self._cond
        self._closed = False                   # guarded-by: self._cond
        self._ingested = 0                     # guarded-by: self._cond
        self._t_first: float | None = None     # guarded-by: self._cond
        self._rng = np.random.default_rng(seed)  # guarded-by: self._cond
        # spill filenames carry an incarnation tag so THIS buffer's
        # files can never collide with (or be mistaken for) a dead
        # incarnation's leftovers: restore() ingests only foreign
        # tags, and a live put during restore can't overwrite the
        # old file restore is about to read
        self._spill_tag = (f"{os.getpid():x}."
                           f"{int(time.time() * 1e3) & 0xffffffff:08x}")
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)

    # ------------------------------------------------------- producers

    def put(self, games: ZeroGames, version: int = 0,
            block: bool = False, timeout: float | None = None,
            evict: bool = True) -> bool:
        """Append a batch; True if accepted, False on timeout/closed.

        ``block=True`` waits for room (producer pacing — bounds
        sample staleness by construction); ``block=False`` evicts the
        oldest entry when full. ``evict=False`` turns a full
        non-blocking put into a plain refusal (return False, buffer
        untouched) — the mode a LOSSLESS ingest path needs: the
        replay service answers ``overload`` with ``retry_after_s``
        instead of silently dropping the oldest game.
        """
        games = ZeroGames(*(None if x is None else np.asarray(x)
                            for x in games))
        n_games = int(games.winners.shape[0])
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        evict_seqs: list[int] = []
        evicted_games = 0
        with self._cond:
            while (block and not self._closed
                   and len(self._entries) >= self.capacity):
                rem = (None if deadline is None
                       else deadline - time.monotonic())
                if rem is not None and rem <= 0:
                    return False
                self._cond.wait(rem)
            if self._closed:
                return False
            if not evict and len(self._entries) >= self.capacity:
                return False
            while len(self._entries) >= self.capacity:
                old = self._entries.pop(0)
                evict_seqs.append(old.seq)
                evicted_games += int(old.games.winners.shape[0])
            entry = ReplayEntry(self._seq, int(version), games,
                                time.monotonic())
            self._seq += 1
            self._entries.append(entry)
            self._ingested += n_games
            if self._t_first is None:
                self._t_first = time.monotonic()
            fill = sum(int(e.games.winners.shape[0])
                       for e in self._entries)
            total, t_first = self._ingested, self._t_first
            self._cond.notify_all()
        if self.spill_dir:
            atomic.atomic_write_json(
                self._spill_path(entry.seq),
                games_to_record(games, entry.version, entry.seq),
                indent=None)
            registry.counter("replay_spilled_total").inc()
            for seq in evict_seqs:
                self._unspill(seq)
        registry.gauge("replay_fill_games").set(fill)
        registry.counter("replay_ingest_games_total").inc(n_games)
        minutes = max(time.monotonic() - t_first, 1e-9) / 60.0
        registry.gauge("replay_ingest_per_min").set(total / minutes)
        if evicted_games:
            registry.counter("replay_evicted_games_total").inc(
                evicted_games)
        return True

    def requeue(self, entry: ReplayEntry) -> bool:
        """Put a consumed entry BACK at the head of the FIFO.

        The take-side loss guard: when the replay service pops an
        entry for ``next_batch`` and then fails to send the reply
        (peer died mid-response), the entry is requeued — same seq,
        same position — and re-spilled, so the failed delivery costs
        nothing. Capacity is deliberately allowed to overshoot by
        the requeued entry (dropping here would be the exact loss
        the guard exists to prevent). False only when closed.
        """
        with self._cond:
            if self._closed:
                return False
            self._entries.insert(0, entry)
            fill = sum(int(e.games.winners.shape[0])
                       for e in self._entries)
            self._cond.notify_all()
        if self.spill_dir:
            atomic.atomic_write_json(
                self._spill_path(entry.seq),
                games_to_record(entry.games, entry.version, entry.seq),
                indent=None)
        registry.gauge("replay_fill_games").set(fill)
        return True

    # ------------------------------------------------------- consumers

    def next_batch(self, timeout: float | None = None) \
            -> ReplayEntry | None:
        """FIFO-pop the oldest entry (the lockstep/bit-exact path).

        Blocks until an entry arrives; None on timeout or when the
        buffer is closed and drained.
        """
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with watchdog.waiting_on("replay_fill"):
            with self._cond:
                while not self._entries and not self._closed:
                    rem = (None if deadline is None
                           else deadline - time.monotonic())
                    if rem is not None and rem <= 0:
                        return None
                    self._cond.wait(rem)
                if not self._entries:
                    return None
                entry = self._entries.pop(0)
                fill = sum(int(e.games.winners.shape[0])
                           for e in self._entries)
                self._cond.notify_all()   # room for paced producers
        if self.spill_dir:
            self._unspill(entry.seq)      # consumed — don't restore it
        self._observe_out(entry, fill)
        return entry

    def sample(self, timeout: float | None = None) \
            -> ReplayEntry | None:
        """Prioritized-recency draw (geometric from the newest entry,
        parameter ``sample_p``); the entry stays in the ring. Blocks
        until non-empty; None on timeout/closed-and-empty."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with watchdog.waiting_on("replay_fill"):
            with self._cond:
                while not self._entries and not self._closed:
                    rem = (None if deadline is None
                           else deadline - time.monotonic())
                    if rem is not None and rem <= 0:
                        return None
                    self._cond.wait(rem)
                if not self._entries:
                    return None
                n = len(self._entries)
                back = min(int(self._rng.geometric(self.sample_p)) - 1,
                           n - 1)
                entry = self._entries[n - 1 - back]
                fill = sum(int(e.games.winners.shape[0])
                           for e in self._entries)
        self._observe_out(entry, fill)
        return entry

    # ------------------------------------------------------- lifecycle

    def close(self) -> None:
        """Reject further puts and unblock every waiter (consumers
        drain what's left, then get None)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    @property
    def fill(self) -> int:
        with self._cond:
            return len(self._entries)

    @property
    def ingested_games(self) -> int:
        with self._cond:
            return self._ingested

    # ----------------------------------------------------- persistence

    def restore(self) -> int:
        """Reload spilled entries after a crash; returns the count.

        Tolerant: unreadable/torn files are skipped. All on-disk
        files are consumed (removed) and the survivors re-spilled
        under fresh sequence numbers, so a second crash can't
        double-restore.

        The insert is ONE critical section: restore-while-producers-
        publish is a real path (a replay service restores its spill
        while reconnecting actors are already shipping), and
        inserting the recovered entries one ``put`` at a time would
        let live puts interleave into the middle of the restored
        stream — reordering the FIFO. Under the single section the
        restored entries land contiguously, before or after any live
        put, and both streams keep their own order."""
        if not self.spill_dir:
            return 0
        paths = sorted(
            p for p in glob.glob(
                os.path.join(self.spill_dir, "entry.*.json"))
            if f".{self._spill_tag}." not in os.path.basename(p))
        recovered = []
        for path in paths:
            try:
                with open(path, encoding="utf-8") as f:
                    rec = json.load(f)
                games, version = record_to_games(rec)
            except (OSError, ValueError, KeyError, TypeError):
                continue
            recovered.append((ZeroGames(
                *(None if x is None else np.asarray(x)
                  for x in games)), version))
        evict_seqs: list[int] = []
        evicted_games = 0
        new_entries: list[ReplayEntry] = []
        with self._cond:
            if self._closed:
                return 0
            for games, version in recovered:
                while len(self._entries) >= self.capacity:
                    old = self._entries.pop(0)
                    evict_seqs.append(old.seq)
                    evicted_games += int(old.games.winners.shape[0])
                entry = ReplayEntry(self._seq, int(version), games,
                                    time.monotonic())
                self._seq += 1
                self._entries.append(entry)
                self._ingested += int(games.winners.shape[0])
                new_entries.append(entry)
            if new_entries and self._t_first is None:
                self._t_first = time.monotonic()
            fill = sum(int(e.games.winners.shape[0])
                       for e in self._entries)
            self._cond.notify_all()
        # file I/O stays outside the lock: consume the old files
        # first, then re-spill only the entries still IN the buffer
        # (a restored entry evicted by a later restored one, or a
        # live entry evicted mid-restore, must not leave a spill
        # file behind to double-restore next time)
        for path in paths:
            try:
                os.unlink(path)
            except OSError:
                pass
        evicted = set(evict_seqs)
        for entry in new_entries:
            if entry.seq in evicted:
                continue
            atomic.atomic_write_json(
                self._spill_path(entry.seq),
                games_to_record(entry.games, entry.version,
                                entry.seq),
                indent=None)
        restored_seqs = {e.seq for e in new_entries}
        for seq in evict_seqs:
            if seq not in restored_seqs:
                self._unspill(seq)
        if new_entries:
            registry.counter("replay_spilled_total").inc(
                len(new_entries))
            registry.gauge("replay_fill_games").set(fill)
        if evicted_games:
            registry.counter("replay_evicted_games_total").inc(
                evicted_games)
        return len(new_entries)

    def discard_spill(self) -> int:
        """Delete every spilled entry WITHOUT restoring it; returns
        the count. The lockstep resume path: the lockstep actor
        replays its games bit-identically from the checkpointed rng
        chain, so restoring leftovers would double-insert them —
        free-run resumes call :meth:`restore` instead."""
        if not self.spill_dir:
            return 0
        paths = glob.glob(os.path.join(self.spill_dir, "entry.*.json"))
        n = 0
        for path in paths:
            try:
                os.unlink(path)
                n += 1
            except OSError:
                pass
        return n

    def _spill_path(self, seq: int) -> str:
        return os.path.join(
            self.spill_dir,
            f"entry.{self._spill_tag}.{seq:08d}.json")

    def _unspill(self, seq: int) -> None:
        try:
            os.unlink(self._spill_path(seq))
        except OSError:
            pass

    def _observe_out(self, entry: ReplayEntry, fill: int) -> None:
        registry.histogram("replay_sample_staleness_seconds").observe(
            time.monotonic() - entry.t_ingest)
        registry.gauge("replay_fill_games").set(fill)


class JsonlIngester:
    """Tail ``*.jsonl`` shards in a directory into a buffer — the
    transport for out-of-process actors (each actor process appends
    game records to its own shard; see docs/SCALE.md).

    Single-consumer by design (no locks): per-shard byte offsets live
    on the instance, and only newline-terminated lines are consumed —
    a torn tail (writer mid-append or crashed) is left for the next
    :meth:`poll`. Records that fail to parse or decode are counted
    and skipped, never fatal. A shard that SHRINKS under our offset
    (an actor restarted by its supervisor truncates and rewrites, or
    logrotate swapped the file) is re-read from byte 0 — counted in
    ``shard_rotated`` — instead of silently tailing past EOF forever.

    Rotation re-reads make ingest at-least-once; the bounded
    ``game_id`` window (:func:`record_game_id` content hashes, the
    newest ``dedup_window`` ids) makes it effectively exactly-once:
    a record already ingested before the rotation is counted in
    ``dedup_hits`` and skipped, never double-fed to the buffer.
    """

    def __init__(self, buffer: ReplayBuffer, path: str,
                 dedup_window: int = 4096):
        self.buffer = buffer
        self.path = path
        self.skipped = 0
        self.schema_skipped = 0
        self.shard_rotated = 0
        self.dedup_hits = 0
        self.dedup_window = int(dedup_window)
        self._offsets: dict[str, int] = {}
        self._seen: dict[str, None] = {}   # insertion-ordered id ring

    def poll(self) -> int:
        """Ingest every complete new line; returns entries added."""
        added = 0
        for shard in sorted(glob.glob(
                os.path.join(self.path, "*.jsonl"))):
            offset = self._offsets.get(shard, 0)
            try:
                with open(shard, "rb") as f:
                    if os.fstat(f.fileno()).st_size < offset:
                        # rotation/truncation: our offset points past
                        # EOF — restart from the top of the new file
                        self.shard_rotated += 1
                        offset = 0
                        self._offsets[shard] = 0
                    f.seek(offset)
                    data = f.read()
            except OSError:
                continue
            end = data.rfind(b"\n")
            if end < 0:
                continue
            for line in data[:end].splitlines():
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                    games, version = record_to_games(rec)
                    gid = record_game_id(rec, games)
                except UnknownSchemaError:
                    # a NEWER writer shares the stream (rolling
                    # upgrade): count separately — the operator's cue
                    # to upgrade the reader, not a data-corruption
                    # signal
                    self.schema_skipped += 1
                    continue
                except (ValueError, KeyError, TypeError):
                    self.skipped += 1
                    continue
                if gid in self._seen:
                    self.dedup_hits += 1
                    continue
                if self.buffer.put(games, version=version):
                    added += 1
                    self._seen[gid] = None
                    while len(self._seen) > self.dedup_window:
                        self._seen.pop(next(iter(self._seen)))
            self._offsets[shard] = offset + end + 1
        return added


def append_jsonl_record(path: str, games: ZeroGames,
                        version: int = 0, seq: int = 0) -> None:
    """Producer side of the JSONL transport: append one record as a
    single newline-terminated line (the ingester's torn-line rule
    makes a concurrent reader safe without locking)."""
    line = json.dumps(games_to_record(games, version, seq),
                      separators=(",", ":")) + "\n"
    with open(path, "a", encoding="utf-8") as f:
        f.write(line)
