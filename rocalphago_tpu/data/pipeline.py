"""Host→device input pipeline over converted shards.

Replaces the reference's ``shuffled_hdf5_batch_generator`` (h5py chunk
reads + per-sample numpy transforms on host; SURVEY.md §3.1 HOT) with:

* memory-mapped/sharded loads on host,
* index-level shuffling with a persistable permutation (the reference's
  ``shuffle.npz`` resume trick),
* double-buffered ``jax.device_put`` prefetch so the TPU never waits on
  the host,
* dihedral augmentation deferred to the *device* (see
  ``training.symmetries``), not done per-sample on host.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import zipfile

import numpy as np


class ShardedDataset:
    """Random-access view over ``prefix-NNNNN.npz`` shards."""

    def __init__(self, prefix: str):
        with open(f"{prefix}-manifest.json") as f:
            self.manifest = json.load(f)
        self.prefix = prefix
        counts = self.manifest["shard_counts"]
        self._starts = np.cumsum([0] + counts)
        self.num_positions = int(self._starts[-1])
        self._cache: dict[int, tuple[np.ndarray, np.ndarray]] = {}

    def __len__(self) -> int:
        return self.num_positions

    @property
    def planes(self) -> int:
        return int(self.manifest["planes"])

    @property
    def board_size(self) -> int:
        return int(self.manifest["board_size"])

    def _shard(self, i: int):
        if i not in self._cache:
            z = np.load(f"{self.prefix}-{i:05d}.npz")
            self._cache[i] = (z["states"], z["actions"])
            # keep at most 4 shards resident
            while len(self._cache) > 4:
                self._cache.pop(next(iter(self._cache)))
        return self._cache[i]

    def gather(self, indices: np.ndarray):
        """(states [b,s,s,F] uint8, actions [b] int32) for global
        indices (any order)."""
        states = None
        actions = np.empty(len(indices), np.int32)
        shard_ids = np.searchsorted(self._starts, indices, "right") - 1
        for sid in np.unique(shard_ids):
            s_states, s_actions = self._shard(int(sid))
            sel = shard_ids == sid
            local = indices[sel] - self._starts[sid]
            if states is None:
                states = np.empty(
                    (len(indices),) + s_states.shape[1:], s_states.dtype)
            states[sel] = s_states[local]
            actions[sel] = s_actions[local]
        return states, actions


def load_hdf5(path: str):
    """Reference-layout HDF5 → (states uint8 NHWC, actions int32).
    Interchange reader for corpora converted by the reference stack."""
    import h5py
    with h5py.File(path, "r") as h5:
        states = np.asarray(h5["states"], np.uint8).transpose(0, 2, 3, 1)
        actions = np.asarray(h5["actions"], np.int32)
    return states, actions


def split_indices(n: int, fractions=(0.93, 0.05, 0.02), seed: int = 0,
                  path: str | None = None, write: bool = True):
    """Shuffled train/val/test index split; persisted to ``path`` (npz)
    so interrupted runs resume with the identical split (the
    reference's ``shuffle.npz`` behavior). ``write=False``
    (non-coordinator processes) still reads an existing file but never
    creates one — the permutation is a pure function of ``seed``, so
    every process computes the identical split regardless."""
    if path is not None:
        try:
            z = np.load(path)
            tr, va, te = z["train"], z["val"], z["test"]
        except (OSError, KeyError, ValueError, zipfile.BadZipFile):
            # BadZipFile/ValueError: a torn read of a file another
            # process is mid-writing (the writer renames atomically,
            # but NFS-style filesystems can still surface partial
            # views) — fall through and recompute; the permutation is
            # a pure function of the seed, so every process agrees
            tr = None
        if tr is not None:
            total = len(tr) + len(va) + len(te)
            if total != n:
                raise ValueError(
                    f"persisted split at {path} covers {total} positions "
                    f"but the dataset has {n}; the corpus changed — "
                    "delete the split file to reshuffle (this breaks "
                    "resume reproducibility) or restore the old corpus")
            return tr, va, te
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    n_train = int(n * fractions[0])
    n_val = int(n * fractions[1])
    train = perm[:n_train]
    val = perm[n_train:n_train + n_val]
    test = perm[n_train + n_val:]
    if path is not None and write:
        # atomic write: non-coordinator processes read this file
        # concurrently in multi-host runs (.npz suffix on the temp
        # name stops np.savez appending another one)
        tmp = path + ".tmp.npz"
        np.savez(tmp, train=train, val=val, test=test)
        os.replace(tmp, path)
    return train, val, test


def batch_iterator(dataset, indices: np.ndarray, batch_size: int,
                   rng: np.random.Generator, epochs: int | None = None,
                   drop_remainder: bool = True,
                   shard_window: int | None = 4, skip: int = 0):
    """Yield host (states, actions) batches, reshuffling every epoch.

    Shuffling is two-level when the corpus spans many shards: shard
    visit order is permuted per epoch, then indices are fully permuted
    inside windows of ``shard_window`` shards — so a minibatch only
    touches shards the dataset cache holds resident (a global
    permutation would decompress nearly every shard per minibatch).
    ``shard_window=None`` restores the global permutation.

    ``skip`` drops the first ``skip`` batches of the FIRST epoch only —
    index arithmetic, no shard reads — the mid-epoch resume cursor:
    with the same ``rng`` seed the epoch's batch order is reproduced
    and the already-consumed prefix is skipped.
    """
    starts = getattr(dataset, "_starts", None)
    epoch = 0
    while epochs is None or epoch < epochs:
        if shard_window is None or starts is None or len(starts) <= 2:
            order = rng.permutation(indices)
        else:
            shard_of = np.searchsorted(starts, indices, "right") - 1
            shard_ids = rng.permutation(np.unique(shard_of))
            chunks = []
            for w in range(0, len(shard_ids), shard_window):
                window = shard_ids[w:w + shard_window]
                pool = indices[np.isin(shard_of, window)]
                chunks.append(rng.permutation(pool))
            order = np.concatenate(chunks)
        end = (len(order) // batch_size) * batch_size if drop_remainder \
            else len(order)
        start = (skip * batch_size) if epoch == 0 else 0
        for i in range(start, end, batch_size):
            yield dataset.gather(order[i:i + batch_size])
        epoch += 1


def device_prefetch(host_iter, size: int = 2):
    """Stage host batches onto the device ahead of consumption.

    A small thread keeps ``size`` batches in flight (``jax.device_put``
    is async, so staging overlaps with the current train step). Worker
    exceptions propagate to the consumer; closing the generator early
    (the normal case — ``batch_iterator`` is infinite by default)
    releases the worker and its staged batches instead of deadlocking
    on the full queue.

    Close is BOUNDED: the stop event is set, staged batches are
    drained so the worker's pending ``put`` can observe the stop
    within its 100 ms poll, and the worker is joined (5 s cap — it
    may be inside one last host batch read). Before this join the
    prefetch thread was fire-and-forget: ``close()`` returned while
    the worker could still be touching the dataset/shard cache it
    was handed (the exact loose-lifecycle shape the ``thread-no-join``
    lint rule now rejects).
    """
    import jax

    q: queue.Queue = queue.Queue(maxsize=size)
    stop = threading.Event()
    _END = object()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in host_iter:
                if not put(jax.device_put(item)):
                    return
            put(_END)
        except BaseException as e:  # noqa: BLE001 — relayed to consumer
            put(e)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        # drain staged batches so a worker blocked on the full queue
        # reaches its stop-event poll, then wait for it to exit —
        # quiescence is part of the generator's close contract
        while not q.empty():
            q.get_nowait()
        t.join(timeout=5.0)
