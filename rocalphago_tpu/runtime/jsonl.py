"""Tolerant JSONL reading (stdlib-only — safe for light scripts).

A process killed mid-``write`` leaves AT MOST one torn trailing line
in a line-buffered JSONL stream (``io.metrics.MetricsLogger`` emits
whole lines through a ``buffering=1`` handle; ``tests/
test_runtime.py`` pins the at-most-one-torn-line invariant), so a
reader that skips undecodable lines loses at most the final
in-flight record instead of crashing. ``bench.py`` and
``scripts/zero_curve.py`` read crash-prone logs through this.
"""

from __future__ import annotations

import json


def read_jsonl(path: str, on_error: str = "skip") -> list:
    """One dict per well-formed line of ``path``.

    ``on_error``: "skip" (default) drops undecodable or non-object
    lines; "raise" propagates the decode error (for writers that
    must be exact)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                if on_error == "raise":
                    raise
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def iter_jsonl(f, on_error: str = "skip"):
    """Streaming form over an open file object."""
    for line in f:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            if on_error == "raise":
                raise
            continue
        if isinstance(rec, dict):
            yield rec
