"""Pipelined chunk dispatch: keep a compiled chunk in flight while
the host decides.

The PR 1-2 chunked runners made every hot loop watchdog-safe by
splitting one long device program into ``chunk``-sized compiled
programs driven from a host loop — but the loops then paid a full
host sync per chunk (``jax.block_until_ready`` for the deadline
check, a blocking ``device_get`` for the self-play done-poll), so the
device idled in every gap, on exactly the sims/sec and games/min
paths the benchmarks headline. This module takes the host back out of
the steady state: a :class:`ChunkPipeline` lets the loop dispatch
chunk N+1 while the host inspects chunk N's already-materialized
scalars, so deadline checks, done-polls and per-chunk observability
run ONE CHUNK BEHIND with the device never idle.

Semantics: pipelining is a SCHEDULING change, not a semantics change.
The chunk programs run in the same order with the same operands —
results are bit-identical to the sync path at any depth
(tier-1-asserted for PUCT search, gumbel search, chunked self-play
and a zero iteration). What shifts is *when the host learns things*:

* a hard deadline (``runtime.deadline.Deadline``) is still checked
  between chunks, but the host may have one extra chunk in flight
  when it sees the expiry — the hard-stop overshoot bound becomes
  "at most ``depth`` in-flight chunks" (one, at the default depth)
  on top of the sync bound; the anytime answer and the one-chunk
  floor are unchanged (docs/RESILIENCE.md);
* the self-play done-poll reads the done-scalar of a RETIRED chunk
  (already materialized — the fetch never syncs the fresh dispatch);
  an extra chunk dispatched onto all-done states is a proven no-op
  (the engine freezes finished games) and its recorded rows are
  replaced by the same zero padding the sync path writes, so the
  result stays bit-identical;
* fault barriers (``runtime.faults``) keep firing once per chunk, in
  dispatch order, on the host — injection points are unmoved.

Depth: ``depth`` = how many dispatched-but-unretired chunks the host
may run ahead. ``depth=0`` reproduces today's fully synchronous
behavior (every ``push`` blocks on the chunk just pushed);
``depth=1`` (the default) keeps one chunk in flight. The default is
env-overridable via ``ROCALPHAGO_PIPELINE_DEPTH`` so the TPU window
hunter can A/B without code changes.

Donation: pipelining must not double slab memory — the chunk loops
donate their big device-resident carries (DeviceTree slabs, self-play
``GoState``, replay grad accumulators) into the next chunk's program
(``jax.jit(..., donate_argnums=...)``). Donating programs advertise
``donates_buffers = True``; :mod:`runtime.retries` REFUSES to wrap
them (a failed dispatch may already have invalidated the donated
input, so a re-dispatch would compute on garbage). Retry stays valid
one level up: the trainers re-invoke the whole iteration from
never-donated state. See docs/PERFORMANCE.md for the full donation
rules.

Observability (``obs.registry``): every pipeline records the
``dispatch_gap_s{runner=...}`` histogram (host-side gaps during which
the device had NOTHING in flight — the idle the sync path pays per
chunk), a ``device_occupancy{runner=...}`` gauge (1 − gap/wall over
the pipeline's active windows) and ``dispatch_chunks_total``;
``scripts/obs_report.py`` renders them and the benches publish
``host_gap_frac`` for the pipelined-vs-sync A/B.
"""

from __future__ import annotations

import os
import time
from collections import deque

DEPTH_ENV = "ROCALPHAGO_PIPELINE_DEPTH"
DEFAULT_DEPTH = 1


def default_depth() -> int:
    """The process-default pipeline depth: ``$ROCALPHAGO_PIPELINE_
    DEPTH`` if set (0 = sync), else :data:`DEFAULT_DEPTH`. Read at
    call time so tests and the TPU hunter can flip it per run."""
    raw = os.environ.get(DEPTH_ENV, "").strip()
    if not raw:
        return DEFAULT_DEPTH
    try:
        depth = int(raw)
    except ValueError as e:
        raise ValueError(
            f"{DEPTH_ENV} must be a non-negative integer, got {raw!r}"
        ) from e
    if depth < 0:
        raise ValueError(f"{DEPTH_ENV} must be >= 0, got {depth}")
    return depth


class ChunkPipeline:
    """Bounded window of in-flight compiled chunks.

    Protocol (one pipeline per chunked run, or one per bench shared
    across reps)::

        pipe = ChunkPipeline(depth=None, runner="device_mcts")
        for ...:                      # the host chunk loop
            out = chunk_program(...)  # async dispatch
            retired = pipe.push(out.some_scalar, payload=...)
            # decide on `retired` chunks' scalars — they are READY
            # (the push blocked until ≤ depth chunks stayed in flight)
        pipe.drain()    # block the tail (deadline-enforced paths)
        # -- or --
        pipe.finish()   # just close the accounting window (async
                        #    paths; a later fetch syncs the tail)

    ``push`` registers a freshly dispatched chunk via a small output
    array ``handle`` (any per-chunk output leaf; a done-scalar when
    the caller wants to read it) and blocks until at most ``depth``
    chunks remain in flight — so the host is paced by real device
    completion, never more than ``depth`` chunks ahead. It returns
    the ``(payload, handle)`` pairs of the chunks retired by this
    call, oldest first; their handles are materialized, so a
    ``device_get`` on them cannot sync the fresh dispatch.

    Gap accounting: a "gap" is host wall time during which NO chunk
    was in flight between two pushes of the same window — the device
    idle the sync path pays once per chunk. ``host_gap_frac`` is
    gap time over active-window wall time; the tail after the last
    retire of a window is NOT a gap (the run is over). Stats survive
    ``finish``; a later ``push`` opens a new window (benches share
    one pipeline across reps). ``reset_stats`` zeroes them (after a
    warmup/compile rep).
    """

    def __init__(self, depth: int | None = None, runner: str = "",
                 registry=None):
        self.depth = default_depth() if depth is None else int(depth)
        if self.depth < 0:
            raise ValueError(f"depth must be >= 0, got {self.depth}")
        self.runner = runner
        self._inflight: deque = deque()
        self._gap_started = None     # queue drained mid-window
        self._window_start = None
        self.chunks = 0
        self.gaps = 0
        self.gap_s = 0.0
        self.wall_s = 0.0            # closed windows only
        self._gap_h = self._occ_g = self._chunks_c = None
        if runner:
            from rocalphago_tpu.obs import registry as obs_registry

            reg = registry or obs_registry.REGISTRY
            self._gap_h = reg.histogram("dispatch_gap_s", runner=runner)
            self._occ_g = reg.gauge("device_occupancy", runner=runner)
            self._chunks_c = reg.counter("dispatch_chunks_total",
                                         runner=runner)

    # ------------------------------------------------------ protocol

    def push(self, handle, payload=None) -> list:
        """Register a dispatched chunk; block until ≤ ``depth`` stay
        in flight; return the retired ``(payload, handle)`` pairs."""
        now = time.monotonic()
        if self._window_start is None:
            self._window_start = now
        if self._gap_started is not None:
            gap = now - self._gap_started
            self._gap_started = None
            self.gaps += 1
            self.gap_s += gap
            if self._gap_h is not None:
                self._gap_h.observe(gap)
        self._inflight.append((payload, handle))
        self.chunks += 1
        if self._chunks_c is not None:
            self._chunks_c.inc()
        retired = []
        while len(self._inflight) > self.depth:
            retired.append(self._retire())
        return retired

    def _retire(self):
        payload, handle = self._inflight.popleft()
        if handle is not None:
            import jax

            jax.block_until_ready(handle)
        if not self._inflight:
            # nothing left in flight: the device is (potentially)
            # idle from here until the next push — that span is the
            # gap the pipeline exists to remove
            self._gap_started = time.monotonic()
        return payload, handle

    def pending(self) -> int:
        return len(self._inflight)

    def drain(self) -> list:
        """Retire (block) every in-flight chunk, then close the
        window. The deadline-enforced paths drain so their rate and
        margin metrics measure real execution, not dispatch."""
        retired = []
        while self._inflight:
            retired.append(self._retire())
        self.finish()
        return retired

    def finish(self) -> None:
        """Close the accounting window WITHOUT blocking the tail —
        the async (training) paths' natural end, where a downstream
        fetch syncs whatever is still in flight. Idempotent."""
        if self._window_start is None:
            return
        end = (self._gap_started if self._gap_started is not None
               and not self._inflight else time.monotonic())
        self.wall_s += max(end - self._window_start, 0.0)
        self._window_start = None
        self._gap_started = None
        if self._occ_g is not None:
            self._occ_g.set(self.occupancy)

    # ------------------------------------------------------- stats

    @property
    def host_gap_frac(self) -> float:
        """Gap time over active wall time (closed windows; the
        current window, if any, counts up to now)."""
        wall = self.wall_s
        if self._window_start is not None:
            wall += time.monotonic() - self._window_start
        if wall <= 0.0:
            return 0.0
        return min(1.0, self.gap_s / wall)

    @property
    def occupancy(self) -> float:
        """1 − ``host_gap_frac``: fraction of the pipeline's active
        wall time with work in flight (the gauge value)."""
        return 1.0 - self.host_gap_frac

    def reset_stats(self) -> None:
        """Zero the counters/accounting (keeps depth and metric
        handles). Benches call this after their warmup/compile rep so
        the A/B numbers cover measured reps only. Refuses while
        chunks are in flight — drain or finish first."""
        if self._inflight:
            raise RuntimeError(
                "reset_stats with chunks still in flight — drain() "
                "first")
        self.chunks = self.gaps = 0
        self.gap_s = self.wall_s = 0.0
        self._window_start = self._gap_started = None

    def __repr__(self) -> str:
        return (f"ChunkPipeline(depth={self.depth}, "
                f"runner={self.runner!r}, chunks={self.chunks}, "
                f"inflight={len(self._inflight)}, "
                f"gap_frac={self.host_gap_frac:.4f})")
