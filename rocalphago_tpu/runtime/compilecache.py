"""Shared persistent-XLA-compile-cache setup for every entry point.

The round-5 headline regression was partly ``includes_compile: true``:
the driver's bench capture paid a cold 20–40s compile because only
``bench.py`` and the test suite configured JAX's persistent
compilation cache — the trainers, the self-play CLI and the GTP
server each recompiled their programs from scratch on every launch.
This helper is the one place that knob lives now; every CLI calls
:func:`enable_compile_cache` at startup, so repeat runs of the SAME
program (the common operational case: resumed trainers, re-launched
benches, restarted GTP engines) skip compile entirely.

Env knob ``ROCALPHAGO_COMPILE_CACHE``:

* unset (default) → ``~/.cache/jax_comp_cache``;
* a path → that directory;
* ``0`` / ``off`` / ``none`` → disabled (no config touched).

First configuration wins: if the process has already pinned a cache
directory (the test suite's conftest, an operator's explicit
``jax.config`` call), the helper leaves it alone — re-pointing the
cache mid-process would split one run's compiles across two caches.

Note the JAX CPU backend does not serialize executables to this cache
(measured no-op — scripts/test.sh); the payoff is on TPU, where the
big self-play/search programs cost 20–40s each to compile.
"""

from __future__ import annotations

import os

ENV = "ROCALPHAGO_COMPILE_CACHE"
DEFAULT_DIR = "~/.cache/jax_comp_cache"
_OFF = ("0", "off", "none", "disable", "disabled")


def enable_compile_cache(min_compile_secs: int = 5) -> str | None:
    """Point JAX's persistent compilation cache at the configured
    directory; returns the active cache dir (existing or newly set),
    or None when disabled/unavailable. Safe to call from any entry
    point, any number of times."""
    raw = os.environ.get(ENV)
    if raw is not None and raw.strip().lower() in _OFF:
        return None
    import jax

    try:
        current = jax.config.jax_compilation_cache_dir
    except AttributeError:      # very old jax: no such config at all
        return None
    if current:
        return current          # first configuration wins
    path = os.path.expanduser(raw or DEFAULT_DIR)
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
    except Exception:  # noqa: BLE001 — older jax without the knobs
        return None
    return path
