"""Hard wall-clock deadlines for the serving path.

The GTP time machinery is PREDICTIVE: :class:`~rocalphago_tpu.search.
clock.MoveClock` converts the per-move second budget into a simulation
budget from a measured sims/sec estimate, and the search then runs
that many simulations however long they take. A compile stall, a
mispredicted rate, or a slow chunk simply blows the clock — the plan
was wrong and nothing enforces it. :class:`Deadline` is the ENFORCER:
an absolute ``time.monotonic`` timestamp threaded through the chunked
search (``run_sims_chunked`` / the gumbel ``run_chunked`` in
:mod:`rocalphago_tpu.search.device_mcts`), checked between compiled
chunks. When it expires the search stops where it is and the caller
serves the ANYTIME answer — argmax of the visits accumulated so far
(the Gumbel searcher reranks its surviving candidates) — instead of
trusting the prediction to the end.

Division of labor: the ``MoveClock`` stays the planner (how many sims
SHOULD fit), the ``Deadline`` is the enforcer (when the move MUST go
out). The floor is one chunk: the first chunk always runs, so an
already-expired deadline still yields a searched move and the caller
returns within the deadline plus one chunk's wall time — the
AlphaGo-lineage anytime contract (the policy prior itself is the
rung below, served by the degradation ladder in
:mod:`rocalphago_tpu.interface.resilient`).
"""

from __future__ import annotations

import time


class Deadline:
    """Absolute wall-clock cutoff (``time.monotonic`` domain).

    ``Deadline(None)`` / ``Deadline.after(None)`` is the unlimited
    deadline: ``expired()`` is always False and ``remaining()`` is
    None, so callers thread one object unconditionally instead of
    branching on "is there a clock at all".
    """

    __slots__ = ("at",)

    def __init__(self, at: float | None):
        self.at = at                  # monotonic timestamp, or None

    @classmethod
    def after(cls, seconds: float | None) -> "Deadline":
        """Deadline ``seconds`` from now (None = unlimited; negative
        budgets clamp to an already-expired deadline)."""
        if seconds is None:
            return cls(None)
        return cls(time.monotonic() + max(float(seconds), 0.0))

    @property
    def unlimited(self) -> bool:
        return self.at is None

    def expired(self) -> bool:
        return self.at is not None and time.monotonic() >= self.at

    def remaining(self) -> float | None:
        """Seconds left (clamped at 0.0), or None when unlimited."""
        if self.at is None:
            return None
        return max(0.0, self.at - time.monotonic())

    def __repr__(self) -> str:
        if self.at is None:
            return "Deadline(unlimited)"
        return f"Deadline(in {self.at - time.monotonic():+.3f}s)"
