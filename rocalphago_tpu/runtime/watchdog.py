"""Heartbeat watchdog for long training loops.

A wedged device program (the round-2 tunnel postmortem: a worker kill
mid-program hangs the host dispatch forever) leaves a ``nohup`` run
silently stuck for hours. The watchdog is a daemon thread the loop
feeds with :meth:`Watchdog.beat` once per iteration; if no beat
arrives within the deadline it logs a ``stall`` event (to the run's
``metrics.jsonl`` via the supplied logger) and — in abort mode —
calls the caller's ``abort_fn``, whose job is to persist the last
COMPLETED state (the in-flight iteration is unrecoverable from a
sibling thread) and ``os._exit``. Logging mode just leaves a
greppable trail for the operator.

Stall events carry WHERE the process hung, not just that it hung:
the ``span`` field is the deepest open tracing span across all
threads (:func:`rocalphago_tpu.obs.trace.where`) at the moment the
watchdog fired — e.g. ``zero.iteration/zero.selfplay`` — so the
operator reads the stuck phase straight off ``metrics.jsonl``.

Starvation vs deadlock: a learner blocked on an empty replay buffer
produces the same no-beat signature as a wedged device program. Code
that blocks *by design* wraps the wait in :func:`waiting_on`, and the
stall event gains a ``waiting_on`` field (e.g. ``replay_fill``) so
soak analysis can tell "waiting for producers" from "hung".
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time

from rocalphago_tpu.analysis import lockcheck
from rocalphago_tpu.obs import trace

STALL_EXIT_CODE = 170

_waiting_lock = lockcheck.make_lock("watchdog._waiting_lock")
_waiting: dict[int, str] = {}  # guarded-by: _waiting_lock


@contextlib.contextmanager
def waiting_on(phase: str):
    """Tag the calling thread as deliberately blocked on ``phase``.

    Nested tags restore the outer phase on exit; the registry is
    keyed by thread ident so concurrent waiters don't clobber each
    other. The lock is released across the yield — the tag is a
    plain dict entry while the caller blocks.
    """
    ident = threading.get_ident()
    with _waiting_lock:
        prev = _waiting.get(ident)
        _waiting[ident] = phase
    try:
        yield
    finally:
        with _waiting_lock:
            if prev is None:
                _waiting.pop(ident, None)
            else:
                _waiting[ident] = prev


def waiting_phases() -> tuple[str, ...]:
    """Sorted distinct phases threads are currently blocked on."""
    with _waiting_lock:
        return tuple(sorted(set(_waiting.values())))


class Watchdog:
    """``with Watchdog(deadline_s, metrics=logger) as wd: wd.beat()``.

    ``metrics``: a ``MetricsLogger``-shaped object (``log(event,
    **fields)``) or None for stderr. ``abort_fn``: optional callable
    run once on the first stall; after it returns the watchdog exits
    the process with ``STALL_EXIT_CODE`` (pass ``exit=False`` to keep
    the process — tests). Repeated stalls without an ``abort_fn`` log
    every ``deadline_s``.
    """

    def __init__(self, deadline_s: float, metrics=None,
                 abort_fn=None, name: str = "train",
                 exit: bool = True, poll_s: float | None = None):
        if deadline_s <= 0:
            raise ValueError(f"deadline must be > 0, got {deadline_s}")
        self.deadline_s = deadline_s
        self.metrics = metrics
        self.abort_fn = abort_fn
        self.name = name
        self.exit = exit
        self.stalls = 0
        self._poll_s = poll_s or min(1.0, deadline_s / 4.0)
        # deliberately lock-free (so deliberately NOT `# guarded-by:`
        # annotated): one writer (beat) and one reader (_watch), and
        # a torn/stale read of a monotonic float only shifts a stall
        # report by one poll — see docs/CONCURRENCY.md's benign list
        self._last_beat = time.monotonic()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._watch, name=f"watchdog-{name}", daemon=True)

    # ------------------------------------------------------ lifecycle

    def start(self) -> "Watchdog":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------ heartbeat

    def beat(self) -> None:
        self._last_beat = time.monotonic()

    def _log(self, elapsed: float) -> None:
        at = trace.where()          # deepest open span, any thread
        waits = waiting_phases()
        waiting = ",".join(waits) if waits else None
        if self.metrics is not None:
            self.metrics.log("stall", watchdog=self.name,
                             elapsed_s=round(elapsed, 1),
                             deadline_s=self.deadline_s, span=at,
                             waiting_on=waiting)
        else:
            print(f"watchdog[{self.name}]: no heartbeat for "
                  f"{elapsed:.0f}s (deadline {self.deadline_s:.0f}s)"
                  f"{f' in {at}' if at else ''}"
                  f"{f' waiting on {waiting}' if waiting else ''}",
                  file=sys.stderr)

    def _watch(self) -> None:
        while not self._stop.wait(self._poll_s):
            elapsed = time.monotonic() - self._last_beat
            if elapsed < self.deadline_s:
                continue
            self.stalls += 1
            self._log(elapsed)
            if self.abort_fn is not None:
                try:
                    self.abort_fn()
                finally:
                    if self.exit:
                        sys.stdout.flush()
                        sys.stderr.flush()
                        os._exit(STALL_EXIT_CODE)
                return
            # keep logging, but not more than once per deadline
            self._last_beat = time.monotonic()
