"""Deterministic fault injection at named barriers.

Opt-in chaos harness: trainers call :func:`barrier` at named points
("barriers") in their loops; a fault PLAN — normally from the
``ROCALPHAGO_FAULT_PLAN`` env var, or installed programmatically via
:func:`install` — declares which barrier hits should kill the process
or raise. With no plan installed a barrier call is two attribute
loads and a ``None`` check, so production loops pay nothing.

Plan grammar (full reference in docs/RESILIENCE.md)::

    plan   := spec ("," spec)*
    spec   := kind "@" ["iter" N "."] barrier [":" hit]
              [":p=" P] [":seed=" S] ["=" arg]
    kind   := "crash" | "io_error" | "error" | "sleep" | "kill"

* ``crash`` — flush stdio and ``os._exit(FAULT_EXIT_CODE)`` (a hard
  kill: no atexit hooks, no finally blocks — the honest model of
  SIGKILL/OOM/power loss);
* ``io_error`` — raise :class:`InjectedFault` (an ``OSError``
  subclass, classified transient by :mod:`.retries`);
* ``error`` — raise ``RuntimeError`` (classified non-transient);
* ``sleep`` — block ``arg`` seconds (trips :mod:`.watchdog`);
* ``kill`` — raise :class:`InjectedKill` (a ``RuntimeError``
  subclass: NON-transient, so the retry layer re-raises immediately
  and the worker thread genuinely dies — the signal the
  :mod:`.supervisor` resurrect path is exercised by).

``iterN.`` restricts the spec to barrier hits whose ``iteration``
argument equals N. ``:hit`` fires on the k-th matching hit (default
the first). A deterministic spec fires at most once. Barrier names
are dot-qualified (``zero.post_save``); a spec's barrier matches on
the full name or any dot-suffix, so ``crash@post_save`` hits
``zero.post_save`` and ``sl.post_save`` alike while
``crash@zero.post_save`` hits only the zero trainer. The barrier
name ``random`` is a wildcard matching EVERY barrier.

RANDOMIZED schedules (the chaos-soak grammar): ``:p=P`` makes the
spec probabilistic — from its ``hit``-th matching hit onward it
fires with probability P per hit, repeatedly (it never retires).
The draw is DETERMINISTIC: hashed from ``seed`` (``:seed=S``,
default 0), the barrier name, and the per-spec hit count, so a
given plan produces the identical kill schedule on every run —
chaos soaks are reproducible by seed. For convenience the comma
form ``kill@random:p=0.05,seed=7`` is accepted too: a plan
fragment with no ``@`` that looks like ``p=``/``seed=`` re-attaches
to the preceding spec.

Examples::

    ROCALPHAGO_FAULT_PLAN=crash@iter3.post_save
    ROCALPHAGO_FAULT_PLAN=io_error@promote:2,sleep@pre_iteration=0.5
    ROCALPHAGO_FAULT_PLAN=kill@random:p=0.05,seed=7
    ROCALPHAGO_FAULT_PLAN=kill@actor.game:p=0.2,kill@learner.step:3
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import re
import sys
import time

FAULT_PLAN_ENV = "ROCALPHAGO_FAULT_PLAN"
FAULT_EXIT_CODE = 173          # distinct from shell/signal codes
_KINDS = ("crash", "io_error", "error", "sleep", "kill")


class InjectedFault(OSError):
    """The raisable injected fault (an OSError: transient class)."""


class InjectedKill(RuntimeError):
    """The injected worker kill (non-transient by the
    :mod:`.retries` classifier, so it rides THROUGH the retry layer
    and takes the worker thread down — the supervisor's problem, not
    the retrier's)."""


@dataclasses.dataclass
class _Spec:
    kind: str
    barrier: str
    iteration: int | None
    hit: int
    arg: float | None
    text: str                  # original spec, for log lines
    p: float | None = None     # probabilistic: fire-chance per hit
    seed: int = 0
    count: int = 0
    fired: bool = False

    def matches(self, name: str, iteration) -> bool:
        if self.iteration is not None and iteration != self.iteration:
            return False
        return (self.barrier == "random"
                or name == self.barrier
                or name.endswith("." + self.barrier))

    def draw(self, name: str) -> bool:
        """Deterministic per-hit Bernoulli draw for ``p`` specs:
        hashed from (seed, barrier name, hit count) so the same plan
        replays the same kill schedule."""
        digest = hashlib.sha256(
            f"{self.seed}:{name}:{self.count}".encode()).digest()
        frac = int.from_bytes(digest[:8], "big") / float(1 << 64)
        return frac < (self.p or 0.0)


_SPEC_RE = re.compile(
    r"^(?P<kind>[a-z_]+)@(?P<barrier>[A-Za-z0-9_.]+)"
    r"(?::(?P<hit>\d+))?(?::p=(?P<p>[0-9.]+))?"
    r"(?::seed=(?P<seed>\d+))?(?:=(?P<arg>[0-9.]+))?$")

# a plan fragment with no "@" that re-attaches to the previous spec
# (the comma form of the probabilistic params: kill@random:p=,seed=)
_PARAM_RE = re.compile(r"^(p|seed)=[0-9.]+$")

# None = not yet loaded from the env; [] = loaded, empty
_plan: list[_Spec] | None = None


def parse_plan(text: str) -> list[_Spec]:
    # re-attach comma-separated p=/seed= fragments to their spec
    raws: list[str] = []
    for frag in text.split(","):
        frag = frag.strip()
        if not frag:
            continue
        if raws and "@" not in frag and _PARAM_RE.match(frag):
            raws[-1] += ":" + frag
        else:
            raws.append(frag)
    specs = []
    for raw in raws:
        m = _SPEC_RE.match(raw)
        if m is None:
            raise ValueError(
                f"bad fault spec {raw!r}: expected "
                "kind@[iterN.]barrier[:hit][=arg] "
                f"(kinds: {', '.join(_KINDS)})")
        kind = m.group("kind")
        if kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} in {raw!r} "
                f"(kinds: {', '.join(_KINDS)})")
        barrier_part = m.group("barrier")
        iteration = None
        first, _, rest = barrier_part.partition(".")
        it_m = re.fullmatch(r"iter(\d+)", first)
        if it_m and rest:
            iteration = int(it_m.group(1))
            barrier_part = rest
        if kind == "sleep" and m.group("arg") is None:
            raise ValueError(
                f"sleep spec {raw!r} needs a duration: sleep@name=0.5")
        p = float(m.group("p")) if m.group("p") else None
        if p is not None and not 0.0 <= p <= 1.0:
            raise ValueError(
                f"fault spec {raw!r}: p must be in [0, 1], got {p}")
        if barrier_part == "random" and p is None:
            raise ValueError(
                f"fault spec {raw!r}: the 'random' wildcard barrier "
                "needs a probability (e.g. kill@random:p=0.05) — "
                "without one it would fire on the very first barrier "
                "of the run")
        specs.append(_Spec(
            kind=kind, barrier=barrier_part, iteration=iteration,
            hit=int(m.group("hit") or 1),
            arg=float(m.group("arg")) if m.group("arg") else None,
            p=p, seed=int(m.group("seed") or 0),
            text=raw))
    return specs


def install(plan: str | None) -> None:
    """Set the active plan (tests); ``None`` re-reads the env on the
    next barrier call, ``""`` disables injection."""
    global _plan
    _plan = None if plan is None else parse_plan(plan)


def _load() -> list[_Spec]:
    global _plan
    if _plan is None:
        _plan = parse_plan(os.environ.get(FAULT_PLAN_ENV, ""))
    return _plan


def active() -> bool:
    return bool(_load())


def _fire(spec: _Spec, name: str) -> None:
    # probabilistic specs never retire: each later hit draws again
    spec.fired = spec.p is None
    if spec.kind == "kill":
        raise InjectedKill(
            f"injected kill at {name} (spec {spec.text})")
    if spec.kind == "crash":
        print(f"faults: injected crash at {name} "
              f"(spec {spec.text})", file=sys.stderr)
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(FAULT_EXIT_CODE)
    if spec.kind == "io_error":
        raise InjectedFault(
            f"injected io_error at {name} (spec {spec.text})")
    if spec.kind == "error":
        raise RuntimeError(
            f"injected error at {name} (spec {spec.text})")
    if spec.kind == "sleep":
        time.sleep(spec.arg or 0.0)


def barrier(name: str, iteration: int | None = None) -> None:
    """Declare a fault barrier. No-op unless a plan names it."""
    plan = _plan if _plan is not None else _load()
    if not plan:
        return
    for spec in plan:
        if spec.fired or not spec.matches(name, iteration):
            continue
        spec.count += 1
        if spec.count < spec.hit:
            continue
        if spec.p is not None and not spec.draw(name):
            continue
        _fire(spec, name)
