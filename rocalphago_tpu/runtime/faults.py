"""Deterministic fault injection at named barriers.

Opt-in chaos harness: trainers call :func:`barrier` at named points
("barriers") in their loops; a fault PLAN — normally from the
``ROCALPHAGO_FAULT_PLAN`` env var, or installed programmatically via
:func:`install` — declares which barrier hits should kill the process
or raise. With no plan installed a barrier call is two attribute
loads and a ``None`` check, so production loops pay nothing.

Plan grammar (full reference in docs/RESILIENCE.md)::

    plan   := spec ("," spec)*
    spec   := kind "@" ["iter" N "."] barrier [":" hit] ["=" arg]
    kind   := "crash" | "io_error" | "error" | "sleep"

* ``crash`` — flush stdio and ``os._exit(FAULT_EXIT_CODE)`` (a hard
  kill: no atexit hooks, no finally blocks — the honest model of
  SIGKILL/OOM/power loss);
* ``io_error`` — raise :class:`InjectedFault` (an ``OSError``
  subclass, classified transient by :mod:`.retries`);
* ``error`` — raise ``RuntimeError`` (classified non-transient);
* ``sleep`` — block ``arg`` seconds (trips :mod:`.watchdog`).

``iterN.`` restricts the spec to barrier hits whose ``iteration``
argument equals N. ``:hit`` fires on the k-th matching hit (default
the first). Each spec fires at most once. Barrier names are
dot-qualified (``zero.post_save``); a spec's barrier matches on the
full name or any dot-suffix, so ``crash@post_save`` hits
``zero.post_save`` and ``sl.post_save`` alike while
``crash@zero.post_save`` hits only the zero trainer.

Examples::

    ROCALPHAGO_FAULT_PLAN=crash@iter3.post_save
    ROCALPHAGO_FAULT_PLAN=io_error@promote:2,sleep@pre_iteration=0.5
"""

from __future__ import annotations

import dataclasses
import os
import re
import sys
import time

FAULT_PLAN_ENV = "ROCALPHAGO_FAULT_PLAN"
FAULT_EXIT_CODE = 173          # distinct from shell/signal codes
_KINDS = ("crash", "io_error", "error", "sleep")


class InjectedFault(OSError):
    """The raisable injected fault (an OSError: transient class)."""


@dataclasses.dataclass
class _Spec:
    kind: str
    barrier: str
    iteration: int | None
    hit: int
    arg: float | None
    text: str                  # original spec, for log lines
    count: int = 0
    fired: bool = False

    def matches(self, name: str, iteration) -> bool:
        if self.iteration is not None and iteration != self.iteration:
            return False
        return (name == self.barrier
                or name.endswith("." + self.barrier))


_SPEC_RE = re.compile(
    r"^(?P<kind>[a-z_]+)@(?P<barrier>[A-Za-z0-9_.]+)"
    r"(?::(?P<hit>\d+))?(?:=(?P<arg>[0-9.]+))?$")

# None = not yet loaded from the env; [] = loaded, empty
_plan: list[_Spec] | None = None


def parse_plan(text: str) -> list[_Spec]:
    specs = []
    for raw in text.split(","):
        raw = raw.strip()
        if not raw:
            continue
        m = _SPEC_RE.match(raw)
        if m is None:
            raise ValueError(
                f"bad fault spec {raw!r}: expected "
                "kind@[iterN.]barrier[:hit][=arg] "
                f"(kinds: {', '.join(_KINDS)})")
        kind = m.group("kind")
        if kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} in {raw!r} "
                f"(kinds: {', '.join(_KINDS)})")
        barrier_part = m.group("barrier")
        iteration = None
        first, _, rest = barrier_part.partition(".")
        it_m = re.fullmatch(r"iter(\d+)", first)
        if it_m and rest:
            iteration = int(it_m.group(1))
            barrier_part = rest
        if kind == "sleep" and m.group("arg") is None:
            raise ValueError(
                f"sleep spec {raw!r} needs a duration: sleep@name=0.5")
        specs.append(_Spec(
            kind=kind, barrier=barrier_part, iteration=iteration,
            hit=int(m.group("hit") or 1),
            arg=float(m.group("arg")) if m.group("arg") else None,
            text=raw))
    return specs


def install(plan: str | None) -> None:
    """Set the active plan (tests); ``None`` re-reads the env on the
    next barrier call, ``""`` disables injection."""
    global _plan
    _plan = None if plan is None else parse_plan(plan)


def _load() -> list[_Spec]:
    global _plan
    if _plan is None:
        _plan = parse_plan(os.environ.get(FAULT_PLAN_ENV, ""))
    return _plan


def active() -> bool:
    return bool(_load())


def _fire(spec: _Spec, name: str) -> None:
    spec.fired = True
    if spec.kind == "crash":
        print(f"faults: injected crash at {name} "
              f"(spec {spec.text})", file=sys.stderr)
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(FAULT_EXIT_CODE)
    if spec.kind == "io_error":
        raise InjectedFault(
            f"injected io_error at {name} (spec {spec.text})")
    if spec.kind == "error":
        raise RuntimeError(
            f"injected error at {name} (spec {spec.text})")
    if spec.kind == "sleep":
        time.sleep(spec.arg or 0.0)


def barrier(name: str, iteration: int | None = None) -> None:
    """Declare a fault barrier. No-op unless a plan names it."""
    plan = _plan if _plan is not None else _load()
    if not plan:
        return
    for spec in plan:
        if spec.fired or not spec.matches(name, iteration):
            continue
        spec.count += 1
        if spec.count >= spec.hit:
            _fire(spec, name)
