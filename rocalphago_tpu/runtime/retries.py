"""Retry with deterministic-jitter exponential backoff.

The classifier draws the line the round-5 tunnel taught: hardware and
infrastructure flake (device unavailable, RPC deadline, filesystem
hiccough, preempted TPU worker) is TRANSIENT — re-dispatching the
same pure program is safe and usually succeeds — while programming
errors (shape mismatches, bad arguments, assertion failures) must
surface immediately; retrying those just burns the backoff budget in
front of the real traceback.

Jitter is DETERMINISTIC (hashed from a seed, the wrapped function's
name, and the attempt index) so an interrupted-and-resumed run
replays the identical sleep schedule — the same discipline the
trainers use for every other random draw (exact resume is the
invariant the chaos tests assert).

Only retry PURE work: a functional train step (state in, new state
out) or an idempotent artifact write. Never wrap a step whose input
buffers were donated to the device program — after a failed dispatch
the donated buffers may already be invalid, so the retry would
compute on garbage. Since the pipelined-dispatch PR this hazard is
ENFORCED, not just documented: chunk programs that donate advertise
``donates_buffers = True`` (the convention
``runtime.pipeline``-driven loops follow), and :func:`retry` /
:func:`retry_call` refuse to wrap such a callable with an explicit
``ValueError``. Retry remains valid one level up — the chunked
iterations confine donation to loop-internal carries and re-derive
them from never-donated state, so wrapping the *iteration* is safe
(and is what the trainers do).
"""

from __future__ import annotations

import functools
import hashlib
import sys
import time

# gRPC/absl status words XLA surfaces for infrastructure failures
# (the jaxlib exception type is one opaque XlaRuntimeError — the
# status word in the message is the only classification signal)
_TRANSIENT_STATUS = (
    "RESOURCE_EXHAUSTED", "UNAVAILABLE", "DEADLINE_EXCEEDED",
    "ABORTED", "CANCELLED", "DATA_LOSS", "INTERNAL",
    "failed to connect", "socket closed", "connection reset",
    "premature end of", "device or resource busy",
)
_TRANSIENT_TYPE_NAMES = (
    "XlaRuntimeError", "JaxRuntimeError", "RpcError",
    "DeadlineExceeded", "ServiceUnavailable",
)
# programming errors: never retry, whatever the message says
_FATAL_TYPES = (TypeError, ValueError, KeyError, IndexError,
                AttributeError, AssertionError, ZeroDivisionError,
                NotImplementedError, KeyboardInterrupt, SystemExit)


def is_transient(exc: BaseException) -> bool:
    """True if ``exc`` looks like infrastructure flake worth a
    re-dispatch; False for programming errors."""
    if isinstance(exc, _FATAL_TYPES):
        return False
    # filesystem / network / device-file errors (includes the chaos
    # harness's InjectedFault, an OSError subclass — by design: the
    # injection models exactly this class of failure)
    if isinstance(exc, (OSError, TimeoutError, ConnectionError)):
        return True
    name = type(exc).__name__
    if any(name == t or name.endswith(t)
           for t in _TRANSIENT_TYPE_NAMES):
        msg = str(exc)
        # XlaRuntimeError also wraps genuine programming errors
        # (INVALID_ARGUMENT shape mismatches) — only the
        # infrastructure status words are retryable
        return any(s in msg for s in _TRANSIENT_STATUS)
    return False


def backoff_delay(attempt: int, base: float, cap: float,
                  seed: int, key: str) -> float:
    """Exponential backoff with deterministic jitter in
    [0.5x, 1.0x] of the exponential envelope."""
    envelope = min(cap, base * (2.0 ** attempt))
    digest = hashlib.sha256(
        f"{seed}:{key}:{attempt}".encode()).digest()
    frac = int.from_bytes(digest[:8], "big") / float(1 << 64)
    return envelope * (0.5 + 0.5 * frac)


def donates(fn) -> bool:
    """Does ``fn`` declare that it donates its input buffers?
    Convention: donating jitted chunk programs (and wrappers around
    them, e.g. ``obs.jaxobs.track``, which delegates attributes) set
    ``donates_buffers = True``."""
    return bool(getattr(fn, "donates_buffers", False))


def retry(max_attempts: int = 3, base_delay: float = 0.5,
          max_delay: float = 30.0, classify=is_transient,
          seed: int = 0, sleep=time.sleep, logger=None):
    """Decorator: re-invoke on transient failures, with
    deterministic-jitter exponential backoff between attempts.

    ``classify(exc) -> bool`` decides retry vs raise; non-transient
    exceptions and the final attempt's exception propagate unchanged.
    ``logger`` (optional callable, e.g. ``MetricsLogger.log``) gets
    ``("retry", attempt=..., of=..., error=..., delay_s=...)`` per
    retry so flake is visible in metrics.jsonl.

    Refuses (``ValueError``, at wrap time) a callable that declares
    ``donates_buffers = True``: after ANY dispatch — including a
    failed one — the donated inputs may be invalid, so re-invoking
    with the same arguments would compute on garbage. Wrap the
    enclosing iteration (which rebuilds its donated carries from
    never-donated state) instead.
    """
    if max_attempts < 1:
        raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")

    def decorate(fn):
        if donates(fn):
            raise ValueError(
                f"retry would re-dispatch {getattr(fn, '__name__', fn)!r}"
                " whose inputs are DONATED (donates_buffers=True) — a "
                "failed attempt may already have invalidated them. "
                "Retry the enclosing iteration instead (see "
                "runtime/retries.py module docstring).")
        key = getattr(fn, "__qualname__", None) or repr(fn)

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            for attempt in range(max_attempts):
                try:
                    return fn(*args, **kwargs)
                except BaseException as e:  # noqa: BLE001 — classified below
                    if attempt + 1 >= max_attempts or not classify(e):
                        raise
                    delay = backoff_delay(attempt, base_delay,
                                          max_delay, seed, key)
                    if logger is not None:
                        logger("retry", of=key, attempt=attempt + 1,
                               max_attempts=max_attempts,
                               error=f"{type(e).__name__}: {e}",
                               delay_s=round(delay, 3))
                    else:
                        print(f"retries: {key} attempt "
                              f"{attempt + 1}/{max_attempts} failed "
                              f"({type(e).__name__}: {e}); retrying "
                              f"in {delay:.2f}s", file=sys.stderr)
                    sleep(delay)
            raise AssertionError("unreachable")  # pragma: no cover

        return wrapper

    return decorate


def retry_call(fn, *args, _retry_kwargs: dict | None = None, **kwargs):
    """One-shot form: ``retry_call(f, x, y)`` ≡ ``retry()(f)(x, y)``."""
    return retry(**(_retry_kwargs or {}))(fn)(*args, **kwargs)
