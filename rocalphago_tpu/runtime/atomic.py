"""Atomic artifact writes: tmp file + fsync + ``os.replace``.

Every artifact a crashed run leaves behind must be either the old
complete version or the new complete version — never a torn prefix.
Bare ``open(path, "w")`` offers no such guarantee: a kill between
``write`` and ``close`` (or between ``close`` and the kernel flushing
the page cache) leaves a truncated file that poisons every later
resume. The fix is the standard three-step dance:

1. write the full payload to a sibling temp file in the SAME
   directory (``os.replace`` is only atomic within a filesystem);
2. ``fsync`` the file so the data is durable before the rename;
3. ``os.replace`` onto the destination — atomic on POSIX.

The directory entry itself is fsynced too (best-effort — not all
filesystems allow opening a directory) so the rename survives a
power loss, not just a process kill.
"""

from __future__ import annotations

import json
import os
import tempfile


def _fsync_dir(path: str) -> None:
    """Best-effort durability for the rename itself."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes,
                       makedirs: bool = True) -> None:
    """Write ``data`` to ``path`` so a crash at ANY point leaves
    either the previous complete file or the new complete file."""
    parent = os.path.dirname(path)
    if makedirs and parent:
        os.makedirs(parent, exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        dir=parent or ".", prefix=os.path.basename(path) + ".",
        suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # the temp file is the one artifact we may leak — never the
        # destination; remove it on any failure (including the
        # injected ones the chaos tests raise)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(parent)


def atomic_write_text(path: str, text: str,
                      makedirs: bool = True) -> None:
    atomic_write_bytes(path, text.encode("utf-8"), makedirs=makedirs)


def atomic_write_json(path: str, obj, indent: int | None = 2,
                      makedirs: bool = True) -> None:
    atomic_write_text(path, json.dumps(obj, indent=indent),
                      makedirs=makedirs)
