"""Supervised worker fleet: restarts, crash-loop parking, drain.

The actor/learner rig is a FLEET — self-play actor threads, a learner
driving the device, the serving dispatcher — and on preemptible pods
individual members die routinely. This module is the supervision
layer that makes those deaths cost seconds instead of the run:

* **Restart policy** (:class:`RestartPolicy`): a dead worker is
  classified with the same transient/fatal line :mod:`.retries`
  draws, restarted after a deterministic-jitter backoff
  (:func:`.retries.backoff_delay` — an interrupted-and-resumed run
  replays the same schedule), and PARKED — permanently, with a
  ``worker_parked`` alarm — once it dies ``max_deaths`` times within
  ``window_s`` (a crash loop: restarting faster only burns the run's
  wall clock in front of the real traceback).
* **Heartbeat liveness**: workers report progress through their
  handle's ``beat``; the monitor tags stale-but-alive workers in the
  process watchdog's ``waiting_on`` registry (``actor:3``-style), so
  a :class:`.watchdog.Watchdog` stall event names WHICH fleet member
  wedged, not just where in code. The first beat after a restart
  closes the MTTR clock (``worker_recovered`` event, kill-detection
  to first post-restart progress).
* **Graceful drain**: :meth:`Supervisor.install_sigterm` routes the
  preemption notice (SIGTERM is how TPU preemption arrives) to
  :meth:`Supervisor.request_drain` — restarts stop, a ``drain``
  event is logged, and the training loop observes
  :attr:`Supervisor.draining` to exit at the next iteration boundary
  with a committed checkpoint (the byte-identical resume proof in
  ``tests/test_fleet_chaos.py``).

Two shapes are provided: :class:`Supervisor` manages REPLACEABLE
workers built fresh per incarnation by a factory (the self-play
actors — a new :class:`~rocalphago_tpu.training.actor.SelfplayActor`
with a fresh rng branch per restart; lockstep actors are registered
``restartable=False`` and park on first death so the lockstep
bit-identity pin survives), while :class:`SupervisedThread` wraps a
single long-lived loop body and re-enters it after an unexpected
exception (the serving dispatcher, whose state lives on the
evaluator object, not the thread).

Lifecycle events (``worker_restart`` / ``worker_parked`` /
``worker_recovered`` / ``drain``) go to the run's ``metrics.jsonl``
via the supplied logger; counts also land in the process registry
(``supervisor_restarts_total{worker=,reason=}``,
``supervisor_parked_total{worker=}``, ``supervisor_mttr_seconds``)
for the ``obs_report.py`` fleet-health section. See
docs/RESILIENCE.md "Fleet supervision".
"""

from __future__ import annotations

import os
import signal
import threading
import time

from rocalphago_tpu.analysis import lockcheck
from rocalphago_tpu.obs import registry
from rocalphago_tpu.runtime import retries
from rocalphago_tpu.runtime import watchdog as watchdog_mod

MAX_DEATHS_ENV = "ROCALPHAGO_SUPERVISOR_MAX_DEATHS"
WINDOW_ENV = "ROCALPHAGO_SUPERVISOR_WINDOW_S"
BACKOFF_ENV = "ROCALPHAGO_SUPERVISOR_BACKOFF_S"
POLL_ENV = "ROCALPHAGO_SUPERVISOR_POLL_S"
HEARTBEAT_ENV = "ROCALPHAGO_SUPERVISOR_HEARTBEAT_S"


def default_max_deaths() -> int:
    """Crash-loop threshold: park a worker after this many deaths
    within the window (env ``ROCALPHAGO_SUPERVISOR_MAX_DEATHS``,
    default 3)."""
    return int(os.environ.get(MAX_DEATHS_ENV, "3"))


def default_window_s() -> float:
    """Crash-loop window in seconds — deaths older than this age out
    of the loop detector (env ``ROCALPHAGO_SUPERVISOR_WINDOW_S``,
    default 60)."""
    return float(os.environ.get(WINDOW_ENV, "60"))


def default_backoff_s() -> float:
    """Base restart backoff in seconds; actual delays follow
    ``retries.backoff_delay``'s deterministic-jitter exponential
    envelope (env ``ROCALPHAGO_SUPERVISOR_BACKOFF_S``,
    default 0.25)."""
    return float(os.environ.get(BACKOFF_ENV, "0.25"))


def default_poll_s() -> float:
    """Monitor poll interval in seconds (env
    ``ROCALPHAGO_SUPERVISOR_POLL_S``, default 0.2)."""
    return float(os.environ.get(POLL_ENV, "0.2"))


def default_heartbeat_s() -> float:
    """Stale-worker threshold in seconds: an alive worker whose last
    beat is older than this gets named in the watchdog's
    ``waiting_on`` registry (env ``ROCALPHAGO_SUPERVISOR_HEARTBEAT_S``,
    default 30)."""
    return float(os.environ.get(HEARTBEAT_ENV, "30"))


class RestartPolicy:
    """When and how fast to resurrect a dead worker.

    ``classify`` reuses :func:`.retries.is_transient` verbatim — the
    reason label on lifecycle events is ``transient`` (infrastructure
    flake, incl. the chaos harness's :class:`~.faults.InjectedFault`
    and :class:`~.faults.InjectedKill`) or ``error`` (everything
    else). Both are restarted — a supervised worker is pure by
    construction (its state is rebuilt by the factory), so the
    donated-buffer hazard that limits in-place retries does not
    apply — but a crash LOOP of either flavour parks.
    """

    def __init__(self, max_deaths: int | None = None,
                 window_s: float | None = None,
                 base_delay: float | None = None,
                 max_delay: float = 30.0, seed: int = 0):
        self.max_deaths = (default_max_deaths()
                           if max_deaths is None else max_deaths)
        self.window_s = (default_window_s()
                         if window_s is None else window_s)
        self.base_delay = (default_backoff_s()
                           if base_delay is None else base_delay)
        self.max_delay = max_delay
        self.seed = seed

    def classify(self, error: BaseException) -> str:
        return "transient" if retries.is_transient(error) else "error"

    def crash_looping(self, deaths: list[float], now: float) -> bool:
        recent = [t for t in deaths if now - t <= self.window_s]
        return len(recent) >= self.max_deaths

    def delay(self, attempt: int, key: str) -> float:
        return retries.backoff_delay(attempt, self.base_delay,
                                     self.max_delay, self.seed, key)


class Handle:
    """One supervised slot: the current worker incarnation plus its
    restart history. Created via :meth:`Supervisor.add`; all fields
    except the beat pair are written only by the monitor thread
    (single-writer — see docs/CONCURRENCY.md's benign list)."""

    def __init__(self, factory, name: str, restartable: bool, sup):
        self.factory = factory
        self.name = name
        self.restartable = restartable
        self.worker = None          # current incarnation
        self.restarts = 0
        self.parked = False
        self.finished = False       # clean exit (games bound, stop)
        self.error: BaseException | None = None
        self.last_mttr_s: float | None = None
        self._sup = sup
        self._deaths: list[float] = []
        # lock-free heartbeat pair: _last_beat has one writer (the
        # worker, via beat) and one reader (the monitor); _recover_t0
        # is set by the monitor only while the worker is dead and
        # cleared by the first post-restart beat — phase-separated
        self._last_beat = time.monotonic()
        self._recover_t0: float | None = None

    def beat(self) -> None:
        """Report liveness/progress; workers call this once per unit
        of work (a finished game). The first beat after a restart
        stamps the MTTR."""
        self._last_beat = time.monotonic()
        t0 = self._recover_t0
        if t0 is not None:
            self._recover_t0 = None
            mttr = time.monotonic() - t0
            self.last_mttr_s = mttr
            registry.histogram("supervisor_mttr_seconds").observe(mttr)
            self._sup._emit("worker_recovered", worker=self.name,
                            restarts=self.restarts,
                            mttr_s=round(mttr, 3))

    def alive(self) -> bool:
        w = self.worker
        return w is not None and w.alive()


class Supervisor:
    """Monitor thread resurrecting factory-built workers on death.

    Worker protocol (duck-typed; :class:`~..training.actor.
    SelfplayActor` satisfies it): ``start()``, ``stop(timeout)``,
    ``alive() -> bool``, and an ``error`` attribute that is None
    after a clean exit. ``factory(attempt, beat)`` builds incarnation
    ``attempt`` (0 = first start); ``beat`` is the handle's heartbeat
    callable for the worker's progress callback.

    A worker whose thread exits with ``error`` set has DIED; the
    monitor classifies, backs off, and restarts it — unless the
    handle is ``restartable=False`` (lockstep actors: a restarted
    lockstep actor would replay games the learner already consumed,
    so the bit-identity contract forbids resurrection and the handle
    parks immediately with reason ``restart_refused``) or the death
    history trips the crash-loop detector.
    """

    def __init__(self, *, metrics=None, policy: RestartPolicy | None = None,
                 poll_s: float | None = None,
                 heartbeat_s: float | None = None):
        self._metrics = metrics
        self.policy = policy or RestartPolicy()
        self._poll_s = default_poll_s() if poll_s is None else poll_s
        self._heartbeat_s = (default_heartbeat_s()
                             if heartbeat_s is None else heartbeat_s)
        self._lock = lockcheck.make_lock("Supervisor._lock")
        self._handles: list[Handle] = []   # guarded-by: self._lock
        self._draining = False             # guarded-by: self._lock
        self.drain_reason: str | None = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._monitor, name="supervisor", daemon=True)
        self._stale_tag: str | None = None      # monitor-thread-only
        self._stale_cm = None                   # monitor-thread-only
        self._old_sigterm = None

    # ------------------------------------------------------ lifecycle

    def add(self, factory, *, name: str,
            restartable: bool = True) -> Handle:
        """Register a worker slot; the worker itself is built and
        started by :meth:`start` (or by a later restart)."""
        h = Handle(factory, name, restartable, self)
        with self._lock:
            self._handles.append(h)
        return h

    def start(self) -> "Supervisor":
        with self._lock:
            handles = list(self._handles)
        # factory + start are caller code: run outside the lock
        for h in handles:
            if h.worker is None:
                h.worker = h.factory(0, h.beat)
                h.worker.start()
                h._last_beat = time.monotonic()
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        """Stop restarting, join the monitor, stop every worker."""
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)
        with self._lock:
            handles = list(self._handles)
        for h in handles:
            if h.worker is not None:
                h.worker.stop(timeout=timeout)
        self.restore_sigterm()

    def handles(self) -> list[Handle]:
        with self._lock:
            return list(self._handles)

    def parked(self) -> list[Handle]:
        return [h for h in self.handles() if h.parked]

    # ---------------------------------------------------------- drain

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def request_drain(self, reason: str = "signal") -> None:
        """Graceful-drain request: restarts stop; the training loop
        polls :attr:`draining` and exits at its next iteration
        boundary with a committed checkpoint. Idempotent."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
            self.drain_reason = reason
        self._emit("drain", phase="requested", reason=reason)

    def install_sigterm(self) -> bool:
        """Route SIGTERM (the preemption notice) to
        :meth:`request_drain`. Signal handlers can only be installed
        from the main thread — returns False (no-op) elsewhere, so
        in-process test harnesses that run training off-main keep
        working."""
        if threading.current_thread() is not threading.main_thread():
            return False
        self._old_sigterm = signal.signal(
            signal.SIGTERM,
            lambda signum, frame: self.request_drain(reason="sigterm"))
        return True

    def restore_sigterm(self) -> None:
        if (self._old_sigterm is not None
                and threading.current_thread()
                is threading.main_thread()):
            signal.signal(signal.SIGTERM, self._old_sigterm)
            self._old_sigterm = None

    # -------------------------------------------------------- monitor

    def _emit(self, event: str, **fields) -> None:
        if self._metrics is not None:
            self._metrics.log(event, **fields)

    def _park(self, h: Handle, reason: str) -> None:
        h.parked = True
        registry.counter("supervisor_parked_total",
                         worker=h.name).inc()
        self._emit("worker_parked", worker=h.name, reason=reason,
                   deaths=len(h._deaths),
                   error=(f"{type(h.error).__name__}: {h.error}"
                          if h.error is not None else None))

    def _restart(self, h: Handle, now: float) -> None:
        err = h.error
        reason = self.policy.classify(err)
        if not h.restartable:
            self._park(h, reason="restart_refused")
            return
        if self.policy.crash_looping(h._deaths, now):
            self._park(h, reason="crash_loop")
            return
        h.restarts += 1
        delay = self.policy.delay(h.restarts, key=h.name)
        registry.counter("supervisor_restarts_total",
                         worker=h.name, reason=reason).inc()
        self._emit("worker_restart", worker=h.name, reason=reason,
                   restarts=h.restarts, delay_s=round(delay, 3),
                   error=f"{type(err).__name__}: {err}")
        # MTTR clock starts at death DETECTION (includes the backoff)
        h._recover_t0 = now
        if self._stop.wait(delay):
            return
        w = h.factory(h.restarts, h.beat)
        w.start()
        h.worker = w
        h._last_beat = time.monotonic()

    def _retag_stale(self, handles: list[Handle], now: float) -> None:
        stale = sorted(
            h.name for h in handles
            if not h.parked and not h.finished and h.alive()
            and now - h._last_beat > self._heartbeat_s)
        tag = ",".join(stale) if stale else None
        if tag == self._stale_tag:
            return
        if self._stale_cm is not None:
            self._stale_cm.__exit__(None, None, None)
            self._stale_cm = None
        if tag is not None:
            self._stale_cm = watchdog_mod.waiting_on(tag)
            self._stale_cm.__enter__()
        self._stale_tag = tag

    def _monitor(self) -> None:
        try:
            while not self._stop.wait(self._poll_s):
                with self._lock:
                    handles = list(self._handles)
                    draining = self._draining
                now = time.monotonic()
                for h in handles:
                    if h.parked or h.finished or h.worker is None:
                        continue
                    if h.alive():
                        continue
                    err = getattr(h.worker, "error", None)
                    if err is None or draining:
                        # games bound reached / stop requested / the
                        # fleet is draining: a death is final either
                        # way, but only a clean one counts as done
                        h.finished = err is None
                        continue
                    h.error = err
                    h._deaths.append(now)
                    self._restart(h, now)
                self._retag_stale(handles, now)
        finally:
            if self._stale_cm is not None:
                self._stale_cm.__exit__(None, None, None)
                self._stale_cm = None
                self._stale_tag = None


class SupervisedThread:
    """Daemon thread that re-enters its target after an unexpected
    exception — the resurrect-on-death wrapper for loop bodies whose
    state lives OUTSIDE the thread (the serving dispatcher: queue,
    counters and stop flag are all on the evaluator object, so the
    loop can simply be entered again).

    A normal return of ``target`` ends the thread (that is the stop
    path). An exception is classified and counted; the thread backs
    off (same deterministic schedule as :class:`Supervisor`) and
    re-enters the target, until the crash-loop detector parks it —
    then ``on_park`` (optional) runs so the owner can fail pending
    work instead of hanging its clients, and the thread exits with
    ``error`` set and ``parked`` True.
    """

    def __init__(self, target, name: str, *,
                 policy: RestartPolicy | None = None, metrics=None,
                 on_park=None):
        self._target = target
        self.name = name
        self.policy = policy or RestartPolicy()
        self._metrics = metrics
        self._on_park = on_park
        self.restarts = 0
        self.parked = False
        self.error: BaseException | None = None
        self._deaths: list[float] = []
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True)

    def start(self) -> "SupervisedThread":
        self._thread.start()
        return self

    def join(self, timeout: float | None = None) -> None:
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)

    def is_alive(self) -> bool:
        return self._thread.is_alive()

    def _emit(self, event: str, **fields) -> None:
        if self._metrics is not None:
            self._metrics.log(event, **fields)

    def _run(self) -> None:
        while True:
            try:
                self._target()
                return                       # clean stop
            except Exception as e:  # noqa: BLE001 — classified below
                now = time.monotonic()
                self._deaths.append(now)
                self.error = e
                reason = self.policy.classify(e)
                if self.policy.crash_looping(self._deaths, now):
                    self.parked = True
                    registry.counter("supervisor_parked_total",
                                     worker=self.name).inc()
                    self._emit("worker_parked", worker=self.name,
                               reason="crash_loop",
                               deaths=len(self._deaths),
                               error=f"{type(e).__name__}: {e}")
                    if self._on_park is not None:
                        self._on_park()
                    return
                self.restarts += 1
                delay = self.policy.delay(self.restarts, key=self.name)
                registry.counter("supervisor_restarts_total",
                                 worker=self.name, reason=reason).inc()
                self._emit("worker_restart", worker=self.name,
                           reason=reason, restarts=self.restarts,
                           delay_s=round(delay, 3),
                           error=f"{type(e).__name__}: {e}")
                time.sleep(delay)
