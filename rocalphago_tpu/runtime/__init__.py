"""Crash-safe runtime layer shared by every trainer and CLI.

The seed stack assumed a perfect machine; round-5 operations showed
the opposite (TPU tunnel availability of 5/243 probes, multi-hour
``nohup`` runs dying mid-write). This package makes the harness
survive the hardware (docs/RESILIENCE.md):

* :mod:`.atomic` — torn-write-proof artifact persistence
  (tmp + fsync + ``os.replace``);
* :mod:`.retries` — deterministic-jitter exponential backoff around
  device dispatch and checkpoint I/O, with a transient-vs-programming
  error classifier;
* :mod:`.faults` — opt-in deterministic fault injection at named
  barriers (``ROCALPHAGO_FAULT_PLAN=crash@iter3.post_save``), the
  mechanism the chaos tests use to prove exact resume;
* :mod:`.watchdog` — a heartbeat thread that logs ``stall`` events
  and can abort a hung run with a clean checkpoint;
* :mod:`.deadline` — hard wall-clock cutoffs for the serving path
  (the play-side enforcer behind the GTP engine's anytime genmove);
* :mod:`.pipeline` — pipelined chunk dispatch (keep a compiled chunk
  in flight while the host decides), the scheduling layer every
  chunked hot loop drives its per-chunk host decisions through;
* :mod:`.compilecache` — one shared persistent-XLA-compile-cache
  setup (``ROCALPHAGO_COMPILE_CACHE``) called by every CLI entry
  point, so repeat runs stop paying the 20–40s TPU compiles.
"""

from rocalphago_tpu.runtime.compilecache import (  # noqa: F401
    enable_compile_cache,
)
from rocalphago_tpu.runtime.atomic import (  # noqa: F401
    atomic_write_bytes,
    atomic_write_json,
    atomic_write_text,
)
from rocalphago_tpu.runtime.deadline import Deadline  # noqa: F401
from rocalphago_tpu.runtime.faults import (  # noqa: F401
    FAULT_EXIT_CODE,
    FAULT_PLAN_ENV,
    InjectedFault,
    barrier,
)
from rocalphago_tpu.runtime.jsonl import (  # noqa: F401
    iter_jsonl,
    read_jsonl,
)
from rocalphago_tpu.runtime.pipeline import (  # noqa: F401
    ChunkPipeline,
    default_depth,
)
from rocalphago_tpu.runtime.retries import (  # noqa: F401
    donates,
    is_transient,
    retry,
    retry_call,
)
from rocalphago_tpu.runtime.watchdog import Watchdog  # noqa: F401
