"""The federated gateway router: N replicas behind one front door.

A thin NDJSON tier on the shared wire core
(:class:`~rocalphago_tpu.net.server.LineServerCore`) federating N
:class:`~rocalphago_tpu.gateway.server.GatewayServer` replicas
(docs/ROLLOUT.md):

* **Sticky sessions** — one accepted connection maps to one backend
  connection (= one replica session slot) for its whole life; frames
  pass through with the router re-correlating ids.
* **Spillover** — a replica refusing ``new_game`` with ``overload``
  is not the client's problem: the router retries the game on the
  next least-loaded healthy replica and only refuses when the whole
  fleet is saturated (the refusal then carries ``retry_after_s``).
* **Drain-aware failover** — a replica saying ``draining`` (or
  dropping the connection mid-game) triggers a reconnect through the
  shared :func:`~rocalphago_tpu.net.client.call_with_backoff` loop
  (honoring ``retry_after_s``), a replay of the game log onto the
  new replica, and a re-send of the in-flight request — at most ONE
  retried genmove per failover, and the client never notices.
* **Health + convergence** — a poll thread reads each replica's
  ``/healthz`` (or its in-process handles), tracking ``draining``,
  reachability, and the serve pool's params version;
  :meth:`RolloutRouter.await_convergence` is the fleet-wide
  promotion barrier ("every replica serves rollout version ≥ v").

Knobs: ``ROCALPHAGO_ROUTER_MAX_CONNS`` (64),
``ROCALPHAGO_ROUTER_DRAIN_S`` (10), ``ROCALPHAGO_ROUTER_HEALTH_S``
(health poll cadence, 1.0 s).
"""

from __future__ import annotations

import json
import os
import threading
import time

from rocalphago_tpu.analysis import lockcheck
from rocalphago_tpu.gateway import protocol
from rocalphago_tpu.gateway.client import (
    GameLog,
    GatewayClient,
    GatewayClosed,
    GatewayError,
    GatewayRefused,
)
from rocalphago_tpu.net import client as net_client
from rocalphago_tpu.net.server import LineServerCore
from rocalphago_tpu.obs import registry as obs_registry

#: cap on concurrently routed connections (env override)
MAX_CONNS_ENV = "ROCALPHAGO_ROUTER_MAX_CONNS"
#: drain grace for in-flight routed conversations (env override)
DRAIN_ENV = "ROCALPHAGO_ROUTER_DRAIN_S"
#: replica health poll cadence in seconds (env override)
HEALTH_ENV = "ROCALPHAGO_ROUTER_HEALTH_S"

#: retry hint a fleet-saturated client receives (seconds)
RETRY_AFTER_S = 1.0


def _env_float(name: str, default):
    raw = os.environ.get(name, "")
    return float(raw) if raw else default


class NoReplicaAvailable(Exception):
    """Every eligible replica refused or is unreachable; carries
    ``retry_after_s`` so the shared backoff loop classifies it as
    transient and honors the fleet's pacing."""

    def __init__(self, msg: str):
        super().__init__(msg)
        self.retry_after_s = RETRY_AFTER_S


class Replica:
    """One federated gateway: its wire address, its optional health
    surface (``http_port`` → ``/healthz``, or ``gateway`` for an
    in-process :class:`~rocalphago_tpu.gateway.server.GatewayServer`
    handle), and the router-side routing state."""

    def __init__(self, host: str, port: int,
                 http_port: int | None = None, gateway=None,
                 name: str | None = None):
        self.host = host
        self.port = int(port)
        self.http_port = http_port
        self.gateway = gateway
        self.name = name or f"{host}:{port}"
        # routing state — guarded-by the owning router's lock
        self.healthy = True
        self.draining = False
        self.sessions = 0          # live routed connections
        self.routed = 0            # connections ever routed here
        self.params_version: int | None = None
        self.rollout_version: int | None = None

    def probe(self) -> dict | None:
        """One health read: the ``/healthz`` JSON (in-process when a
        ``gateway`` handle was given), or None when unreachable."""
        if self.gateway is not None:
            g = self.gateway
            return {"status": ("draining" if g.draining else "ok"),
                    "serve": g.pool.stats(), "gateway": g.stats()}
        if self.http_port is None:
            return None
        import urllib.error
        import urllib.request

        url = f"http://{self.host}:{self.http_port}/healthz"
        try:
            with urllib.request.urlopen(url, timeout=2.0) as r:
                return json.loads(r.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            # 503 while draining still carries the body
            try:
                return json.loads(e.read().decode("utf-8"))
            except (OSError, ValueError):
                return None
        except (OSError, ValueError):
            return None


class RolloutRouter:
    """The federation front door (module docstring).

    ``replicas`` is a list of :class:`Replica`; health starts
    optimistic (everyone eligible) and converges from the first poll.
    """

    def __init__(self, replicas, host: str = "127.0.0.1",
                 port: int = 0, max_conns: int | None = None,
                 drain_s: float | None = None,
                 health_s: float | None = None, metrics=None):
        if not replicas:
            raise ValueError("a router needs at least one replica")
        self.replicas = list(replicas)
        self.host = host
        self.metrics = metrics
        self.max_conns = int(_env_float(MAX_CONNS_ENV, 64)
                             if max_conns is None else max_conns)
        self.drain_s = float(_env_float(DRAIN_ENV, 10.0)
                             if drain_s is None else drain_s)
        self.health_s = float(_env_float(HEALTH_ENV, 1.0)
                              if health_s is None else health_s)
        self._max_frame = protocol.max_frame_bytes()
        self._lock = lockcheck.make_lock("RolloutRouter._lock")
        self._spillovers = 0         # guarded-by: self._lock
        self._failovers = 0          # guarded-by: self._lock
        self._retried_genmoves = 0   # guarded-by: self._lock
        self._routed = 0             # guarded-by: self._lock
        self._closed = False
        self._health_stop = threading.Event()
        self._live_g = obs_registry.gauge("router_conns_live")
        self._acc_c = obs_registry.counter("router_connections_total",
                                           result="accepted")
        self._shed_c = obs_registry.counter("router_connections_total",
                                            result="shed")
        self._spill_c = obs_registry.counter("router_spillovers_total")
        self._fail_c = obs_registry.counter("router_failovers_total")
        self._retry_c = obs_registry.counter(
            "router_retried_genmoves_total")
        self._core = LineServerCore(
            host=host, port=port, max_conns=self.max_conns,
            drain_s=self.drain_s, handler=self._handle,
            refusal=self._refusal_frame, name="router",
            metrics=metrics, live_gauge=self._live_g,
            accepted_counter=self._acc_c, shed_counter=self._shed_c)
        self._health_thread = threading.Thread(
            target=self._health_loop, name="router-health",
            daemon=True)

    # ------------------------------------------------------ lifecycle

    def start(self) -> "RolloutRouter":
        self._core.start()
        self._health_thread.start()
        return self

    @property
    def port(self) -> int:
        return self._core.port

    @property
    def draining(self) -> bool:
        return self._core.draining

    def drain(self, reason: str = "requested",
              timeout: float | None = None) -> None:
        self._health_stop.set()
        self._core.drain(reason=reason, timeout=timeout)
        if self._health_thread.is_alive():
            self._health_thread.join(timeout=5.0)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.drain(reason="close")

    def __enter__(self) -> "RolloutRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -------------------------------------------------------- health

    def poll_health_once(self) -> None:
        """One probe sweep over the fleet (the health thread's body;
        callable inline from tests)."""
        for rep in self.replicas:
            info = rep.probe()
            with self._lock:
                if info is None:
                    # unreachable only counts against replicas that
                    # HAVE a health surface; a bare address stays
                    # eligible until the wire refuses it
                    rep.healthy = (rep.gateway is None
                                   and rep.http_port is None)
                    rep.draining = False
                    continue
                rep.healthy = True
                rep.draining = (info.get("status") == "draining"
                                or bool(info.get("gateway", {})
                                        .get("draining")))
                serve = info.get("serve", {})
                params = serve.get("params")
                if params is not None:
                    rep.params_version = params.get("version")
                elif "params_version" in serve:   # multisize block
                    rep.params_version = serve.get("params_version")

    def _health_loop(self) -> None:
        while not self._health_stop.is_set():
            self.poll_health_once()
            self._health_stop.wait(self.health_s)

    def await_convergence(self, version: int,
                          timeout: float = 30.0) -> bool:
        """Block until every non-draining replica's serve pool
        reports params version ≥ ``version`` (the fleet-wide
        promotion barrier). False on timeout."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.poll_health_once()
            with self._lock:
                reps = [r for r in self.replicas if not r.draining]
                done = reps and all(
                    r.params_version is not None
                    and r.params_version >= version for r in reps)
            if done:
                return True
            time.sleep(min(0.05, self.health_s))
        return False

    # ------------------------------------------------------- routing

    def _eligible(self, exclude=()) -> list:
        with self._lock:
            reps = [r for r in self.replicas
                    if r.healthy and not r.draining
                    and r.name not in exclude]
            return sorted(reps, key=lambda r: (r.sessions, r.name))

    def _connect_backend(self, exclude=()):
        """Least-loaded-first connect sweep; raises
        :class:`NoReplicaAvailable` (transient, with a retry hint)
        when the whole eligible fleet refuses or is unreachable."""
        for rep in self._eligible(exclude):
            try:
                backend = GatewayClient(rep.host, rep.port,
                                        timeout=30.0)
            except GatewayRefused as e:
                with self._lock:
                    rep.draining = (e.code == "draining") \
                        or rep.draining
                continue
            except (GatewayClosed, OSError):
                with self._lock:
                    rep.healthy = False
                continue
            with self._lock:
                rep.sessions += 1
                rep.routed += 1
                self._routed += 1
            obs_registry.counter("router_routed_total",
                                 replica=rep.name).inc()
            return backend, rep
        raise NoReplicaAvailable(
            f"no replica available (fleet of {len(self.replicas)})")

    def _release(self, rep) -> None:
        with self._lock:
            rep.sessions = max(0, rep.sessions - 1)

    def _refusal_frame(self, code: str) -> dict:
        obs_registry.counter("router_errors_total", code=code).inc()
        return protocol.error_frame(
            code, f"router {code}: {self.max_conns} connections live",
            retry_after_s=RETRY_AFTER_S)

    def _send(self, conn, msg: dict) -> bool:
        return self._core.send(conn, msg)

    def _emit(self, phase: str, **fields) -> None:
        if self.metrics is not None:
            self.metrics.log("router", phase=phase, **fields)

    # ------------------------------------------------------- handler

    def _handle(self, conn, reader, cid: int) -> None:
        try:
            backend, rep = self._connect_backend()
        except NoReplicaAvailable as e:
            self._send(conn, protocol.error_frame(
                "overload", str(e), retry_after_s=RETRY_AFTER_S))
            return
        log = GameLog()
        try:
            hello = dict(backend.hello)
            hello["name"] = "rocalphago-router"
            if not self._send(conn, hello):
                return
            while True:
                if self._core.draining:
                    self._send(conn, {"type": "goodbye",
                                      "reason": "draining"})
                    break
                try:
                    msg = protocol.read_frame(reader, self._max_frame)
                except protocol.ProtocolError as e:
                    self._send(conn, protocol.error_frame(
                        e.code, str(e)))
                    if e.fatal:
                        break
                    continue
                if msg is None:
                    break
                rid = msg.get("id")
                try:
                    reply, backend, rep = self._route(msg, backend,
                                                      rep, log)
                except Exception as e:  # noqa: BLE001 — the routed
                    # conversation is unrecoverable (no replica can
                    # continue it): a typed refusal, never a hang,
                    # and the failover path already tore the dead
                    # backend down
                    backend, rep = None, None
                    retry = getattr(e, "retry_after_s",
                                    RETRY_AFTER_S)
                    self._send(conn, protocol.error_frame(
                        "overload",
                        f"no replica can continue this game: {e}",
                        id=rid, retry_after_s=retry))
                    break
                reply = dict(reply)
                if rid is None:
                    reply.pop("id", None)
                else:
                    reply["id"] = rid
                if not self._send(conn, reply):
                    break
        finally:
            if backend is not None:
                backend.close()
            if rep is not None:
                self._release(rep)

    def _route(self, msg: dict, backend, rep, log: GameLog):
        """Forward one frame, absorbing replica failures: returns
        ``(reply, backend, rep)`` with the backend possibly moved to
        another replica (spillover/failover)."""
        mtype = msg.get("type")
        forward = dict(msg)
        forward.pop("id", None)
        try:
            try:
                reply = backend.request(forward)
            except GatewayRefused as e:
                if mtype == "new_game":
                    backend, rep = self._spillover(backend, rep, e)
                    reply = backend.request(forward)
                else:
                    raise GatewayClosed(
                        f"replica refused mid-game ({e.code})")
        except (GatewayClosed, OSError):
            backend, rep, reply = self._failover(forward, backend,
                                                 rep, log, mtype)
        except GatewayError as e:
            # a typed refusal passes through as the frame it was
            return self._error_reply(e), backend, rep
        self._track(mtype, msg, reply, log)
        return reply, backend, rep

    def _error_reply(self, e: GatewayError) -> dict:
        msg = str(e)
        if msg.startswith(f"{e.code}: "):
            msg = msg[len(e.code) + 2:]
        return protocol.error_frame(e.code, msg,
                                    retry_after_s=e.retry_after_s)

    def _track(self, mtype, msg, reply, log: GameLog) -> None:
        """Keep the per-connection game log replayable (the failover
        replay source)."""
        if reply.get("type") == "error":
            return
        if mtype == "new_game":
            log.start(reply.get("board"), reply.get("komi"))
        elif mtype == "play":
            log.play(str(msg.get("color", "")), str(msg.get("move",
                                                            "")))
        elif mtype == "genmove" and reply.get("type") == "move":
            log.play(str(msg.get("color", "")), reply.get("move"))
        elif mtype == "komi":
            log.set_komi(msg.get("komi"))
        elif mtype == "close":
            log.clear()

    def _spillover(self, backend, rep, refusal):
        """``new_game`` overload on one replica → the next one."""
        try:
            nb, nr = self._connect_backend(exclude=(rep.name,))
        except NoReplicaAvailable:
            # the WHOLE fleet is saturated: surface the original
            # structured refusal (retry_after_s intact); the current
            # backend stays up — the conversation continues on it
            raise refusal
        backend.close()
        self._release(rep)
        with self._lock:
            self._spillovers += 1
        self._spill_c.inc()
        self._emit("spillover", replica=rep.name, code=refusal.code)
        return nb, nr

    def _failover(self, forward, backend, rep, log: GameLog, mtype):
        """Mid-conversation replica loss: reconnect (shared backoff,
        honoring retry hints), replay the game, re-send the in-flight
        request — the ≤ 1 retried genmove the soak green-gates on."""
        backend.close()
        self._release(rep)
        with self._lock:
            self._failovers += 1
            rep.healthy = rep.gateway is not None and \
                not rep.gateway.draining
            if mtype == "genmove":
                self._retried_genmoves += 1
        self._fail_c.inc()
        if mtype == "genmove":
            self._retry_c.inc()
        self._emit("failover", replica=rep.name, request=str(mtype))

        # prefer a DIFFERENT replica, but a single-replica fleet may
        # only come back on the one that dropped (post-restart)
        excl = (rep.name,) if len(self.replicas) > 1 else ()

        def attempt():
            nb, nr = self._connect_backend(exclude=excl)
            try:
                if log.active:
                    log.replay(nb)
                return nb, nr, nb.request(forward)
            except BaseException:
                nb.close()
                self._release(nr)
                raise

        return net_client.call_with_backoff(
            attempt, attempts=4, key="router.failover")

    # --------------------------------------------------------- stats

    def stats(self) -> dict:
        """The probes' ``router`` block (schema: docs/ROLLOUT.md —
        the ``rollout-probe-drift`` lint rule diffs this literal
        against the documented schema both ways; ``replicas`` is the
        dynamic per-replica map, documented as ``{}``)."""
        wire = self._core.counters()
        with self._lock:
            replicas = {
                r.name: {"healthy": r.healthy,
                         "draining": r.draining,
                         "sessions": r.sessions,
                         "routed": r.routed,
                         "params_version": r.params_version}
                for r in self.replicas}
            spillovers = self._spillovers
            failovers = self._failovers
            retried = self._retried_genmoves
            routed = self._routed
        return {
            "proto": protocol.PROTO_VERSION,
            "draining": wire["draining"],
            "conns": {
                "live": wire["live"],
                "max": self.max_conns,
                "accepted": wire["accepted"],
                "shed": wire["shed"],
            },
            "routed": routed,
            "spillovers": spillovers,
            "failovers": failovers,
            "retried_genmoves": retried,
            "drain_s": self.drain_s,
            "health_s": self.health_s,
            "replicas": replicas,
        }


class RouterHTTP:
    """``/healthz`` + ``/metrics`` sidecar for the router (the same
    shape :class:`~rocalphago_tpu.gateway.httpapi.GatewayHTTP` gives
    a single gateway — the router's health JSON carries its
    ``router`` stats block instead of a pool's)."""

    def __init__(self, router: RolloutRouter, host: str = "127.0.0.1",
                 port: int = 0):
        from http.server import (
            BaseHTTPRequestHandler,
            ThreadingHTTPServer,
        )

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: N802 — quiet
                pass

            def _reply(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server contract
                if self.path == "/metrics":
                    self._reply(200,
                                obs_registry.render_text().encode(),
                                "text/plain; version=0.0.4")
                    return
                if self.path == "/healthz":
                    draining = router.draining
                    body = json.dumps({
                        "status": ("draining" if draining else "ok"),
                        "router": router.stats(),
                    }, sort_keys=True).encode()
                    self._reply(503 if draining else 200, body,
                                "application/json")
                    return
                self._reply(404, b"not found\n", "text/plain")

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1}, name="router-http")

    def start(self) -> "RouterHTTP":
        self._thread.start()
        return self

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def close(self) -> None:
        self._httpd.shutdown()
        self._thread.join(timeout=10.0)
        self._httpd.server_close()


def _parse_replica(spec: str) -> Replica:
    """``host:port[:http_port]`` → :class:`Replica`."""
    parts = spec.split(":")
    if len(parts) not in (2, 3):
        raise ValueError(
            f"replica spec {spec!r} is not host:port[:http_port]")
    http = int(parts[2]) if len(parts) == 3 else None
    return Replica(parts[0], int(parts[1]), http_port=http)


def main(argv=None) -> int:
    """Run a router over already-running gateway replicas until
    SIGTERM (drain, exit 0) or Ctrl-C."""
    import argparse

    ap = argparse.ArgumentParser(
        description="Federated gateway router (docs/ROLLOUT.md)")
    ap.add_argument("--replica", action="append", required=True,
                    help="host:port[:http_port] — repeat per replica")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9464)
    ap.add_argument("--http-port", type=int, default=9465,
                    help="/healthz + /metrics port (0 disables)")
    ap.add_argument("--max-conns", type=int, default=None)
    ap.add_argument("--metrics", default=None,
                    help="JSONL path for router/drain events")
    a = ap.parse_args(argv)

    from rocalphago_tpu.runtime.supervisor import Supervisor

    metrics = None
    if a.metrics:
        from rocalphago_tpu.io.metrics import MetricsLogger

        metrics = MetricsLogger(a.metrics, echo=False)
    router = RolloutRouter(
        [_parse_replica(s) for s in a.replica], host=a.host,
        port=a.port, max_conns=a.max_conns, metrics=metrics).start()
    http = None
    if a.http_port:
        http = RouterHTTP(router, host=a.host,
                          port=a.http_port).start()
    sup = Supervisor(metrics=metrics)
    sup.install_sigterm()
    print(f"router: serving on {a.host}:{router.port} over "
          f"{len(router.replicas)} replicas "
          f"(http {'off' if http is None else http.port})")
    try:
        while not sup.draining:
            time.sleep(0.2)
    except KeyboardInterrupt:
        sup.request_drain(reason="keyboard")
    router.drain(reason="sigterm")
    if http is not None:
        http.close()
    if metrics is not None:
        obs_registry.log_to(metrics)
        metrics.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
