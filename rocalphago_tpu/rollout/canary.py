"""Wilson-gated canary: a candidate net earns full rollout in play.

The gate between "a new net exists" and "every session serves it"
(docs/ROLLOUT.md). The controller stages the candidate on the pool
WITHOUT flipping the current pointer
(:meth:`~rocalphago_tpu.serve.sessions.ServePool.stage_params`),
assigns a configurable fraction of new gateway sessions to it
(:meth:`assign` → the session pins the staged version), accumulates
live-game outcomes per arm, and decides on the SAME statistical
machinery ``ZeroGate`` trusts: the Wilson 95% lower bound
(:func:`rocalphago_tpu.interface.elo.wilson_lower_bound`) on the
candidate's decided-game win rate. At the game budget:

* lb ≥ 0.5 — **promote**: the staged version becomes current on
  every compiled shape (a pointer flip; in-flight searches finish on
  their pinned version);
* lb < 0.5 — **rollback**: the staged version retires; sessions
  pinned to it fall back to the incumbent on their NEXT genmove
  (the evaluator's acquire-fallback), so a bad canary never strands
  a game. The incumbent's play is bit-unaffected throughout — its
  sessions never touched the candidate's params.

Decisions, arm assignments and rollbacks land as structured
``canary`` events on the metrics logger, and the per-arm record /
lb trajectory as obs metrics (`docs/OBSERVABILITY.md`).

Knobs: ``ROCALPHAGO_ROLLOUT_CANARY_FRACTION`` (default 0.1) and
``ROCALPHAGO_ROLLOUT_CANARY_GAMES`` (decision budget, default 32).
"""

from __future__ import annotations

import os

from rocalphago_tpu.analysis import lockcheck
from rocalphago_tpu.interface.elo import wilson_lower_bound
from rocalphago_tpu.obs import registry as obs_registry

#: fraction of new sessions routed to the candidate (env override)
FRACTION_ENV = "ROCALPHAGO_ROLLOUT_CANARY_FRACTION"
#: decided candidate games before the gate decides (env override)
GAMES_ENV = "ROCALPHAGO_ROLLOUT_CANARY_GAMES"


def default_fraction() -> float:
    raw = os.environ.get(FRACTION_ENV, "")
    return float(raw) if raw else 0.1


def default_min_games() -> int:
    raw = os.environ.get(GAMES_ENV, "")
    return int(raw) if raw else 32


class CanaryController:
    """One candidate rollout over one pool (module docstring).

    ``pool`` needs the rollout surface
    (``stage_params``/``promote_version``/``discard_version`` —
    :class:`~rocalphago_tpu.serve.sessions.ServePool` or
    :class:`~rocalphago_tpu.multisize.pool.MultiSizePool`). States:
    ``idle`` → :meth:`stage` → ``running`` → ``promoted`` |
    ``rolled_back``; a finished controller can :meth:`stage` again.
    """

    def __init__(self, pool, fraction: float | None = None,
                 min_games: int | None = None, metrics=None):
        self.pool = pool
        self.fraction = (default_fraction() if fraction is None
                         else float(fraction))
        if not 0.0 < self.fraction <= 1.0:
            raise ValueError(
                f"canary fraction must be in (0, 1], "
                f"got {self.fraction}")
        self.min_games = (default_min_games() if min_games is None
                          else int(min_games))
        self.metrics = metrics
        self._lock = lockcheck.make_lock("CanaryController._lock")
        # everything below guarded-by: self._lock
        self.state = "idle"
        self.candidate_version: int | None = None
        self.incumbent_version: int | None = None
        self._acc = 0.0               # fractional-assignment carry
        self._assigned = {"candidate": 0, "incumbent": 0}
        self._wins = {"candidate": 0, "incumbent": 0}
        self._losses = {"candidate": 0, "incumbent": 0}
        self.wilson_lb: float | None = None
        self.promotions = 0
        self.rollbacks = 0
        self._lb_g = obs_registry.gauge("rollout_canary_lb")
        self._rb_c = obs_registry.counter(
            "rollout_canary_rollbacks_total")
        self._pr_c = obs_registry.counter(
            "rollout_canary_promotions_total")

    def _emit(self, phase: str, **fields) -> None:
        if self.metrics is not None:
            self.metrics.log("canary", phase=phase, **fields)

    # ---------------------------------------------------------- flow

    def stage(self, params_p, params_v,
              version: int | None = None) -> int:
        """Stage the candidate pair on the pool and start routing a
        slice of new sessions to it. Returns the staged version."""
        with self._lock:
            if self.state == "running":
                raise RuntimeError(
                    f"a canary (version {self.candidate_version}) "
                    "is already running")
        # pool calls outside the controller lock (no lock nesting);
        # one controller drives one pool — no concurrent stage race
        incumbent = self.pool.params_version
        v = self.pool.stage_params(params_p, params_v,
                                   version=version)
        with self._lock:
            self.state = "running"
            self.candidate_version = v
            self.incumbent_version = incumbent
            self._acc = 0.0
            self._assigned = {"candidate": 0, "incumbent": 0}
            self._wins = {"candidate": 0, "incumbent": 0}
            self._losses = {"candidate": 0, "incumbent": 0}
            self.wilson_lb = None
        self._emit("stage", candidate=v, incumbent=incumbent,
                   fraction=self.fraction, min_games=self.min_games)
        return v

    def assign(self) -> int | None:
        """Arm a NEW session: the candidate's staged version for a
        ``fraction`` slice (fractional accumulator — exact share,
        no rng), None (= incumbent / current pointer) otherwise."""
        with self._lock:
            if self.state != "running":
                return None
            self._acc += self.fraction
            if self._acc >= 1.0:
                self._acc -= 1.0
                self._assigned["candidate"] += 1
                v = self.candidate_version
            else:
                self._assigned["incumbent"] += 1
                v = None
        obs_registry.counter(
            "rollout_canary_assigned_total",
            arm="candidate" if v is not None else "incumbent").inc()
        return v

    def record(self, arm: str, won: bool) -> str:
        """One decided game's outcome for ``arm`` (``"candidate"`` /
        ``"incumbent"``); draws are simply not recorded. Returns the
        controller state after the gate had its chance to decide."""
        if arm not in ("candidate", "incumbent"):
            raise ValueError(f"unknown canary arm {arm!r}")
        decide = None
        with self._lock:
            if self.state != "running":
                return self.state
            (self._wins if won else self._losses)[arm] += 1
            wins = self._wins["candidate"]
            decided = wins + self._losses["candidate"]
            lb = wilson_lower_bound(wins, decided)
            self.wilson_lb = lb
            if decided >= self.min_games:
                decide = "promote" if lb >= 0.5 else "rollback"
        obs_registry.counter("rollout_canary_games_total",
                             arm=arm).inc()
        self._lb_g.set(lb)
        self._emit("record", arm=arm, won=bool(won),
                   wilson_lb=round(lb, 4), decided=decided)
        if decide == "promote":
            self.promote()
        elif decide == "rollback":
            self.rollback()
        return self.state

    def promote(self) -> None:
        """Full rollout: the candidate becomes current everywhere."""
        with self._lock:
            if self.state != "running":
                return
            v = self.candidate_version
            self.state = "promoted"
            self.promotions += 1
            lb = self.wilson_lb
        self.pool.promote_version(v)
        self._pr_c.inc()
        self._emit("promote", candidate=v,
                   wilson_lb=None if lb is None else round(lb, 4))

    def rollback(self, reason: str = "wilson_lb") -> None:
        """Instant rollback: retire the staged version; canary-armed
        sessions fall back to the incumbent on their next genmove."""
        with self._lock:
            if self.state != "running":
                return
            v = self.candidate_version
            self.state = "rolled_back"
            self.rollbacks += 1
            lb = self.wilson_lb
        self.pool.discard_version(v)
        self._rb_c.inc()
        self._emit("rollback", candidate=v, reason=reason,
                   wilson_lb=None if lb is None else round(lb, 4))

    # --------------------------------------------------------- stats

    def stats(self) -> dict:
        """The probes' ``canary`` block (schema: docs/ROLLOUT.md —
        the ``rollout-probe-drift`` lint rule diffs this literal
        against the documented schema both ways)."""
        with self._lock:
            return {
                "state": self.state,
                "fraction": self.fraction,
                "min_games": self.min_games,
                "candidate_version": self.candidate_version,
                "incumbent_version": self.incumbent_version,
                "assigned": {
                    "candidate": self._assigned["candidate"],
                    "incumbent": self._assigned["incumbent"],
                },
                "games": {
                    "candidate_wins": self._wins["candidate"],
                    "candidate_losses": self._losses["candidate"],
                    "incumbent_wins": self._wins["incumbent"],
                    "incumbent_losses": self._losses["incumbent"],
                },
                "wilson_lb": (None if self.wilson_lb is None
                              else round(self.wilson_lb, 4)),
                "promotions": self.promotions,
                "rollbacks": self.rollbacks,
            }
