"""Hot-swap: promoted params under live sessions, no restart.

The serving side of the rollout path (docs/ROLLOUT.md). Params are
ARGUMENTS to the compiled serve programs at fixed shapes, so
installing a new pair is a pointer flip on the pool's
:class:`~rocalphago_tpu.serve.evaluator.BatchingEvaluator` —
``jax_compiles_total`` stays flat, live games keep playing, and
every in-flight genmove finishes on the version it pinned.

Two feeds drive the :class:`HotSwapper`:

* :class:`PublisherWatcher` — in-process: blocks on
  :meth:`~rocalphago_tpu.training.actor.ParamsPublisher.wait_version`
  and applies each newly published snapshot (training and serving in
  one process, e.g. a self-improving bot).
* :class:`SpillWatcher` — cross-process: polls the gate's
  ``rollout.json`` spill pointer (written atomically by
  ``ZeroGate.promote`` / ``ParamsPublisher(spill_dir=...)``), loads
  the checkpoint pair it names, and applies it. A restarted serving
  process picks up the latest gated version the same way.

Both watchers are daemon threads with a bounded ``stop``; the poll
cadence is ``ROCALPHAGO_ROLLOUT_POLL_S`` (default 0.5 s).
"""

from __future__ import annotations

import os
import threading
import time

from rocalphago_tpu.obs import registry as obs_registry

#: watcher poll cadence in seconds (env override)
POLL_ENV = "ROCALPHAGO_ROLLOUT_POLL_S"


def default_poll_s() -> float:
    raw = os.environ.get(POLL_ENV, "")
    return float(raw) if raw else 0.5


def load_spill_params(spill_dir: str, spill: dict, policy_template,
                      value_template) -> tuple:
    """Deserialize the checkpoint pair a spill pointer names into
    host pytrees shaped like the given templates (the serving nets'
    own params — same architecture by construction)."""
    from flax import serialization

    out = []
    for key, template in (("policy", policy_template),
                          ("value", value_template)):
        path = os.path.join(spill_dir, str(spill[key]))
        with open(path, "rb") as f:
            out.append(serialization.from_bytes(template, f.read()))
    return tuple(out)


class HotSwapper:
    """Applies a params pair to one or more swap targets — anything
    with a ``set_params(params_p, params_v)`` surface
    (:class:`~rocalphago_tpu.serve.sessions.ServePool`,
    :class:`~rocalphago_tpu.multisize.pool.MultiSizePool`, or a bare
    :class:`~rocalphago_tpu.serve.evaluator.BatchingEvaluator`).

    ``version`` is the ROLLOUT version (the gate iteration /
    publisher version) — the targets' evaluators allocate their own
    monotonic params versions internally; :attr:`version` is what
    fleet convergence checks compare."""

    def __init__(self, *targets, metrics=None):
        if not targets:
            raise ValueError("HotSwapper needs at least one target")
        self.targets = tuple(targets)
        self.metrics = metrics
        self.version = -1      # latest applied ROLLOUT version
        self.swaps = 0
        self._swap_c = obs_registry.counter("rollout_swaps_total")
        self._ver_g = obs_registry.gauge("rollout_params_version")
        self._swap_h = obs_registry.histogram("rollout_swap_seconds")

    def apply(self, params_p, params_v, version: int) -> None:
        """Swap every target to the pair (pointer flips — bounded by
        host work, no device compile)."""
        t0 = time.monotonic()
        for target in self.targets:
            target.set_params(params_p, params_v)
        dt = time.monotonic() - t0
        self.version = int(version)
        self.swaps += 1
        self._swap_c.inc()
        self._ver_g.set(self.version)
        self._swap_h.observe(dt)
        if self.metrics is not None:
            self.metrics.log("rollout", phase="swap",
                             version=self.version,
                             targets=len(self.targets),
                             elapsed_s=round(dt, 6))


class _WatcherThread:
    """Shared daemon-thread skeleton for the two watchers."""

    def __init__(self, name: str, poll_s: float | None):
        self.poll_s = default_poll_s() if poll_s is None \
            else float(poll_s)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, name=name,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)

    def _loop(self) -> None:  # pragma: no cover — trivial dispatch
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self.poll_s)

    def poll_once(self) -> bool:
        raise NotImplementedError


class PublisherWatcher(_WatcherThread):
    """In-process feed: apply each newly published snapshot."""

    def __init__(self, publisher, swapper: HotSwapper,
                 poll_s: float | None = None):
        super().__init__("rollout-publisher-watch", poll_s)
        self.publisher = publisher
        self.swapper = swapper

    def poll_once(self) -> bool:
        got = self.publisher.wait_version(self.swapper.version + 1,
                                          timeout=self.poll_s)
        if got is None:
            return False
        version, pp, pv = got
        self.swapper.apply(pp, pv, version)
        return True

    def _loop(self) -> None:
        # wait_version already blocks up to poll_s — no extra sleep
        while not self._stop.is_set():
            self.poll_once()


class SpillWatcher(_WatcherThread):
    """Cross-process feed: follow the gate's spill pointer.

    ``policy_template`` / ``value_template`` are the serving nets'
    param pytrees (deserialization shape). A pointer naming files
    that are mid-replace or already pruned is skipped and retried
    next poll — the atomic pointer-last write ordering means that
    window only exists for PRUNED history, never the latest pair."""

    def __init__(self, spill_dir: str, swapper: HotSwapper,
                 policy_template, value_template,
                 poll_s: float | None = None, metrics=None):
        super().__init__("rollout-spill-watch", poll_s)
        self.spill_dir = spill_dir
        self.swapper = swapper
        self.policy_template = policy_template
        self.value_template = value_template
        self.metrics = metrics

    def poll_once(self) -> bool:
        """One poll: apply the spill-pointed version when it is newer
        than what the swapper already serves. Returns True when a
        swap happened."""
        from rocalphago_tpu.training.actor import read_spill

        spill = read_spill(self.spill_dir)
        if spill is None:
            return False
        version = int(spill["version"])
        if version <= self.swapper.version:
            return False
        try:
            pp, pv = load_spill_params(
                self.spill_dir, spill, self.policy_template,
                self.value_template)
        except (OSError, ValueError) as e:
            # torn window (pruned file, partial copy): skip, retry
            if self.metrics is not None:
                self.metrics.log("rollout", phase="spill_skip",
                                 version=version, error=str(e))
            return False
        self.swapper.apply(pp, pv, version)
        return True
