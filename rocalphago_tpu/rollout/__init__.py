"""Live model rollout: hot-swap serving, Wilson-gated canary, and
the federated gateway router (docs/ROLLOUT.md).

The subsystem that closes the loop from ``ZeroGate.promote`` to a
player's next move, in three layers:

* :mod:`~rocalphago_tpu.rollout.hotswap` — swap a promoted param
  pytree under live sessions as a versioned pointer flip (no
  recompile, no dropped games), fed in-process by a
  :class:`~rocalphago_tpu.training.actor.ParamsPublisher` or
  cross-process by the gate's spill file;
* :mod:`~rocalphago_tpu.rollout.canary` — route a slice of sessions
  to a candidate version and gate full rollout on the Wilson 95%
  lower bound, with instant rollback to the incumbent;
* :mod:`~rocalphago_tpu.rollout.router` — federate N gateway
  replicas behind one front door: sticky routing, spillover on
  ``overload``, drain-aware failover, health probing, and
  convergence checks for a fleet-wide promotion.
"""

from rocalphago_tpu.rollout.canary import CanaryController
from rocalphago_tpu.rollout.hotswap import (
    HotSwapper,
    PublisherWatcher,
    SpillWatcher,
)
from rocalphago_tpu.rollout.router import Replica, RolloutRouter

__all__ = [
    "CanaryController",
    "HotSwapper",
    "PublisherWatcher",
    "Replica",
    "RolloutRouter",
    "SpillWatcher",
]
