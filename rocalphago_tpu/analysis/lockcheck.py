"""Runtime lock-order harness: the dynamic half of the concurrency
model.

The static family (:mod:`.rules.concurrency`) proves the DECLARED
lock model — guarded attributes, a cycle-free acquisition graph —
without running anything. This module checks the OBSERVED behavior
against the same model: instrumented ``Lock``/``RLock``/``Condition``
wrappers record per-thread held-sets, build the observed lock-order
graph edge by edge, and raise :class:`LockOrderInversion` the moment
an acquisition closes a cycle (the deadlock that would otherwise
need the right interleaving to fire). A ``Condition.wait`` while the
thread still holds ANOTHER checked lock raises
:class:`BlockingUnderLock` — the runtime form of the
``blocking-call-under-lock`` rule.

Enabled by ``ROCALPHAGO_LOCKCHECK=1`` (off = the factories return
plain ``threading`` primitives; zero overhead). The serve stack,
``MetricsLogger``, and the trace/native module locks construct
through :func:`make_lock`/:func:`make_rlock`/:func:`make_condition`,
each passing a SITE label equal to its static lock identity
(``BatchingEvaluator._cond``, ``ServePool._lock``, ``trace._lock``
…), so :func:`observed_edges` and the static graph from
:func:`rocalphago_tpu.analysis.rules.concurrency.build_lock_graph`
speak the same names. The reconciliation test
(``tests/test_lockcheck.py``) runs the PR-8 serve soak under the
harness and asserts every observed edge exists in the static graph —
an observed edge the model lacks means the model (or the resolver)
is wrong, not the code.

Two metrics land in the existing obs registry per site:
``lock_wait_seconds{site=}`` (acquire wait when the lock was
contended) and ``lock_contention_total{site=}`` (count of contended
acquires). The registry's own internals stay UN-instrumented — the
sink of these metrics cannot be self-instrumented without recursing
(the same reason the inventory family's ``PRODUCER_EXCLUDE`` lists
the registry module).

Stdlib-only, like the rest of :mod:`rocalphago_tpu.analysis`.
"""

from __future__ import annotations

import os
import threading
import time

LOCKCHECK_ENV = "ROCALPHAGO_LOCKCHECK"


def enabled() -> bool:
    return os.environ.get(LOCKCHECK_ENV, "") not in ("", "0")


class LockOrderInversion(RuntimeError):
    """An acquisition closed a cycle in the observed lock-order
    graph: some other code path takes these locks in the opposite
    order, so the right interleaving deadlocks."""


class BlockingUnderLock(RuntimeError):
    """A blocking wait ran while the thread held another checked
    lock — every thread needing that lock stalls behind the wait."""


# ------------------------------------------------------- observed graph

_state_lock = threading.Lock()
_edges: dict = {}          # (from_site, to_site) -> count  # guarded-by: _state_lock
_tls = threading.local()


def _held_stack() -> list:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def held_sites() -> tuple:
    """The calling thread's currently held checked-lock sites, in
    acquisition order (RLock reentries collapsed)."""
    out = []
    for site in _held_stack():
        if site not in out:
            out.append(site)
    return tuple(out)


def observed_edges() -> set:
    """Every (held_site, acquired_site) pair observed so far — the
    runtime acquisition graph the reconciliation test diffs against
    the static one."""
    with _state_lock:
        return set(_edges)


def reset() -> None:
    """Drop the observed graph (tests)."""
    with _state_lock:
        _edges.clear()


def _reaches(src: str, dst: str, edges) -> list | None:
    """DFS path src → dst over ``edges`` keys; returns the path as a
    list of sites or None. Called under ``_state_lock``."""
    adj: dict = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    stack = [(src, [src])]
    seen = set()
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        if node in seen:
            continue
        seen.add(node)
        for nxt in adj.get(node, ()):
            stack.append((nxt, path + [nxt]))
    return None


def _note_acquired(site: str) -> None:
    stack = _held_stack()
    cycle = None
    if site not in stack:
        new = [(h, site) for h in dict.fromkeys(stack)]
        if new:
            with _state_lock:
                for edge in new:
                    fresh = edge not in _edges
                    _edges[edge] = _edges.get(edge, 0) + 1
                    if fresh and cycle is None:
                        back = _reaches(site, edge[0], _edges)
                        if back is not None:
                            cycle = back + [site]
    stack.append(site)
    if cycle is not None:
        raise LockOrderInversion(
            f"acquiring '{site}' while holding {held_sites()[:-1]} "
            f"closes the cycle {' -> '.join(cycle)}")


def _note_released(site: str) -> None:
    stack = _held_stack()
    # release the innermost matching entry (RLock reentry pops one)
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] == site:
            del stack[i]
            return


def _observe(site: str, wait_s: float, contended: bool) -> None:
    # lazy import: obs.registry constructs ITS locks plainly, so this
    # emission never touches a checked lock (no self-instrumentation)
    from rocalphago_tpu.obs import registry as obs_registry
    if contended:
        obs_registry.counter("lock_contention_total", site=site).inc()
    obs_registry.histogram("lock_wait_seconds", site=site).observe(
        wait_s)


# ------------------------------------------------------------ wrappers


class CheckedLock:
    """``threading.Lock``/``RLock`` wrapper with held-set, order and
    contention accounting. Site = the lock's static identity."""

    def __init__(self, site: str, inner=None):
        self.site = site
        self._inner = inner if inner is not None else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        contended = False
        wait = 0.0
        ok = self._inner.acquire(blocking=False)
        if not ok:
            contended = True
            if not blocking:
                self._observe_failed()
                return False
            t0 = time.monotonic()
            ok = self._inner.acquire(True, timeout)
            wait = time.monotonic() - t0
        if ok:
            try:
                _note_acquired(self.site)
            except LockOrderInversion:
                # unwind: the caller never sees the lock as held
                self._inner.release()
                _note_released(self.site)
                raise
            _observe(self.site, wait, contended)
        return ok

    def _observe_failed(self) -> None:
        _observe(self.site, 0.0, True)

    def release(self) -> None:
        self._inner.release()
        _note_released(self.site)

    def locked(self):
        return self._inner.locked()

    def __enter__(self) -> "CheckedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class CheckedRLock(CheckedLock):
    """Reentrant variant: the held stack counts reentries, so a
    nested acquire of the SAME site adds no edge and release pops
    one level."""

    def __init__(self, site: str):
        super().__init__(site, threading.RLock())


class CheckedCondition:
    """``threading.Condition`` wrapper over a :class:`CheckedLock`.
    ``wait`` re-books the held-set around the release/reacquire the
    condition performs, and FLAGS a wait made while the thread holds
    any OTHER checked lock (:class:`BlockingUnderLock`)."""

    def __init__(self, site: str):
        self.site = site
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)

    # -- lock surface -------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        contended = False
        wait = 0.0
        ok = self._lock.acquire(blocking=False)
        if not ok:
            contended = True
            if not blocking:
                _observe(self.site, 0.0, True)
                return False
            t0 = time.monotonic()
            ok = self._lock.acquire(True, timeout)
            wait = time.monotonic() - t0
        if ok:
            try:
                _note_acquired(self.site)
            except LockOrderInversion:
                self._lock.release()
                _note_released(self.site)
                raise
            _observe(self.site, wait, contended)
        return ok

    def release(self) -> None:
        self._lock.release()
        _note_released(self.site)

    def __enter__(self) -> "CheckedCondition":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # -- condition surface --------------------------------------------
    def wait(self, timeout: float | None = None):
        others = [s for s in held_sites() if s != self.site]
        if others:
            raise BlockingUnderLock(
                f"Condition '{self.site}' .wait() while holding "
                f"{others} — the wait releases only its OWN lock; "
                "the others stay held for the full wait")
        _note_released(self.site)       # wait releases the lock...
        try:
            return self._cond.wait(timeout)
        finally:
            _note_acquired(self.site)   # ...and reacquires before return

    def wait_for(self, predicate, timeout: float | None = None):
        t0 = time.monotonic()
        while not predicate():
            left = None if timeout is None else \
                timeout - (time.monotonic() - t0)
            if left is not None and left <= 0:
                return predicate()
            self.wait(left)
        return True

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()


# ------------------------------------------------------------ factories


def make_lock(site: str):
    """A ``threading.Lock`` — checked (site-labelled) when
    ``ROCALPHAGO_LOCKCHECK=1``, plain otherwise. Site must be the
    lock's static identity (``Class.attr`` / ``module._name``)."""
    return CheckedLock(site) if enabled() else threading.Lock()


def make_rlock(site: str):
    return CheckedRLock(site) if enabled() else threading.RLock()


def make_condition(site: str):
    return CheckedCondition(site) if enabled() else \
        threading.Condition()
