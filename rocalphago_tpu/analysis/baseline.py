"""jaxlint baseline: grandfathered findings, committed to the repo.

The baseline is how the linter lands on an existing codebase without
a flag day: every finding triaged as *intentional* is recorded here
(with a one-line justification) and stops failing the build; any NEW
finding still fails. Entries match by fingerprint — ``rule + path +
stripped source line`` — so pure line-number drift (code added above)
does not invalidate them, while editing the offending line itself
resurfaces the finding for re-triage. Matching is count-aware: two
identical offending lines in one file need two entries.

File shape (``.jaxlint-baseline.json``, sorted, one entry per line
for reviewable diffs):

    {"version": 1,
     "findings": [{"rule": ..., "path": ..., "snippet": ...,
                   "note": "why this is intentional"}, ...]}

Workflow: ``scripts/lint.py --update-baseline`` rewrites the file
from the current findings, preserving notes of entries that still
match; hand-edit the ``note`` fields after. A baseline entry whose
finding no longer exists is dropped on update (and reported as stale
by ``--check`` output so the file cannot silently rot).
"""

from __future__ import annotations

import collections
import json
import os

from rocalphago_tpu.analysis.core import Finding

VERSION = 1


class Baseline:
    """Multiset of grandfathered fingerprints + their notes."""

    def __init__(self, entries: list[dict] | None = None):
        self.entries = entries or []
        self._counts = collections.Counter(
            self._fp(e) for e in self.entries)

    @staticmethod
    def _fp(entry: dict) -> str:
        return (f"{entry.get('rule', '')}::{entry.get('path', '')}::"
                f"{entry.get('snippet', '')}")

    def partition(self, findings: list[Finding]):
        """-> (new, grandfathered, stale_entries). Count-aware: each
        baseline entry absorbs at most one finding."""
        budget = collections.Counter(self._counts)
        new, old = [], []
        for f in findings:
            fp = f.fingerprint()
            if budget.get(fp, 0) > 0:
                budget[fp] -= 1
                old.append(f)
            else:
                new.append(f)
        stale = []
        for e in self.entries:
            fp = self._fp(e)
            if budget.get(fp, 0) > 0:
                budget[fp] -= 1
                stale.append(e)
        return new, old, stale

    def note_for(self, f: Finding) -> str:
        fp = f.fingerprint()
        for e in self.entries:
            if self._fp(e) == fp:
                return e.get("note", "")
        return ""


def load_baseline(path: str) -> Baseline:
    if not os.path.exists(path):
        return Baseline()
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return Baseline(list(data.get("findings", [])))


def write_baseline(path: str, findings: list[Finding],
                   previous: Baseline | None = None) -> dict:
    """Serialize ``findings`` as the new baseline, carrying notes
    forward from ``previous`` where fingerprints still match."""
    entries = []
    for f in sorted(findings):
        note = previous.note_for(f) if previous is not None else ""
        entries.append({"rule": f.rule, "path": f.path,
                        "snippet": f.snippet,
                        "message": f.message, "note": note})
    payload = {"version": VERSION, "findings": entries}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=False)
        f.write("\n")
    os.replace(tmp, path)
    return payload
