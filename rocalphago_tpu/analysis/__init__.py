"""jaxlint — JAX-aware static analysis for this codebase.

The stack's correctness conventions are mostly *invisible to Python*:
buffer donation (a donated array must never be read again — PR 4's
`donates_buffers` discipline), jit-boundary purity (no host syncs or
Python control flow on tracers inside compiled bodies), PRNG key
hygiene (never consume the same key twice), retrace discipline
(static arguments must be hashable and low-cardinality), and the
documented observability/resilience inventories (every metric, span,
fault barrier and ``ROCALPHAGO_*`` env knob is contract, not
incidental string). Each of these has cost a debugging cycle when
violated; none is caught by the type system or the test suite until
the bad path actually runs.

This package proves them *before* code runs: an AST-based rule
framework (:mod:`.core`), five rule families (:mod:`.rules`), a
committed baseline for grandfathered findings (:mod:`.baseline`),
per-line suppression comments, and text/JSON reporters
(:mod:`.reporters`). ``scripts/lint.py`` is the CLI; the self-lint
test in ``tests/test_jaxlint.py`` keeps the shipped tree clean in
tier-1. See docs/STATIC_ANALYSIS.md for the rule catalog and the
suppression/baseline workflow.

Stdlib-only by design (``ast`` + ``re`` + ``json``): the linter must
run anywhere the repo checks out, including hosts without jax.
"""

from rocalphago_tpu.analysis.core import (  # noqa: F401
    Finding, LintContext, ModuleInfo, all_rule_ids, lint_source,
    module_rule, project_rule, run_lint,
)
from rocalphago_tpu.analysis.config import LintConfig, load_config  # noqa: F401
from rocalphago_tpu.analysis.baseline import (  # noqa: F401
    Baseline, load_baseline, write_baseline,
)
