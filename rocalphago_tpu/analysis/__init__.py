"""jaxlint — JAX-aware static analysis for this codebase.

The stack's correctness conventions are mostly *invisible to Python*:
buffer donation (a donated array must never be read again — PR 4's
`donates_buffers` discipline), jit-boundary purity (no host syncs or
Python control flow on tracers inside compiled bodies), PRNG key
hygiene (never consume the same key twice), retrace discipline
(static arguments must be hashable and low-cardinality), the
documented observability/resilience inventories (every metric, span,
fault barrier, serve-probe field and ``ROCALPHAGO_*`` env knob is
contract, not incidental string), and the threaded serve stack's
lock discipline (``# guarded-by:`` annotations, a cycle-free
lock-acquisition graph — docs/CONCURRENCY.md). Each of these has
cost a debugging cycle when violated; none is caught by the type
system or the test suite until the bad path actually runs.

This package proves them *before* code runs: an AST-based rule
framework (:mod:`.core`), six rule families (:mod:`.rules`), a
committed baseline for grandfathered findings (:mod:`.baseline`),
per-line suppression comments, and text/JSON reporters
(:mod:`.reporters`). ``scripts/lint.py`` is the CLI; the self-lint
test in ``tests/test_jaxlint.py`` keeps the shipped tree clean in
tier-1. The concurrency model is also checked at RUNTIME by
:mod:`.lockcheck` (``ROCALPHAGO_LOCKCHECK=1`` instrumented locks,
observed-vs-static graph reconciliation in the serve soak). See
docs/STATIC_ANALYSIS.md for the rule catalog and the
suppression/baseline workflow.

Stdlib-only by design (``ast`` + ``re`` + ``json``): the linter must
run anywhere the repo checks out, including hosts without jax.
"""

from rocalphago_tpu.analysis.core import (  # noqa: F401
    Finding, LintContext, ModuleInfo, all_rule_ids, lint_source,
    module_rule, project_rule, run_lint,
)
from rocalphago_tpu.analysis.config import LintConfig, load_config  # noqa: F401
from rocalphago_tpu.analysis.baseline import (  # noqa: F401
    Baseline, load_baseline, write_baseline,
)
