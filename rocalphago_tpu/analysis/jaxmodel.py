"""Shared AST model: how THIS codebase expresses jit and donation.

The rule families all need the same three facts about a module:

* which function defs are jit-compiled (decorator forms
  ``@jax.jit`` / ``@functools.partial(jax.jit, ...)``, call forms
  ``jax.jit(fn, ...)`` / ``functools.partial(jax.jit, ...)(fn)``,
  and ``jaxobs.track("entry", fn)`` wrappers);
* each jitted def's *static* parameters (``static_argnames`` /
  ``static_argnums`` resolved against the def's signature);
* which callables DONATE input buffers, and at which positions —
  ``donate_argnums`` on any jit form, plus the repo convention
  ``fn.donates_buffers = True`` (see runtime/retries.py).

Everything here is a heuristic over names ("a call whose dotted path
ends in ``jit``"), which is the right trade for a project lint: the
codebase controls its own idiom, and the baseline absorbs the rare
mismatch.
"""

from __future__ import annotations

import ast
import dataclasses

JIT_NAMES = ("jax.jit", "jit", "pjit", "jax.pjit")
PARTIAL_NAMES = ("functools.partial", "partial")
TRACK_SUFFIX = ("track",)


def dotted(node) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_segment(name: str | None) -> str | None:
    return name.rsplit(".", 1)[-1] if name else None


def _const_str_tuple(node) -> tuple:
    """Literal ``("a", "b")`` / ``"a"`` -> names; () otherwise."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


def _const_int_tuple(node) -> tuple:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, int))
    return ()


@dataclasses.dataclass(frozen=True)
class JitSpec:
    """Options attached to one jit wrapper expression."""
    static_names: tuple = ()
    static_nums: tuple = ()
    donate_nums: tuple = ()

    @property
    def donates(self) -> bool:
        return bool(self.donate_nums)


def jit_wrapper_spec(call: ast.Call) -> JitSpec | None:
    """``call`` IS a jit wrapper constructor?  Handles ``jax.jit(...)``
    and ``functools.partial(jax.jit, ...)``; returns its spec."""
    name = dotted(call.func)
    if name in JIT_NAMES:
        pass
    elif name in PARTIAL_NAMES and call.args \
            and dotted(call.args[0]) in JIT_NAMES:
        pass
    else:
        return None
    kw = {k.arg: k.value for k in call.keywords if k.arg}
    return JitSpec(
        static_names=_const_str_tuple(kw.get("static_argnames")),
        static_nums=_const_int_tuple(kw.get("static_argnums")),
        donate_nums=_const_int_tuple(kw.get("donate_argnums")))


def positional_params(fndef) -> tuple:
    a = fndef.args
    return tuple(p.arg for p in (*a.posonlyargs, *a.args))


def all_params(fndef) -> tuple:
    a = fndef.args
    return tuple(p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs))


def static_param_names(fndef, spec: JitSpec) -> frozenset:
    pos = positional_params(fndef)
    nums = {pos[i] for i in spec.static_nums if 0 <= i < len(pos)}
    return frozenset(set(spec.static_names) | nums)


@dataclasses.dataclass
class DonatingCallable:
    """A callable known (or declared by convention) to donate."""
    name: str                 # simple name (last attribute segment)
    donate_nums: tuple | None  # None = convention-only, positions unknown
    params: tuple = ()        # underlying def's positional params, if known
    line: int = 0
    module: str = ""
    #: declared via `X.donates_buffers = True` — the repo's explicit
    #: cross-module contract (runtime/retries.py). Only these entries
    #: propagate beyond their own module; jit-inferred donation stays
    #: module-local (bare names like `iteration` collide otherwise).
    convention: bool = False


class ModuleJaxIndex:
    """Per-module index of jitted defs and donating callables.
    Build once via :func:`index_module` (cached on the module)."""

    def __init__(self):
        # id(fndef) -> (fndef, JitSpec)
        self.jitted: dict[int, tuple] = {}
        # simple callable name -> DonatingCallable
        self.donating: dict[str, DonatingCallable] = {}
        # def name -> fndef (module/class/nested, last def wins)
        self.defs: dict[str, ast.AST] = {}

    def jit_spec_for_def(self, fndef) -> JitSpec | None:
        hit = self.jitted.get(id(fndef))
        return hit[1] if hit else None

    def _mark_jitted(self, fndef, spec: JitSpec) -> None:
        prev = self.jitted.get(id(fndef))
        if prev:  # merge: decorator + call-site info
            p = prev[1]
            spec = JitSpec(
                static_names=tuple(set(p.static_names)
                                   | set(spec.static_names)),
                static_nums=tuple(set(p.static_nums)
                                  | set(spec.static_nums)),
                donate_nums=tuple(set(p.donate_nums)
                                  | set(spec.donate_nums)))
        self.jitted[id(fndef)] = (fndef, spec)
        if spec.donates:
            self._mark_donating(fndef.name, spec.donate_nums,
                                positional_params(fndef), fndef.lineno)

    def _mark_donating(self, name, nums, params, line,
                       convention: bool = False) -> None:
        prev = self.donating.get(name)
        if prev and prev.donate_nums and not nums:
            prev.convention = prev.convention or convention
            return  # keep the position-bearing entry
        self.donating[name] = DonatingCallable(
            name=name, donate_nums=tuple(nums) if nums else
            (prev.donate_nums if prev else None),
            params=params or (prev.params if prev else ()),
            line=line,
            convention=convention or (prev.convention if prev
                                      else False))


def index_module(mod) -> ModuleJaxIndex:
    """Build (or return the cached) :class:`ModuleJaxIndex` for a
    ``core.ModuleInfo``."""
    cached = getattr(mod, "_jax_index", None)
    if cached is not None:
        return cached
    idx = ModuleJaxIndex()
    tree = mod.tree

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            idx.defs[node.name] = node

    for node in ast.walk(tree):
        # decorator forms
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                spec = (jit_wrapper_spec(dec)
                        if isinstance(dec, ast.Call) else None)
                if spec is None and dotted(dec) in JIT_NAMES:
                    spec = JitSpec()
                if spec is not None:
                    idx._mark_jitted(node, spec)
        # call forms
        if isinstance(node, ast.Call):
            spec = jit_wrapper_spec(node)
            if spec is not None and node.args:
                # jax.jit(fn, ...) — fn may be a def in this module
                target = dotted(node.args[0])
                fndef = idx.defs.get(last_segment(target) or "")
                if fndef is not None and target not in JIT_NAMES:
                    idx._mark_jitted(fndef, spec)
            # functools.partial(jax.jit, ...)(fn)
            if isinstance(node.func, ast.Call):
                inner = jit_wrapper_spec(node.func)
                if inner is not None and node.args:
                    fndef = idx.defs.get(
                        last_segment(dotted(node.args[0])) or "")
                    if fndef is not None:
                        idx._mark_jitted(fndef, inner)
            # jaxobs.track("entry", fn)
            if last_segment(dotted(node.func)) in TRACK_SUFFIX \
                    and len(node.args) >= 2:
                fndef = idx.defs.get(
                    last_segment(dotted(node.args[1])) or "")
                if fndef is not None:
                    idx._mark_jitted(fndef, JitSpec())

    # assignment forms: name = <jit wrapper>(fn) / X.donates_buffers = True
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        # X.donates_buffers = True  (the runtime/retries.py convention)
        for tgt in node.targets:
            if isinstance(tgt, ast.Attribute) \
                    and tgt.attr == "donates_buffers" \
                    and isinstance(node.value, ast.Constant) \
                    and node.value.value is True:
                owner = last_segment(dotted(tgt.value))
                if owner:
                    fndef = idx.defs.get(owner)
                    spec = idx.jit_spec_for_def(fndef) \
                        if fndef is not None else None
                    idx._mark_donating(
                        owner,
                        spec.donate_nums if spec else None,
                        positional_params(fndef) if fndef is not None
                        else (), node.lineno, convention=True)
        # name = jax.jit(fn, donate_argnums=...) and partial forms;
        # jaxobs.track("entry", jax.jit(...)) wrappers delegate
        # attributes, so unwrap them to the inner jit expression
        value = node.value
        if isinstance(value, ast.Call) \
                and last_segment(dotted(value.func)) in TRACK_SUFFIX \
                and len(value.args) >= 2 \
                and isinstance(value.args[1], ast.Call):
            value = value.args[1]
        if isinstance(value, ast.Call):
            spec = jit_wrapper_spec(value)
            applied = None
            if spec is not None and value.args:
                applied = spec  # jax.jit(fn, ...) called with the fn
            elif isinstance(value.func, ast.Call):
                applied = jit_wrapper_spec(value.func)
            if applied is not None and applied.donates:
                for tgt in node.targets:
                    name = last_segment(dotted(tgt))
                    if name:
                        fndef = idx.defs.get(
                            last_segment(dotted(value.args[0]))
                            or "") if value.args else None
                        idx._mark_donating(
                            name, applied.donate_nums,
                            positional_params(fndef)
                            if fndef is not None else (), node.lineno)

    mod._jax_index = idx
    return idx


def project_donating_index(ctx) -> dict:
    """Union of the CONVENTION-marked donating callables across
    modules, keyed by simple name — chunk programs are donated where
    they are BUILT but called where they are USED (tests, other
    packages). Only ``donates_buffers = True`` declarations cross
    module boundaries: that flag is the repo's explicit contract,
    while jit-inferred donation stays module-local (bare names like
    ``iteration`` exist in several trainers with different specs)."""
    cached = ctx.cache.get("donating")
    if cached is not None:
        return cached
    merged: dict[str, DonatingCallable] = {}
    for mod in ctx.modules:
        idx = index_module(mod)
        for name, d in idx.donating.items():
            if not d.convention:
                continue
            prev = merged.get(name)
            if prev is None or (prev.donate_nums is None
                                and d.donate_nums is not None):
                d.module = mod.rel
                merged[name] = d
    ctx.cache["donating"] = merged
    return merged


def donating_for_module(mod, ctx) -> dict:
    """The donation registry a module's call sites resolve against:
    cross-module convention entries, overridden by the module's own
    index, with non-donating LOCAL defs shadowing colliding names."""
    idx = index_module(mod)
    donating = dict(project_donating_index(ctx))
    donating.update(idx.donating)
    for name in list(donating):
        if name in idx.defs and name not in idx.donating:
            del donating[name]
    return donating
