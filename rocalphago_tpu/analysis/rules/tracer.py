"""Tracer / host-sync hazards inside jit-compiled bodies.

Inside a jit-compiled function every non-static argument is a
tracer: ``float(x)`` / ``int(x)`` / ``bool(x)`` / ``x.item()`` raise
``TracerConversionError`` (or, on concrete paths like the fused
encode/self-play loops, silently force a device sync that serializes
the pipeline), ``np.*`` calls drop the value out of the traced
graph, and Python ``if``/``while`` on a tracer-derived value raises
``TracerBoolConversionError``. All of these are *runtime* failures
today — and only on the branch that actually traces. This rule finds
them at lint time.

Taint model (forward, evaluation order, per jitted body):

* non-static parameters start tainted; ``static_argnames`` /
  ``static_argnums`` parameters start clean (branching on a static
  arg is exactly what static args are for);
* assignment propagates taint through expressions; re-binding a name
  to a clean value clears it;
* trace-time-static projections sanitize: ``.shape`` / ``.ndim`` /
  ``.dtype`` / ``.size``, ``len(...)``, and ``x is None`` tests are
  concrete during tracing, so ``if x.ndim == 2:`` is clean;
* nested defs inside a jitted body (scan/while/cond bodies) are
  analyzed with ALL parameters tainted — that is what ``lax``
  passes them.

Rules: ``host-sync-in-jit`` (conversions, ``.item()``/``.tolist()``,
``np.*`` on tainted values) and ``python-branch-on-tracer``
(``if``/``while``/``assert``/ternary on a tainted test).
"""

from __future__ import annotations

import ast

from rocalphago_tpu.analysis.core import module_rule
from rocalphago_tpu.analysis.jaxmodel import (
    all_params, dotted, index_module, static_param_names,
)

#: attribute projections that are concrete at trace time
SANITIZERS = ("shape", "ndim", "dtype", "size", "aval", "sharding")
#: host conversions that force a sync / fail on tracers
CONVERSIONS = ("float", "int", "bool", "complex")
SYNC_METHODS = ("item", "tolist", "block_until_ready", "__array__")
NUMPY_PREFIXES = ("np.", "numpy.", "onp.")


class _Taint:
    """Forward taint walk over one jitted body."""

    def __init__(self, mod, fndef, tainted: set, findings: list):
        self.mod = mod
        self.findings = findings
        self.tainted = set(tainted)
        self.body = fndef.body

    # -- expression taint --------------------------------------------
    def is_tainted(self, node) -> bool:
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in SANITIZERS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name == "len":
                return False  # len() of a traced array is static
            if name and (name in CONVERSIONS
                         or name.startswith(NUMPY_PREFIXES)):
                return False  # result is a host value (flagged below)
            parts = [node.func] + list(node.args) \
                + [k.value for k in node.keywords]
            return any(self.is_tainted(p) for p in parts)
        if isinstance(node, ast.Compare):
            ops = node.ops
            if all(isinstance(o, (ast.Is, ast.IsNot)) for o in ops):
                return False  # `x is None` is a trace-time fact
            return self.is_tainted(node.left) or any(
                self.is_tainted(c) for c in node.comparators)
        if isinstance(node, (ast.Lambda,)):
            return False
        return any(self.is_tainted(c)
                   for c in ast.iter_child_nodes(node))

    # -- statement walk ----------------------------------------------
    def assign(self, target, value_tainted: bool) -> None:
        if isinstance(target, ast.Name):
            (self.tainted.add if value_tainted
             else self.tainted.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self.assign(e, value_tainted)
        elif isinstance(target, ast.Starred):
            self.assign(target.value, value_tainted)

    def check_expr(self, node) -> None:
        """Flag host syncs anywhere inside ``node``."""
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            name = dotted(sub.func)
            args = list(sub.args) + [k.value for k in sub.keywords]
            if name in CONVERSIONS and any(
                    self.is_tainted(a) for a in args):
                self.findings.append(self.mod.finding(
                    "host-sync-in-jit", sub,
                    f"{name}() on a traced value inside a jit body — "
                    "fails under jit (TracerConversionError) or "
                    "forces a host sync; keep it in jnp, or make the "
                    "argument static"))
            elif isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in SYNC_METHODS \
                    and self.is_tainted(sub.func.value):
                self.findings.append(self.mod.finding(
                    "host-sync-in-jit", sub,
                    f".{sub.func.attr}() on a traced value inside a "
                    "jit body — host sync / trace failure"))
            elif name and name.startswith(NUMPY_PREFIXES) and any(
                    self.is_tainted(a) for a in args):
                self.findings.append(self.mod.finding(
                    "host-sync-in-jit", sub,
                    f"{name}(...) on a traced value inside a jit "
                    "body — numpy drops the value out of the traced "
                    "graph (use jnp)"))

    def check_test(self, node, kw: str) -> None:
        if self.is_tainted(node):
            self.findings.append(self.mod.finding(
                "python-branch-on-tracer", node,
                f"Python `{kw}` on a tracer-derived value inside a "
                "jit body — raises TracerBoolConversionError at "
                "trace time; use lax.cond/select/jnp.where"))

    def walk(self, body) -> None:
        for st in body:
            self.stmt(st)

    def stmt(self, st) -> None:
        if isinstance(st, ast.Assign):
            self.check_expr(st.value)
            t = self.is_tainted(st.value)
            for tgt in st.targets:
                self.assign(tgt, t)
        elif isinstance(st, ast.AugAssign):
            self.check_expr(st.value)
            if isinstance(st.target, ast.Name) \
                    and self.is_tainted(st.value):
                self.tainted.add(st.target.id)
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            self.check_expr(st.value)
            self.assign(st.target, self.is_tainted(st.value))
        elif isinstance(st, ast.If):
            self.check_expr(st.test)
            self.check_test(st.test, "if")
            self.walk(st.body)
            self.walk(st.orelse)
        elif isinstance(st, ast.While):
            self.check_expr(st.test)
            self.check_test(st.test, "while")
            self.walk(st.body)
            self.walk(st.orelse)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self.check_expr(st.iter)
            if self.is_tainted(st.iter):
                self.findings.append(self.mod.finding(
                    "python-branch-on-tracer", st,
                    "Python `for` over a traced value inside a jit "
                    "body — iteration count must be trace-time "
                    "static; use lax.scan/fori_loop"))
            self.assign(st.target, self.is_tainted(st.iter))
            self.walk(st.body)
            self.walk(st.orelse)
        elif isinstance(st, ast.Assert):
            self.check_test(st.test, "assert")
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # scan/while/cond body: every parameter is a tracer
            inner = _Taint(self.mod, st,
                           set(self.tainted) | set(all_params(st)),
                           self.findings)
            inner.walk(st.body)
        elif isinstance(st, (ast.Return, ast.Expr, ast.Raise)):
            for child in ast.iter_child_nodes(st):
                self.check_expr(child)
                if isinstance(child, ast.IfExp):
                    self.check_test(child.test, "ternary")
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self.check_expr(item.context_expr)
            self.walk(st.body)
        elif isinstance(st, ast.Try):
            self.walk(st.body)
            for h in st.handlers:
                self.walk(h.body)
            self.walk(st.orelse)
            self.walk(st.finalbody)


@module_rule(
    "host-sync-in-jit",
    "float()/int()/.item()/np.* on traced values inside jit bodies")
def host_sync_in_jit(mod, ctx):
    findings: list = []
    idx = index_module(mod)
    for fndef, spec in idx.jitted.values():
        static = static_param_names(fndef, spec)
        params = [p for p in all_params(fndef)
                  if p not in ("self", "cls")]
        tainted = {p for p in params if p not in static}
        _Taint(mod, fndef, tainted, findings).walk(fndef.body)
    # one walk produces both rule ids; split here
    return [f for f in findings if f.rule == "host-sync-in-jit"]


@module_rule(
    "python-branch-on-tracer",
    "Python if/while/assert on tracer-derived values in jit bodies")
def python_branch_on_tracer(mod, ctx):
    findings: list = []
    idx = index_module(mod)
    for fndef, spec in idx.jitted.values():
        static = static_param_names(fndef, spec)
        params = [p for p in all_params(fndef)
                  if p not in ("self", "cls")]
        tainted = {p for p in params if p not in static}
        _Taint(mod, fndef, tainted, findings).walk(fndef.body)
    return [f for f in findings if f.rule == "python-branch-on-tracer"]
