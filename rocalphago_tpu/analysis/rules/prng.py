"""PRNG key discipline.

A JAX PRNG key is single-use: every ``jax.random.*`` sampler (and
``split`` itself) consumes the key value it is given, and two calls
with the same key return *correlated* (identical-stream) results —
the classic silent bug that degrades self-play diversity without
failing a single test. The discipline is mechanical: every consume
is preceded by a fresh ``split`` (or derives a per-item key with
``fold_in``), i.e. a key name is consumed at most once between
re-bindings.

``prng-key-reuse`` — the same key name is consumed by two
``jax.random.*`` calls with no intervening re-binding of that name.

``prng-key-reuse-in-loop`` — a key defined outside a loop is
consumed inside the loop body and never re-bound within it: every
iteration draws the same stream. (``fold_in(key, i)`` is the
sanctioned pattern and is exempt.)

Key names are tracked three ways: values returned by
``PRNGKey``/``key``/``split``/``fold_in``, names matching the key
convention (``key``, ``rng``, ``*_key``, ``*_rng``, …) whether bound
as parameters or assigned, and tuple-unpacks of ``split``. A
consume only counts when such a name is passed to a ``*.random.*``
call, so dict-iteration ``key`` variables never false-positive.
"""

from __future__ import annotations

import ast
import re

from rocalphago_tpu.analysis.core import module_rule
from rocalphago_tpu.analysis.events import iter_scopes, scope_events
from rocalphago_tpu.analysis.jaxmodel import dotted

#: jax.random.* entry points that do NOT consume in the reuse sense
NON_CONSUMING = ("PRNGKey", "key", "wrap_key_data", "key_data",
                 "fold_in", "clone", "key_impl")
#: producers whose result is a fresh key (re-binding from these
#: makes the target key-like)
PRODUCERS = ("PRNGKey", "key", "split", "fold_in", "clone")

KEYLIKE_NAME = re.compile(
    r"(^|_)(key|keys|rng|rngs|prng)(_|$)|_key$|_rng$")

_RANDOM_CALL = re.compile(r"(^|\.)random\.([A-Za-z_][A-Za-z0-9_]*)$")


def _random_fn(call: ast.Call) -> str | None:
    """``jax.random.normal`` -> ``normal``; None for non-random
    calls. Accepts any ``*.random.<fn>`` dotted path plus bare
    ``split``/``fold_in``/``PRNGKey`` (from-imports)."""
    name = dotted(call.func)
    if not name:
        return None
    m = _RANDOM_CALL.search(name)
    if m:
        return m.group(2)
    if name in ("split", "fold_in", "PRNGKey"):
        return name
    return None


def _scope_param_keys(scope) -> set:
    if not isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return set()
    a = scope.args
    names = [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]
    return {n for n in names if KEYLIKE_NAME.search(n)}


def _walk_module(mod) -> list:
    findings = []
    for scope in iter_scopes(mod.tree):
        ev = scope_events(scope)
        keylike = _scope_param_keys(scope)
        consumed: dict = {}   # name -> event index of first consume
        loop_consumes: list = []   # (idx, name, call node)
        for i, e in enumerate(ev.events):
            if e.kind == "write":
                producer = bool(
                    e.src and e.src.rsplit(".", 1)[-1] in PRODUCERS)
                if producer or KEYLIKE_NAME.search(e.name or ""):
                    keylike.add(e.name)
                else:
                    keylike.discard(e.name)
                consumed.pop(e.name, None)
            elif e.kind == "call":
                fn = _random_fn(e.call)
                if fn is None or fn in NON_CONSUMING:
                    continue
                key_arg = None
                if e.call.args and isinstance(e.call.args[0], ast.Name) \
                        and e.call.args[0].id in keylike:
                    key_arg = e.call.args[0].id
                for k in e.call.keywords:
                    if k.arg in ("key", "rng", "seed") \
                            and isinstance(k.value, ast.Name) \
                            and k.value.id in keylike:
                        key_arg = k.value.id
                if key_arg is None:
                    continue
                if key_arg in consumed:
                    findings.append(mod.finding(
                        "prng-key-reuse", e.call,
                        f"key '{key_arg}' already consumed by a "
                        "jax.random call (line "
                        f"{ev.events[consumed[key_arg]].node.lineno})"
                        " — split it (or fold_in a counter) before "
                        "reusing; reuse silently draws the SAME "
                        "stream"))
                else:
                    consumed[key_arg] = i
                loop_consumes.append((i, key_arg, e.call))
        # loop reuse: consumed inside a loop, never re-bound in it
        flagged = set()
        for i, name, call in loop_consumes:
            loop = ev.enclosing_loop(i)
            if loop is None or (name, loop) in flagged:
                continue
            writes_in_loop = any(
                t.kind == "write" and t.name == name
                for t in ev.events[loop[0]:loop[1]])
            if not writes_in_loop:
                flagged.add((name, loop))
                findings.append(mod.finding(
                    "prng-key-reuse-in-loop", call,
                    f"key '{name}' consumed inside a loop without "
                    "re-binding — every iteration draws the same "
                    "stream; split per iteration or fold_in the "
                    "loop index"))
    return findings


def _cached_walk(mod) -> list:
    cached = getattr(mod, "_prng_findings", None)
    if cached is None:
        cached = mod._prng_findings = _walk_module(mod)
    return cached


@module_rule(
    "prng-key-reuse",
    "the same PRNG key consumed twice without a split/re-bind")
def prng_key_reuse(mod, ctx):
    return [f for f in _cached_walk(mod) if f.rule == "prng-key-reuse"]


@module_rule(
    "prng-key-reuse-in-loop",
    "a key consumed in a loop body without per-iteration splitting")
def prng_key_reuse_in_loop(mod, ctx):
    return [f for f in _cached_walk(mod)
            if f.rule == "prng-key-reuse-in-loop"]
