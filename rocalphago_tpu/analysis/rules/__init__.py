"""jaxlint rule families. Importing this package registers every
rule with :mod:`rocalphago_tpu.analysis.core`; the catalog lives in
docs/STATIC_ANALYSIS.md."""

from rocalphago_tpu.analysis.rules import (  # noqa: F401
    concurrency, donation, inventory, prng, retrace, tracer,
)
