"""Retrace hazards: arguments that make XLA recompile (or refuse).

``jax.jit`` keys its executable cache on the *hash* of every static
argument. Two consequences this rule family proves statically:

``float-static-arg`` — a float literal flowing into a
``static_argnames``/``static_argnums`` position. Floats hash fine
but have effectively unbounded cardinality (komi sweeps, time
budgets, learning-rate schedules…), so every distinct value is a
full recompile — the "recompile storm" the compile tracker
(docs/OBSERVABILITY.md) exists to catch at runtime. Pass floats as
traced arguments; keep static for genuinely low-cardinality ints/
strings/bools.

``unhashable-static-arg`` — a list/dict/set literal (or
``list()``/``dict()``/``set()`` call) at a static position:
``TypeError: unhashable type`` at call time, on the branch that
traces. Use a tuple.

``mutable-global-in-jit`` — a jitted body reads a module-level
list/dict/set that is mutated somewhere in the module. jit captures
the value AT TRACE TIME; later mutations are silently ignored (no
retrace), which is a correctness bug wearing a performance-bug
costume. Hoist to an argument or freeze to a tuple.
"""

from __future__ import annotations

import ast

from rocalphago_tpu.analysis.core import module_rule
from rocalphago_tpu.analysis.jaxmodel import (
    dotted, index_module, jit_wrapper_spec, last_segment,
    positional_params, static_param_names,
)

MUTATORS = ("append", "extend", "insert", "add", "update", "pop",
            "popitem", "remove", "discard", "clear", "setdefault",
            "sort", "reverse")


def _is_float_literal(node) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, float)
    if isinstance(node, ast.UnaryOp) and isinstance(node.operand,
                                                    ast.Constant):
        return isinstance(node.operand.value, float)
    if isinstance(node, ast.Call) and dotted(node.func) == "float":
        return True
    return False


def _is_unhashable_literal(node) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.SetComp, ast.DictComp)):
        return True
    if isinstance(node, ast.Call) \
            and dotted(node.func) in ("list", "dict", "set"):
        return True
    return False


def _static_args_of_call(call: ast.Call, fndef, spec):
    """(param_or_index, arg_node) pairs at static positions."""
    out = []
    params = positional_params(fndef) if fndef is not None else ()
    static_names = set(spec.static_names)
    if fndef is not None:
        static_names = set(static_param_names(fndef, spec))
    nums = set(spec.static_nums)
    for i, a in enumerate(call.args):
        pname = params[i] if i < len(params) else None
        if i in nums or (pname and pname in static_names):
            out.append((pname or i, a))
    for k in call.keywords:
        if k.arg and k.arg in static_names:
            out.append((k.arg, k.value))
    return out


def _check_static_args(mod, call, fndef, spec, findings) -> None:
    for where, arg in _static_args_of_call(call, fndef, spec):
        if _is_float_literal(arg):
            findings.append(mod.finding(
                "float-static-arg", arg,
                f"float value at static position {where!r} — every "
                "distinct value recompiles; pass it traced, or make "
                "it a low-cardinality int/str"))
        elif _is_unhashable_literal(arg):
            findings.append(mod.finding(
                "unhashable-static-arg", arg,
                f"unhashable list/dict/set at static position "
                f"{where!r} — TypeError at trace time; use a tuple"))


def _walk_module(mod) -> list:
    findings: list = []
    idx = index_module(mod)
    # name -> (fndef, spec) for jitted defs with static positions
    by_name = {}
    for fndef, spec in idx.jitted.values():
        if spec.static_names or spec.static_nums:
            by_name[fndef.name] = (fndef, spec)
    # alias form: `g = jax.jit(f, static_argnums=...)` — calls go
    # through `g`, so map the assigned name to the same spec
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        spec = jit_wrapper_spec(node.value)
        fndef = None
        if spec is not None and node.value.args:
            fndef = idx.defs.get(
                last_segment(dotted(node.value.args[0])) or "")
        elif isinstance(node.value.func, ast.Call):
            spec = jit_wrapper_spec(node.value.func)
            if spec is not None and node.value.args:
                fndef = idx.defs.get(
                    last_segment(dotted(node.value.args[0])) or "")
        if spec is None or not (spec.static_names or spec.static_nums):
            continue
        for tgt in node.targets:
            name = last_segment(dotted(tgt))
            if name:
                by_name.setdefault(name, (fndef, spec))
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        # calls to module-known jitted defs
        hit = by_name.get(last_segment(dotted(node.func)) or "")
        if hit is not None:
            _check_static_args(mod, node, hit[0], hit[1], findings)
        # inline `jax.jit(f, static_argnums=...)(args)` /
        # `partial(jax.jit, ...)(f)(args)`
        if isinstance(node.func, ast.Call):
            spec = jit_wrapper_spec(node.func)
            if spec is not None and (spec.static_names
                                     or spec.static_nums):
                inner = node.func.args[0] if node.func.args else None
                fndef = idx.defs.get(
                    last_segment(dotted(inner)) or "") \
                    if inner is not None else None
                _check_static_args(mod, node, fndef, spec, findings)

    # mutable globals read by jitted bodies
    mutable_globals = {}
    for st in mod.tree.body:
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name) \
                and _is_unhashable_literal(st.value):
            mutable_globals[st.targets[0].id] = st
    if mutable_globals:
        mutated = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in MUTATORS \
                    and isinstance(node.func.value, ast.Name):
                mutated.add(node.func.value.id)
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                tgts = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in tgts:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name):
                        mutated.add(t.value.id)
        hot = set(mutable_globals) & mutated
        for fndef, _spec in idx.jitted.values():
            local = set()
            for sub in ast.walk(fndef):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    local.update(p.arg for p in (
                        *sub.args.posonlyargs, *sub.args.args,
                        *sub.args.kwonlyargs))
                if isinstance(sub, ast.Name) \
                        and isinstance(sub.ctx, ast.Store):
                    local.add(sub.id)
            for sub in ast.walk(fndef):
                if isinstance(sub, ast.Name) \
                        and isinstance(sub.ctx, ast.Load) \
                        and sub.id in hot and sub.id not in local:
                    findings.append(mod.finding(
                        "mutable-global-in-jit", sub,
                        f"jitted '{fndef.name}' captures mutable "
                        f"global '{sub.id}' which is mutated in this "
                        "module — jit freezes the trace-time value; "
                        "later mutations are silently ignored"))
    return findings


def _cached(mod) -> list:
    cached = getattr(mod, "_retrace_findings", None)
    if cached is None:
        cached = mod._retrace_findings = _walk_module(mod)
    return cached


@module_rule(
    "float-static-arg",
    "float literal at a static_argnames/argnums position (recompile "
    "per value)")
def float_static_arg(mod, ctx):
    return [f for f in _cached(mod) if f.rule == "float-static-arg"]


@module_rule(
    "unhashable-static-arg",
    "list/dict/set at a static position (TypeError at trace time)")
def unhashable_static_arg(mod, ctx):
    return [f for f in _cached(mod)
            if f.rule == "unhashable-static-arg"]


@module_rule(
    "mutable-global-in-jit",
    "jitted body captures a mutated module-level list/dict/set")
def mutable_global_in_jit(mod, ctx):
    return [f for f in _cached(mod)
            if f.rule == "mutable-global-in-jit"]
