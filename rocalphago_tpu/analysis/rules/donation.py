"""Donation safety (PR 4's discipline, statically enforced).

``donation-reuse`` — a buffer passed at a donated position is dead
the moment the call is dispatched: XLA may already have reused its
memory, so ANY later read computes on garbage. The legal idiom is
the carry pattern (``carry = f(..., carry, ...)``): the store on the
same statement re-binds the name to the *result*, which is a live
buffer. The rule flattens each scope to evaluation-order events and
checks, for every donated ``Name`` argument, that the next touch of
that name is a write — including around the back edge of an
enclosing loop (``f(x)`` alone in a loop donates the same buffer
twice on iteration 2, which XLA rejects at best and corrupts at
worst).

``retry-wraps-donating`` — ``runtime.retries`` refuses donating
callables at runtime (wrap time); this rule proves it at lint time,
including the decorator form and one-shot ``retry_call``. A failed
dispatch may already have invalidated the donated inputs, so the
retry would re-dispatch garbage; wrap the enclosing iteration
instead (see runtime/retries.py module docstring).
"""

from __future__ import annotations

import ast

from rocalphago_tpu.analysis.core import module_rule
from rocalphago_tpu.analysis.events import iter_scopes, scope_events
from rocalphago_tpu.analysis.jaxmodel import (
    donating_for_module, dotted, index_module, jit_wrapper_spec,
    last_segment,
)

RETRY_NAMES = ("retry", "retry_call")


def _donated_arg_names(call: ast.Call, don) -> list:
    """Names passed at donated positions of ``call``; positions may
    map through keywords when the underlying def's params are known."""
    out = []
    if don.donate_nums is None:
        return out
    for i in don.donate_nums:
        if i < len(call.args):
            a = call.args[i]
            if isinstance(a, ast.Name):
                out.append(a.id)
        elif don.params and i < len(don.params):
            pname = don.params[i]
            for k in call.keywords:
                if k.arg == pname and isinstance(k.value, ast.Name):
                    out.append(k.value.id)
    return out


def _resolve_donating(call: ast.Call, donating: dict):
    """The donation info for this call's callee, if any. Matches by
    the callee's last dotted segment (chunk programs are attributes:
    ``search.run_sims_donated``), or an inline jit wrapper with
    ``donate_argnums`` (``jax.jit(f, donate_argnums=(0,))(x)``)."""
    name = last_segment(dotted(call.func))
    if name in donating:
        return donating[name]
    if isinstance(call.func, ast.Call):
        spec = jit_wrapper_spec(call.func)
        if spec is not None and spec.donates:
            from rocalphago_tpu.analysis.jaxmodel import DonatingCallable
            return DonatingCallable(name="<inline jit>",
                                    donate_nums=spec.donate_nums)
    return None


@module_rule(
    "donation-reuse",
    "a buffer passed at a donated position must not be read again")
def donation_reuse(mod, ctx):
    findings = []
    donating = donating_for_module(mod, ctx)
    for scope in iter_scopes(mod.tree):
        ev = scope_events(scope)
        for i, e in enumerate(ev.events):
            if e.kind != "call":
                continue
            don = _resolve_donating(e.call, donating)
            if don is None:
                continue
            for name in _donated_arg_names(e.call, don):
                f = _next_touch_violation(ev, i, name, don)
                if f is not None:
                    node, msg = f
                    findings.append(mod.finding("donation-reuse",
                                                node, msg))
    return findings


def _next_touch_violation(ev, i: int, name: str, don):
    """After the donate at event ``i``, is the next touch of ``name``
    a read?  Checks forward to the enclosing loop end (or scope end),
    then around the loop back edge."""
    loop = ev.enclosing_loop(i)
    end = loop[1] if loop else len(ev.events)
    for j in range(i + 1, end):
        t = ev.events[j]
        if t.name == name:
            if t.kind == "read":
                return (t.node,
                        f"'{name}' read after being DONATED to "
                        f"{don.name} (donate position) — the buffer "
                        "may already be invalid; re-bind it from the "
                        "call result first")
            return None  # write re-binds: cleared
    if loop:
        for j in range(loop[0], i + 1):
            t = ev.events[j]
            if t.name == name:
                if t.kind == "read":
                    return (t.node,
                            f"'{name}' donated to {don.name} inside a "
                            "loop without re-binding — the next "
                            "iteration reads/donates a dead buffer")
                return None
        # donate past loop end with no touch inside: fall through
        for j in range(end, len(ev.events)):
            t = ev.events[j]
            if t.name == name:
                if t.kind == "read":
                    return (t.node,
                            f"'{name}' read after being DONATED to "
                            f"{don.name} — the buffer may already be "
                            "invalid")
                return None
    return None


@module_rule(
    "retry-wraps-donating",
    "retry/retry_call must never wrap a donating callable")
def retry_wraps_donating(mod, ctx):
    findings = []
    known = set(donating_for_module(mod, ctx))

    def is_donating_ref(node) -> bool:
        return last_segment(dotted(node)) in known

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            callee = last_segment(dotted(node.func))
            # retry_call(fn, ...) — one-shot form
            if callee == "retry_call" and node.args \
                    and is_donating_ref(node.args[0]):
                findings.append(mod.finding(
                    "retry-wraps-donating", node,
                    "retry_call on a donating callable "
                    f"({dotted(node.args[0])}) — a failed dispatch "
                    "may already have invalidated the donated "
                    "inputs; retry the enclosing iteration instead"))
            # retry(...)(fn) — decorator-call form
            if isinstance(node.func, ast.Call) \
                    and last_segment(dotted(node.func.func)) == "retry" \
                    and node.args and is_donating_ref(node.args[0]):
                findings.append(mod.finding(
                    "retry-wraps-donating", node,
                    "retry(...) wraps a donating callable "
                    f"({dotted(node.args[0])}) — retry the enclosing "
                    "iteration instead"))
        # @retry(...) decorator on a def that donates
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in known:
            for dec in node.decorator_list:
                base = dec.func if isinstance(dec, ast.Call) else dec
                if last_segment(dotted(base)) == "retry":
                    findings.append(mod.finding(
                        "retry-wraps-donating", dec,
                        f"@retry on donating def '{node.name}' — a "
                        "failed dispatch may already have invalidated "
                        "the donated inputs"))
    return findings
